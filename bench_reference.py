"""Measure the reference pipeline's per-stage CPU costs: the comparison
anchor BASELINE.md:24-29 demands (the reference publishes no numbers).

Reproduces the observable compute path of the reference WITHOUT copying its
code:

- serving hot loop (reference: services/vision_analysis/server.py:116-152):
  JPEG/PNG decode -> resize-256 preprocess -> torch U-Net(3,1) forward ->
  sigmoid/threshold -> nearest-resize mask -> numpy/scipy curvature
  (tests/oracle.py, written from the SURVEY spec of pkg/geometry_utils.py)
  -> PNG mask encode;
- training epoch (reference: scripts/train_segmenter.py:103-210): Adam
  lr=1e-4, batch 4, BCEWithLogitsLoss, 256x256, forward+backward over the
  dataset.

The torch U-Net here is written fresh from the architecture spec
(SURVEY.md section 2.1: DoubleConv = (3x3 conv no-bias -> BN -> ReLU) x 2,
4x down/up, bilinear decoder with halved mid-channels, channel ladder
64..1024//2), so parameter count and FLOPs match the deployed reference
model (pkg/segmentation_model.py:86-120, instantiated UNet(3, 1) at
train_segmenter.py:143).

Writes BASELINE_MEASURED.json; bench.py reads it to report vs_baseline
against *measured* reference throughput instead of the design target.
"""

from __future__ import annotations

import json
import platform
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))


def build_torch_unet(base_features: int = 64):
    """Reference-equivalent torch model from the SURVEY spec (bilinear
    variant, the deployed configuration at the default width; smaller
    ``base_features`` keeps the same ladder shape for fast/committable
    parity fixtures)."""
    import torch
    import torch.nn as nn

    class DoubleConv(nn.Module):
        def __init__(self, cin, cout, mid=None):
            super().__init__()
            mid = mid or cout
            self.block = nn.Sequential(
                nn.Conv2d(cin, mid, 3, padding=1, bias=False),
                nn.BatchNorm2d(mid), nn.ReLU(inplace=True),
                nn.Conv2d(mid, cout, 3, padding=1, bias=False),
                nn.BatchNorm2d(cout), nn.ReLU(inplace=True),
            )

        def forward(self, x):
            return self.block(x)

    class Down(nn.Module):
        def __init__(self, cin, cout):
            super().__init__()
            self.block = nn.Sequential(nn.MaxPool2d(2), DoubleConv(cin, cout))

        def forward(self, x):
            return self.block(x)

    class Up(nn.Module):
        def __init__(self, cin, cout):
            super().__init__()
            self.up = nn.Upsample(scale_factor=2, mode="bilinear",
                                  align_corners=True)
            self.conv = DoubleConv(cin, cout, mid=cin // 2)

        def forward(self, x, skip):
            x = self.up(x)
            dy = skip.size(2) - x.size(2)
            dx = skip.size(3) - x.size(3)
            x = nn.functional.pad(
                x, [dx // 2, dx - dx // 2, dy // 2, dy - dy // 2]
            )
            return self.conv(torch.cat([skip, x], dim=1))

    class UNet(nn.Module):
        def __init__(self, n_channels=3, n_classes=1):
            super().__init__()
            f = base_features
            self.inc = DoubleConv(n_channels, f)
            self.down1 = Down(f, f * 2)
            self.down2 = Down(f * 2, f * 4)
            self.down3 = Down(f * 4, f * 8)
            self.down4 = Down(f * 8, f * 16 // 2)
            self.up1 = Up(f * 16, f * 8 // 2)
            self.up2 = Up(f * 8, f * 4 // 2)
            self.up3 = Up(f * 4, f * 2 // 2)
            self.up4 = Up(f * 2, f)
            self.outc = nn.Conv2d(f, n_classes, 1)

        def forward(self, x):
            x1 = self.inc(x)
            x2 = self.down1(x1)
            x3 = self.down2(x2)
            x4 = self.down3(x3)
            x5 = self.down4(x4)
            y = self.up1(x5, x4)
            y = self.up2(y, x3)
            y = self.up3(y, x2)
            y = self.up4(y, x1)
            return self.outc(y)

    return UNet()


def synthetic_frame(h=480, w=640, seed=0):
    from robotic_discovery_platform_tpu.io.frames import SyntheticSource

    src = SyntheticSource(width=w, height=h, seed=seed, n_frames=1)
    src.start()
    color, depth = src.get_frames()
    src.stop()
    return color, depth


def bench_serving(n_frames: int = 20) -> dict:
    """Per-stage times for the reference hot loop.

    Honesty note: an *untrained* net's sigmoid>0.5 mask covers most of the
    frame, which drives the FITPACK smoothing fit into a pathological
    many-thousand-edge-point regime (~9 s/frame) that a deployed, trained
    segmenter never sees. The geometry stage is therefore timed on a
    representative actuator-band mask (tests/oracle.make_arc_scene: an
    ~80 px curved band, the workload the reference was built for) while
    decode/forward/encode are timed on the same frames as before.
    """
    import cv2
    import torch

    from oracle import make_arc_scene, oracle_curvature

    model = build_torch_unet().eval()
    color, depth = synthetic_frame()
    ok1, jpg = cv2.imencode(".jpg", color)
    ok2, png = cv2.imencode(".png", depth)
    assert ok1 and ok2
    h, w = color.shape[:2]
    arc_mask, arc_depth, arc_intr, arc_scale, _ = make_arc_scene(h, w)

    stages = {"decode": [], "forward": [], "geometry": [], "encode": []}
    for i in range(n_frames):
        t0 = time.perf_counter()
        c = cv2.imdecode(jpg, cv2.IMREAD_COLOR)
        cv2.imdecode(png, cv2.IMREAD_UNCHANGED)
        t1 = time.perf_counter()
        x = cv2.resize(c[..., ::-1], (256, 256),
                       interpolation=cv2.INTER_AREA).astype(np.float32) / 255.0
        xt = torch.from_numpy(x.transpose(2, 0, 1))[None]
        with torch.no_grad():
            logits = model(xt)
        mask = (torch.sigmoid(logits)[0, 0] > 0.5).numpy().astype(np.uint8)
        mask = cv2.resize(mask, (w, h), interpolation=cv2.INTER_NEAREST)
        t2 = time.perf_counter()
        res = oracle_curvature(arc_mask, arc_depth, arc_intr, arc_scale)
        assert res[0] > 0, "geometry anchor degenerated to the empty result"
        t3 = time.perf_counter()
        cv2.imencode(".png", arc_mask * 255)
        t4 = time.perf_counter()
        if i >= 2:  # skip warmup iterations
            stages["decode"].append(t1 - t0)
            stages["forward"].append(t2 - t1)
            stages["geometry"].append(t3 - t2)
            stages["encode"].append(t4 - t3)

    out = {k: round(float(np.median(v)) * 1e3, 3) for k, v in stages.items()}
    total = sum(out.values())
    out["total_ms"] = round(total, 3)
    out["fps"] = round(1000.0 / total, 3)
    return out


def bench_training(n_images: int = 64, epochs: int = 2) -> dict:
    import torch

    from robotic_discovery_platform_tpu.training import synthetic

    imgs, masks = synthetic.generate_arrays(n_images, 256, 256, seed=0)
    x = torch.from_numpy(
        (imgs.astype(np.float32) / 255.0).transpose(0, 3, 1, 2)
    )
    y = torch.from_numpy(
        (masks.astype(np.float32) / 255.0).transpose(0, 3, 1, 2)
    )
    model = build_torch_unet().train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)
    loss_fn = torch.nn.BCEWithLogitsLoss()
    times = []
    for _ in range(epochs):
        t0 = time.perf_counter()
        for i in range(0, n_images, 4):
            opt.zero_grad()
            loss = loss_fn(model(x[i:i + 4]), y[i:i + 4])
            loss.backward()
            opt.step()
        times.append(time.perf_counter() - t0)
    epoch_s = min(times)
    n_params = sum(p.numel() for p in model.parameters())
    return {
        "epoch_s": round(epoch_s, 3),
        "images_per_s": round(n_images / epoch_s, 3),
        "n_images": n_images,
        "batch_size": 4,
        "img_size": 256,
        "torch_params": int(n_params),
    }


def main() -> None:
    import torch

    result = {
        "host": platform.processor() or platform.machine(),
        "python": platform.python_version(),
        "torch": torch.__version__,
        "torch_threads": torch.get_num_threads(),
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "serving_cpu_per_stage": bench_serving(),
        "geometry_workload_note": (
            "the geometry stage is timed on a representative arc-band mask "
            "(an untrained net's near-full-frame mask drives FITPACK into a "
            "~9 s/frame pathological regime no trained deployment sees), "
            "while decode/forward/encode use the raw synthetic frames; the "
            "framework bench (bench.py) times geometry on its own "
            "model-produced masks, so the geometry stages of the two "
            "benches see similar but not byte-identical workloads"
        ),
        "training_cpu": bench_training(),
    }
    out = REPO / "BASELINE_MEASURED.json"
    out.write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
