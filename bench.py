"""Headline benchmark: fused segmentation + curvature throughput at 640x480
on one chip, against the 30 FPS north-star target (BASELINE.json; the
reference publishes no numbers -- BASELINE.md).

Methodology note: on this image the TPU is reached through a loopback relay
with ~110 ms host<->device round-trip latency and a `block_until_ready` that
returns immediately, so naive per-call timing measures the tunnel, not the
chip. We therefore time K data-dependent fused iterations chained inside one
compiled `lax.scan` (each frame is a function of the previous mask, so no
iteration can be elided or overlapped) plus exactly one host fetch, and
subtract the independently measured fetch round-trip. That is the
steady-state streaming throughput of the chip itself.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "frames/sec", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

TARGET_FPS = 30.0  # BASELINE.json north star for serving on v5e-1
CHAIN = 200


def _roundtrip_ms() -> float:
    """Median host->device->host latency for a trivial fetch."""

    @jax.jit
    def trivial(x):
        return x + 1.0

    x = jnp.ones((8,))
    float(trivial(x)[0])
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        float(trivial(x)[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def main() -> None:
    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet
    from robotic_discovery_platform_tpu.ops import geometry, pipeline
    from robotic_discovery_platform_tpu.utils.config import (
        GeometryConfig,
        ModelConfig,
    )

    model = build_unet(ModelConfig())
    variables = init_unet(model, jax.random.key(0))
    geom_cfg = GeometryConfig()

    h, w = 480, 640
    rng = np.random.default_rng(0)
    frame = rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
    frame[h // 3: 2 * h // 3] = (200, 60, 60)
    depth = jnp.asarray(np.full((h, w), 500, np.uint16))
    intrinsics = jnp.asarray(
        [[600.0, 0, w / 2], [0, 600.0, h / 2], [0, 0, 1]], jnp.float32
    )
    scale = jnp.float32(0.001)

    def fused_step(f):
        x = pipeline.preprocess(f[None], 256)
        logits = model.apply(variables, x, train=False)
        m = pipeline.logits_to_native_masks(logits, h, w)[0]
        prof = geometry.compute_curvature_profile(
            m, depth, intrinsics, scale, geom_cfg
        )
        # Data dependency on BOTH the mask and the curvature result so no
        # stage can be dead-code-eliminated across iterations.
        dep = (m & jnp.uint8(1)) ^ (prof.mean_curvature > 1e30).astype(jnp.uint8)
        return f ^ dep[..., None]

    @jax.jit
    def chained(f0):
        final, _ = lax.scan(lambda c, _: (fused_step(c), None), f0, None,
                            length=CHAIN)
        return final

    f0 = jnp.asarray(frame)
    t0 = time.perf_counter()
    np.asarray(chained(f0))
    compile_s = time.perf_counter() - t0
    rt_ms = _roundtrip_ms()
    print(
        f"# backend={jax.default_backend()} compile={compile_s:.1f}s "
        f"roundtrip={rt_ms:.1f}ms chain={CHAIN}",
        file=sys.stderr,
    )

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(chained(f0))
        best = min(best, time.perf_counter() - t0)
    per_frame_ms = max((best * 1e3 - rt_ms) / CHAIN, 1e-6)
    fps = 1000.0 / per_frame_ms

    print(json.dumps({
        "metric": "fused_seg_curvature_fps_640x480_1chip",
        "value": round(fps, 2),
        "unit": "frames/sec",
        "vs_baseline": round(fps / TARGET_FPS, 3),
    }))


if __name__ == "__main__":
    main()
