"""Headline benchmark: fused segmentation + curvature throughput at 640x480
on one chip, vs the MEASURED reference CPU pipeline (BASELINE_MEASURED.json,
produced by bench_reference.py) and the 30 FPS design target (BASELINE.json;
the reference itself publishes no numbers -- BASELINE.md).

Methodology note: on this image the TPU is reached through a loopback relay
with ~110 ms host<->device round-trip latency and a `block_until_ready` that
returns immediately, so naive per-call timing measures the tunnel, not the
chip. We therefore time K data-dependent fused iterations chained inside one
compiled `lax.scan` (each frame is a function of the previous mask, so no
iteration can be elided or overlapped) plus exactly one host fetch, and
subtract the independently measured fetch round-trip. That is the
steady-state streaming throughput of the chip itself.

The model forward runs through the Pallas-fused kernels (ops/pallas) on TPU
and plain Flax/XLA elsewhere -- the same auto policy the server uses; both
paths are timed and reported on stderr, with batched (cross-stream
micro-batching) throughput at B=4 and B=8.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "frames/sec", "vs_baseline": N,
   "vs_target": N}
where vs_baseline is vs the measured reference CPU FPS when
BASELINE_MEASURED.json exists (falling back to the 30 FPS target), and
vs_target is always vs the 30 FPS north star.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

TARGET_FPS = 30.0  # BASELINE.json north star for serving on v5e-1
CHAIN = 200

# Wall-clock ceiling for the whole bench. The TPU on this image sits behind
# a tunnel that can wedge mid-run (jax.devices() then blocks forever in C
# land, unreachable by Python exception handling) -- when the deadline
# fires we still emit the one structured JSON line the driver parses.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "2400"))


_HEADLINE_METRIC = "fused_seg_curvature_fps_640x480_1chip"


#: error kinds that mean "the accelerator tunnel was unusable" -- their
#: payloads carry `"skipped": "tunnel"` so the driver (and the autotune
#: pass reading bench artifacts) can tell a skipped window from a real
#: regression or a recorded-0.0 artifact (the BENCH_r04/r05 failure modes)
_TUNNEL_KINDS = ("tpu_unavailable", "bench_deadline_exceeded",
                 "nonfinite_measurement")


def _error_payload(kind: str, detail: str,
                   metric: str = _HEADLINE_METRIC) -> dict:
    payload = {
        "metric": metric,
        "value": 0.0,
        "unit": "frames/sec",
        "vs_baseline": 0.0,
        "vs_target": 0.0,
        "error": kind,
        "detail": detail[-800:],
    }
    if kind in _TUNNEL_KINDS:
        payload["skipped"] = "tunnel"
    return payload


# exactly ONE result line (success or structured error) ever reaches
# stdout: emit and deadline-fire race under one lock, and after the line is
# out the deadline timer only force-exits (a teardown hang on the wedged
# tunnel must still die) without printing a second, contradictory line.
# Plain bool under the lock -- nothing ever *waits* on this state.
_result_printed = False
_EMIT_LOCK = threading.Lock()


def _emit_result(payload: dict) -> None:
    global _result_printed
    with _EMIT_LOCK:
        if _result_printed:
            return
        print(json.dumps(payload), flush=True)
        _result_printed = True


def _arm_deadline(metric: str = _HEADLINE_METRIC) -> None:
    def fire() -> None:
        _emit_result(_error_payload(
            "bench_deadline_exceeded",
            f"no result after {DEADLINE_S:.0f}s "
            "(accelerator tunnel likely wedged mid-run)",
            metric,
        ))
        os._exit(0)

    t = threading.Timer(DEADLINE_S, fire)
    t.daemon = True
    t.start()


def _probe_backend(attempts: int | None = None,
                   timeout_s: float | None = None) -> None:
    """Prove the default backend can initialize AT ALL before this process
    touches it. Backend bring-up on a wedged tunnel does not raise -- it
    hangs indefinitely inside platform discovery (the round-4 BENCH
    artifact) -- so the probe runs in a killable subprocess with a hard
    timeout and bounded retries. Raises RuntimeError on terminal failure.
    BENCH_PROBE_ATTEMPTS / BENCH_PROBE_TIMEOUT_S tune the budget (a
    flapping tunnel rewards fast-failing probes in an outer retry loop;
    the defaults suit the driver's one-shot run)."""
    if attempts is None:
        attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))
    last = ""
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "print(float(jnp.ones(()) + 1), jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode == 0:
                print(f"# backend probe ok: {proc.stdout.strip()}"
                      f" (attempt {attempt + 1})", file=sys.stderr)
                return
            err_lines = proc.stderr.strip().splitlines() if proc.stderr else []
            last = err_lines[-1] if err_lines else f"rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            last = f"backend init hung >{timeout_s:.0f}s (tunnel wedged?)"
        except Exception as exc:  # noqa: BLE001 -- e.g. OSError spawning
            last = f"{type(exc).__name__}: {exc}"
        print(f"# backend probe attempt {attempt + 1}/{attempts} failed: "
              f"{last}", file=sys.stderr)
        if attempt < attempts - 1:
            time.sleep(10)
    raise RuntimeError(f"backend unavailable after {attempts} probes: {last}")


def _roundtrip_ms() -> float:
    """Median host->device->host latency for a trivial fetch."""

    @jax.jit
    def trivial(x):
        return x + 1.0

    x = jnp.ones((8,))
    # First device op in this process = backend bring-up; the tunneled
    # backend intermittently drops the first connection even when healthy,
    # so retry it with the same bounds as the compile path.
    for attempt in range(4):
        try:
            float(trivial(x)[0])
            break
        except Exception:
            if attempt == 3:
                raise
            time.sleep(5)
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        float(trivial(x)[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def _measure_chain(chained, f0, chain: int, rt_ms: float, reps: int = 3):
    """Best-of-reps per-iteration ms for one compiled chain + one fetch.
    The first call (compile) retries: the tunneled compile service on this
    image intermittently drops connections (HTTP 500 / truncated body)."""
    t0 = time.perf_counter()
    for attempt in range(4):
        try:
            np.asarray(chained(f0))
            break
        except Exception:
            if attempt == 3:
                raise
            time.sleep(5)
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(chained(f0))
        best = min(best, time.perf_counter() - t0)
    return max((best * 1e3 - rt_ms) / chain, 1e-6), compile_s


def main() -> None:
    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet
    from robotic_discovery_platform_tpu.ops import geometry, pipeline
    from robotic_discovery_platform_tpu.ops import pallas as pallas_ops
    from robotic_discovery_platform_tpu.utils.config import (
        GeometryConfig,
        ModelConfig,
    )

    model = build_unet(ModelConfig())
    variables = init_unet(model, jax.random.key(0))
    # Headline profile = the SERVING DEFAULT (ServerConfig.geometry_stride=1,
    # reference-exact dense geometry). The stride-2 decimated profile is the
    # documented opt-in fast path (fast_stride2_b1; accuracy quantified in
    # GEOMETRY_PARITY.json).
    geom_cfg = GeometryConfig(stride=1)
    geom_cfg_fast = GeometryConfig(stride=2)
    on_tpu = pallas_ops.use_pallas()
    pnet = pallas_ops.make_pallas_unet(model, variables) if on_tpu else None

    h, w = 480, 640
    rng = np.random.default_rng(0)
    frame = rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
    frame[h // 3: 2 * h // 3] = (200, 60, 60)
    depth = jnp.asarray(np.full((h, w), 500, np.uint16))
    intrinsics = jnp.asarray(
        [[600.0, 0, w / 2], [0, 600.0, h / 2], [0, 0, 1]], jnp.float32
    )
    scale = jnp.float32(0.001)

    def make_fused_step(forward, batch: int, gcfg, impl: str = "dense"):
        depth_b = jnp.broadcast_to(depth, (batch, h, w))
        intr_b = jnp.broadcast_to(intrinsics, (batch, 3, 3))
        scale_b = jnp.broadcast_to(scale, (batch,))

        def per_frame(mm, dd, kk, ss):
            return geometry.compute_curvature_profile(mm, dd, kk, ss, gcfg)

        def one_frame(fi, dd, kk, ss):
            x = pipeline.preprocess(fi[None], 256)
            logits = (forward(x) if forward is not None
                      else model.apply(variables, x, train=False))
            m = pipeline.logits_to_native_masks(logits, h, w)[0]
            prof = per_frame(m, dd, kk, ss)
            dep = (m & jnp.uint8(1)) ^ (
                prof.mean_curvature > 1e30
            ).astype(jnp.uint8)
            return fi ^ dep[..., None]

        def fused_step(f):  # f: [B, H, W, 3] uint8
            if impl == "scan" and batch > 1:
                # scan-over-frames inside ONE dispatch: B=1 VMEM residency,
                # amortized launch (ServerConfig.batch_impl="scan")
                _, out = lax.scan(
                    lambda c, inp: (c, one_frame(*inp)), 0,
                    (f, depth_b, intr_b, scale_b),
                )
                return out
            x = pipeline.preprocess(f, 256)
            logits = (forward(x) if forward is not None
                      else model.apply(variables, x, train=False))
            m = pipeline.logits_to_native_masks(logits, h, w)
            # same batching policy as ops/pipeline._analyze_batch: vmap --
            # the packed-key sort batches as ONE row-batched XLA sort
            if batch == 1:
                prof = jax.tree.map(
                    lambda a: a[None],
                    per_frame(m[0], depth_b[0], intr_b[0], scale_b[0]),
                )
            else:
                prof = jax.vmap(per_frame)(m, depth_b, intr_b, scale_b)
            # Data dependency on BOTH the mask and the curvature result so no
            # stage can be dead-code-eliminated across iterations.
            dep = (m & jnp.uint8(1)) ^ (
                prof.mean_curvature[:, None, None] > 1e30
            ).astype(jnp.uint8)
            return f ^ dep[..., None]

        return fused_step

    def bench(forward, batch: int, rt_ms: float, gcfg=None, impl="dense"):
        step = make_fused_step(forward, batch, gcfg or geom_cfg, impl)

        @jax.jit
        def chained(f0):
            final, _ = lax.scan(lambda c, _: (step(c), None), f0, None,
                                length=CHAIN)
            return final

        f0 = jnp.broadcast_to(jnp.asarray(frame), (batch, h, w, 3))
        per_iter_ms, compile_s = _measure_chain(chained, f0, CHAIN, rt_ms)
        return batch * 1000.0 / per_iter_ms, compile_s

    rt_ms = _roundtrip_ms()
    results = {}
    pallas_fwd = (lambda x: pnet(x)) if pnet is not None else None
    # BENCH_TRACE_DIR=<dir> captures a jax.profiler trace of one fused chain
    # (TensorBoard-viewable) around the flax-forward measurement.
    from robotic_discovery_platform_tpu.utils.profiling import jax_trace

    with jax_trace(os.environ.get("BENCH_TRACE_DIR")):
        fps_flax, compile_s = bench(None, 1, rt_ms)
    results["flax_b1"] = fps_flax
    if pnet is not None:
        results["pallas_b1"], _ = bench(pallas_fwd, 1, rt_ms)
    best_fwd = None
    fps = fps_flax
    if results.get("pallas_b1", 0) > fps_flax:
        best_fwd, fps = pallas_fwd, results["pallas_b1"]
    # the opt-in fast profile: stride-2 decimated geometry
    results["fast_stride2_b1"], _ = bench(best_fwd, 1, rt_ms, geom_cfg_fast)
    # Batched serving throughput (cross-stream micro-batching, B frames per
    # dispatch; the PallasUNet auto policy runs these XLA-uniform -- mixed
    # per-layer dispatch and batched Pallas both measure slower). Context
    # for the numbers: b1 already runs the chip at its measured ceiling, so
    # batching targets dispatch amortization, not per-frame speedup.
    for b in (4, 8):
        results[f"batched_b{b}"], _ = bench(best_fwd, b, rt_ms)
    # scan-over-frames batching (ServerConfig.batch_impl="scan"): one
    # dispatch, B=1 VMEM residency -- the round-4 verdict's candidate fix
    # for dense batching's VMEM-spill anti-scaling
    for b in (4, 8):
        results[f"batched_scan_b{b}"], _ = bench(
            best_fwd, b, rt_ms, impl="scan")

    # MFU: conv-only analytic FLOPs over the v5e bf16 peak (the standard
    # matmul-FLOP MFU basis; utils/flops.py, validated vs XLA cost
    # analysis). Per-frame seconds come from the headline fused rate, so
    # geometry/preprocess time COUNTS AGAINST utilization -- this is
    # end-to-end serving MFU, not an isolated-kernel number.
    from robotic_discovery_platform_tpu.utils import flops as flops_lib

    fwd_flops = flops_lib.unet_forward_flops(256)
    serving_mfu = flops_lib.mfu(fwd_flops, 1.0 / fps)

    print(
        f"# backend={jax.default_backend()} compile={compile_s:.1f}s "
        f"roundtrip={rt_ms:.1f}ms chain={CHAIN} "
        f"mfu={serving_mfu:.3f} "
        + " ".join(f"{k}={v:.1f}fps" for k, v in results.items()),
        file=sys.stderr,
    )

    baseline_fps = None
    measured = Path(__file__).resolve().parent / "BASELINE_MEASURED.json"
    if measured.exists():
        try:
            baseline_fps = json.loads(measured.read_text())[
                "serving_cpu_per_stage"]["fps"]
        except (KeyError, json.JSONDecodeError):
            baseline_fps = None

    if not np.isfinite(fps) or fps <= 0.0:
        # the BENCH_r05 artifact: a wedged tunnel let the run finish with
        # a zero measurement -- record a skipped row, never a 0.0 result
        _emit_result(_error_payload(
            "nonfinite_measurement",
            f"measured {fps!r} frames/sec (tunnel wedged mid-run?)",
        ))
        return

    _emit_result({
        "metric": "fused_seg_curvature_fps_640x480_1chip",
        "backend": jax.default_backend(),
        "value": round(fps, 2),
        "unit": "frames/sec",
        "vs_baseline": round(fps / (baseline_fps or TARGET_FPS), 3),
        "vs_target": round(fps / TARGET_FPS, 3),
        "batched_fps": {k: round(v, 1) for k, v in results.items()},
        "mfu": round(serving_mfu, 4),
        "mfu_basis": {
            "flops_per_frame": fwd_flops,
            "peak_tflops_bf16": flops_lib.V5E_PEAK_BF16_TFLOPS,
            "note": "conv-only analytic FLOPs (utils/flops.py) over the "
                    "end-to-end fused frame time (geometry included)",
        },
        "baseline_src": ("measured_reference_cpu" if baseline_fps
                         else "design_target_30fps"),
    })


def serving_pipeline_main(smoke: bool = False, chips: int = 1,
                          dispatch_mode: str = "round_robin",
                          precision: str = "f32") -> None:
    """serving_pipeline_fps: N synthetic concurrent streams through the
    LIVE BatchDispatcher (serving/batching.py), pipelined
    (max_inflight=2) vs serial (pipeline_depth=1), reporting aggregate
    FPS, the measured overlap seconds (rdp_batch_overlap_seconds source),
    the in-flight high-water mark, and a bitwise per-stream parity check
    between the two modes.

    ``chips > 1`` additionally routes the pipelined run across a
    ``make_serving_mesh(chips)`` device mesh (DeviceRouter, round_robin
    or sharded per ``dispatch_mode``) and reports aggregate + per-chip
    FPS, per-chip dispatch balance, and scaling efficiency vs the 1-chip
    pipelined figure; parity stays bitwise against single-chip serial.

    ``smoke`` is the CPU-runnable variant (tiny model, 64x64 frames) CI
    runs -- with ``--chips N`` it exercises the multi-chip path on faked
    CPU devices (XLA_FLAGS=--xla_force_host_platform_device_count) --
    including under RDP_FAULTS="serving.batch.complete:exc:1", where the
    injected completer fault must error-complete its frames and leave the
    dispatcher serving (errored_frames >= 1, value > 0).

    ``precision`` selects the serving tier (ops/pallas/quant.py: f32 /
    bf16 / int8-weight-quantized). Every tier additionally reports parity
    against an f32 reference analyzer over the parity frame set (mask
    IoU, |delta curvature|) and whether the ServerConfig gate thresholds
    pass; the within-tier pipelined-vs-serial check stays bitwise.
    """
    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet
    from robotic_discovery_platform_tpu.ops import pipeline
    from robotic_discovery_platform_tpu.parallel import mesh as mesh_lib
    from robotic_discovery_platform_tpu.serving.batching import (
        BatchDispatcher,
        DeviceRouter,
    )
    from robotic_discovery_platform_tpu.utils.config import ModelConfig

    if smoke:
        h, w, img_size, base = 64, 64, 64, 8
        streams, frames_per_stream, parity_frames = 4, 6, 4
    else:
        h, w, img_size, base = 480, 640, 256, 64
        streams, frames_per_stream, parity_frames = 8, 24, 8
    if chips > 1:
        # enough concurrent submitters to keep every chip's window fed
        streams = max(streams, 4 * chips)
        frames_per_stream = max(frames_per_stream, 12)
    max_inflight = 2

    mcfg = ModelConfig(base_features=base, compute_dtype="float32")
    model = build_unet(mcfg)
    variables = init_unet(model, jax.random.key(0), img_size=img_size)
    # precision tier: the served engine binds the transformed pair; the
    # pristine f32 pair stays around as the parity reference
    from robotic_discovery_platform_tpu.ops.pallas import quant
    from robotic_discovery_platform_tpu.utils.config import ServerConfig

    served_model, served_vars, qreport = quant.apply_precision(
        model, variables, precision
    )
    if qreport is not None and qreport.get("layers"):
        print(f"# {precision}: quantized {qreport['layers']} conv kernels "
              f"(max rel err {qreport['max_rel_err']:.2%})",
              file=sys.stderr)
    batch_analyze = pipeline.make_batch_analyzer(served_model,
                                                 img_size=img_size)

    def analyze(frames, depths, intr, scales):
        return batch_analyze(served_vars, frames, depths, intr, scales)

    def make_router() -> DeviceRouter:
        """Mesh + per-placement analyzers, mirroring the server's
        _make_engine: weights are bound to each chip (or mesh-replicated)
        once, never re-transferred per dispatch."""
        mesh = mesh_lib.make_serving_mesh(chips)
        if dispatch_mode == "round_robin":
            analyzers = [
                (lambda f, d_, i, s, _v=v: batch_analyze(_v, f, d_, i, s))
                for v in (jax.device_put(served_vars, dev)
                          for dev in mesh_lib.device_ring(mesh))
            ]
        else:
            v_repl = mesh_lib.shard_pytree(mesh, served_vars)
            analyzers = [
                lambda f, d_, i, s: batch_analyze(v_repl, f, d_, i, s)
            ]
        return DeviceRouter(mesh, dispatch_mode, analyzers)

    rng = np.random.default_rng(0)
    depth = np.full((h, w), 500, np.uint16)
    intr = np.asarray(
        [[0.94 * w, 0, w / 2], [0, 0.94 * w, h / 2], [0, 0, 1]], np.float32
    )
    # one fixed frame set, shared by both modes, so the parity check
    # compares the SAME inputs bit for bit
    stream_frames = [
        [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
         for _ in range(frames_per_stream)]
        for _ in range(streams)
    ]
    parity_set = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                  for _ in range(parity_frames)]

    def leaves_identical(a, b) -> bool:
        if a is None or b is None:
            return a is b
        la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
        if len(la) != len(lb):
            return False
        for x, y in zip(la, lb):
            x, y = np.asarray(x), np.asarray(y)
            if x.dtype != y.dtype or x.shape != y.shape:
                return False
            eq_nan = np.issubdtype(x.dtype, np.floating)
            if not np.array_equal(x, y, equal_nan=eq_nan):
                return False
        return True

    def run_mode(inflight: int, router=None) -> dict:
        # sharded routing needs max_batch to cover the mesh width; the
        # round-robin and single-chip runs keep the smoke's b<=2 buckets
        mb = (max(2, router.chips)
              if router is not None and router.mode == "sharded" else 2)
        d = BatchDispatcher(
            analyze, window_ms=2.0, max_batch=mb, max_backlog=1024,
            submit_timeout_s=300.0, max_inflight=inflight, router=router,
        )
        errored = 0
        try:
            # warm-up submit: pays its bucket's compile on the first routed
            # chip and absorbs any injected completer fault (CI's
            # graceful-degradation proof)
            try:
                d.submit(parity_set[0], depth, intr, 0.001)
            except Exception:
                errored += 1
            # warm every reachable bucket on EVERY routed placement off
            # the timed path
            for b in sorted({d.bucket_for(n) for n in range(1, mb + 1)}):
                d.warm(
                    np.stack([parity_set[0]] * b),
                    np.stack([depth] * b),
                    np.stack([intr] * b),
                    np.full((b,), 0.001, np.float32),
                )
            # parity phase: sequential b=1 submits, results kept for the
            # cross-mode bitwise comparison
            parity = []
            for f in parity_set:
                try:
                    parity.append(d.submit(f, depth, intr, 0.001))
                except Exception:
                    errored += 1
                    parity.append(None)
            # throughput phase: concurrent streams
            ok = [0] * streams
            errs = [0] * streams

            def stream(s: int) -> None:
                for f in stream_frames[s]:
                    try:
                        d.submit(f, depth, intr, 0.001)
                        ok[s] += 1
                    except Exception:
                        errs[s] += 1

            threads = [threading.Thread(target=stream, args=(s,))
                       for s in range(streams)]
            overlap0 = d.overlap_s_total
            frames0 = list(d.chip_frames)
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            errored += sum(errs)
            return {
                "fps": sum(ok) / wall if wall > 0 else 0.0,
                "overlap_s": d.overlap_s_total - overlap0,
                "high_water": d.inflight_high_water,
                "errored": errored,
                "parity": parity,
                "wall": wall,
                # throughput-phase frames per chip (parity/warm excluded)
                "chip_frames": [a - b for a, b in
                                zip(d.chip_frames, frames0)],
                "chip_dispatches": list(d.chip_dispatches),
            }
        finally:
            d.stop()

    router = make_router() if chips > 1 else None
    pipelined = run_mode(max_inflight, router)
    one_chip = run_mode(max_inflight) if chips > 1 else None
    serial = run_mode(1)
    identical = all(
        leaves_identical(a, b)
        for a, b in zip(pipelined["parity"], serial["parity"])
    )
    # precision parity vs an f32 reference analyzer over the same parity
    # frames, gated by the ServerConfig warm-up thresholds (at f32 the
    # reference is the served model itself, so the report is the trivial
    # 1.0-IoU / 0-delta anchor)
    ref_batch_analyze = pipeline.make_batch_analyzer(model,
                                                     img_size=img_size)
    scfg = ServerConfig()
    ref_outs, got_outs = [], []
    for f, got in zip(parity_set, pipelined["parity"]):
        if got is None:
            continue
        ref = jax.tree.map(
            lambda a: a[0],
            ref_batch_analyze(
                variables, f[None], depth[None], intr[None],
                np.full((1,), 0.001, np.float32),
            ),
        )
        ref_outs.append(ref)
        got_outs.append(got)
    precision_parity = quant.parity_report(ref_outs, got_outs)
    gates_pass = quant.parity_gates_pass(
        precision_parity, scfg.quant_parity_min_iou,
        scfg.quant_parity_max_curv_err,
    )
    chip_note = ""
    if chips > 1:
        base_fps = one_chip["fps"] or 1e-9
        chip_note = (
            f"chips={chips}({dispatch_mode}) "
            f"1chip={one_chip['fps']:.1f}fps "
            f"scaling={pipelined['fps'] / base_fps:.2f}x "
            f"balance={pipelined['chip_frames']} "
        )
    print(
        f"# backend={jax.default_backend()} "
        f"pipelined={pipelined['fps']:.1f}fps "
        f"(overlap={pipelined['overlap_s']:.3f}s "
        f"high_water={pipelined['high_water']}) "
        f"{chip_note}"
        f"serial={serial['fps']:.1f}fps "
        f"(overlap={serial['overlap_s']:.3f}s) identical={identical} "
        f"precision={precision} "
        f"(iou={precision_parity['mask_iou_mean']:.4f} "
        f"curv_err={precision_parity['curvature_err_max']:.4g} "
        f"gates={'pass' if gates_pass else 'FAIL'})",
        file=sys.stderr,
    )
    payload = {
        "metric": "serving_pipeline_fps",
        "backend": jax.default_backend(),
        "precision": precision,
        "parity": {
            **precision_parity,
            "gates_pass": gates_pass,
            "min_iou_gate": scfg.quant_parity_min_iou,
            "max_curv_err_gate": scfg.quant_parity_max_curv_err,
        },
        "value": round(pipelined["fps"], 2),
        "unit": "frames/sec",
        "serial_fps": round(serial["fps"], 2),
        "speedup_vs_serial": round(
            pipelined["fps"] / serial["fps"], 3) if serial["fps"] else 0.0,
        "overlap_seconds": round(pipelined["overlap_s"], 4),
        "serial_overlap_seconds": round(serial["overlap_s"], 4),
        "inflight_high_water": pipelined["high_water"],
        "max_inflight": max_inflight,
        "identical": identical,
        "errored_frames": pipelined["errored"] + serial["errored"],
        "streams": streams,
        "frames_per_stream": frames_per_stream,
        "smoke": smoke,
    }
    if not np.isfinite(payload["value"]) or payload["value"] <= 0.0:
        _emit_result(_error_payload(
            "nonfinite_measurement",
            f"measured {payload['value']!r} frames/sec "
            "(tunnel wedged mid-run?)",
            "serving_pipeline_fps",
        ))
        return
    if chips > 1:
        wall = pipelined["wall"] or 1e-9
        base_fps = one_chip["fps"]
        payload.update({
            "chips": chips,
            "dispatch_mode": dispatch_mode,
            "fps_1chip_pipelined": round(base_fps, 2),
            "scaling_vs_1chip": (round(pipelined["fps"] / base_fps, 3)
                                 if base_fps else 0.0),
            "scaling_efficiency": (round(
                pipelined["fps"] / base_fps / chips, 3) if base_fps
                else 0.0),
            "per_chip_fps": {
                str(i): round(n / wall, 2)
                for i, n in enumerate(pipelined["chip_frames"])
            },
            "chip_frames": pipelined["chip_frames"],
            "chip_dispatches": pipelined["chip_dispatches"],
        })
    _emit_result(payload)


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--serving-pipeline", action="store_true",
        help="run the serving_pipeline_fps bench (pipelined vs serial "
             "dispatch through the live BatchDispatcher) instead of the "
             "headline fused-graph bench",
    )
    parser.add_argument(
        "--smoke", action="store_true",
        help="CPU-runnable smoke variant of --serving-pipeline",
    )
    parser.add_argument(
        "--chips", type=int, default=1,
        help="route the pipelined serving bench across N mesh chips "
             "(serving/batching.DeviceRouter); with --smoke the devices "
             "are faked CPU devices "
             "(XLA_FLAGS=--xla_force_host_platform_device_count)",
    )
    parser.add_argument(
        "--dispatch-mode", default="round_robin",
        choices=["round_robin", "sharded"],
        help="how --chips routes dispatches: whole buckets round-robined "
             "onto the least-loaded chip, or each bucket sharded over the "
             "mesh 'data' axis",
    )
    parser.add_argument(
        "--precision", default="f32", choices=["f32", "bf16", "int8"],
        help="serving precision tier for --serving-pipeline "
             "(ops/pallas/quant.py): f32 = untransformed (bitwise "
             "identical to today), bf16 = bfloat16 activations, int8 = "
             "bf16 activations + per-channel int8 weight quantization; "
             "non-f32 tiers report parity vs the f32 reference and "
             "whether the ServerConfig gates pass",
    )
    cli = parser.parse_args()
    _metric = ("serving_pipeline_fps" if cli.serving_pipeline
               else _HEADLINE_METRIC)
    _arm_deadline(_metric)
    if cli.serving_pipeline and cli.smoke and cli.chips > 1:
        # the smoke multi-chip path runs on faked CPU devices: pin the
        # platform and force enough virtual devices BEFORE backend init
        # (honors an already-exported XLA_FLAGS count when it is enough)
        from robotic_discovery_platform_tpu.utils.platforms import (
            force_cpu_platform,
        )

        force_cpu_platform(min_devices=max(8, cli.chips))
    try:
        _probe_backend()
    except Exception as e:  # noqa: BLE001 -- any probe failure is terminal
        # Terminal backend failure: one parseable JSON line, clean exit --
        # never a bare traceback (round-4's rc=1 artifact was unparseable).
        _emit_result(_error_payload("tpu_unavailable", str(e), _metric))
        sys.exit(0)
    try:
        if cli.serving_pipeline:
            serving_pipeline_main(smoke=cli.smoke, chips=cli.chips,
                                  dispatch_mode=cli.dispatch_mode,
                                  precision=cli.precision)
        else:
            main()
    except Exception as e:  # noqa: BLE001 -- structured artifact by design
        import traceback

        traceback.print_exc()
        _emit_result(_error_payload(
            "bench_error", f"{type(e).__name__}: {e}", _metric))
        sys.exit(0)
