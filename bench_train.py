"""Training benchmark + mIoU parity run: the second north-star obligation
(BASELINE.json: ">= 5x train wall-clock vs the single-device reference at
equal mIoU"; BASELINE.md:24-29).

Three measurements on a fixed synthetic dataset (same generator, seed, and
hyperparameters as bench_reference.py's training anchor -- Adam 1e-4, batch
4, BCE, 256x256, reference: scripts/train_segmenter.py:45-50,143-145):

1. steady-state TPU train-step throughput (chained lax.scan, one fetch --
   see bench.py for why naive timing lies on this image);
2. an end-to-end `train_model` convergence run recording wall-clock and
   final val mIoU/Dice (the metric the reference never computes, SURVEY.md
   section 2.1 "Trainer");
3. the torch reference-equivalent trained with the same data/config,
   evaluated with the same mIoU -- the parity anchor.

Writes TRAINBENCH.json. Run bench_reference.py first if you also want the
per-stage serving anchor.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

N_IMAGES = 64
IMG = 256
BATCH = 4
EPOCHS = 10
SEED = 0


def dataset():
    from robotic_discovery_platform_tpu.training import synthetic

    imgs, masks = synthetic.generate_arrays(N_IMAGES, IMG, IMG, seed=SEED)
    return (imgs.astype(np.float32) / 255.0,
            masks.astype(np.float32) / 255.0)


def miou_np(prob, target, thresh=0.5, eps=1e-7):
    """Same definition as models/losses.mean_iou, in numpy so the torch and
    jax runs are scored identically."""
    pred = (prob > thresh).astype(np.float64)
    t = (target > thresh).astype(np.float64)
    inter = (pred * t).sum()
    union = pred.sum() + t.sum() - inter
    iou_fg = (inter + eps) / (union + eps)
    pred_b, t_b = 1 - pred, 1 - t
    inter_b = (pred_b * t_b).sum()
    union_b = pred_b.sum() + t_b.sum() - inter_b
    iou_bg = (inter_b + eps) / (union_b + eps)
    return float((iou_fg + iou_bg) / 2)


def dice_np(prob, target, thresh=0.5, eps=1e-7):
    pred = (prob > thresh).astype(np.float64)
    t = (target > thresh).astype(np.float64)
    inter = (pred * t).sum()
    return float((2 * inter + eps) / (pred.sum() + t.sum() + eps))


def bench_tpu_step_throughput() -> dict:
    """Chained-scan steady-state train-step rate at the reference batch size
    and at a TPU-efficient batch size."""
    import jax
    import jax.numpy as jnp
    import optax

    from robotic_discovery_platform_tpu.models import losses
    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet
    from robotic_discovery_platform_tpu.training import trainer
    from robotic_discovery_platform_tpu.utils.config import ModelConfig

    model = build_unet(ModelConfig())
    tx = optax.adam(1e-4)
    state = trainer.create_state(model, tx, jax.random.key(0), IMG)
    step = trainer.core_train_step(model, tx, losses.bce_with_logits)
    xs, ys = dataset()

    out = {}
    for batch in (BATCH, 32):
        x = jnp.asarray(xs[:batch])
        y = jnp.asarray(ys[:batch])

        @jax.jit
        def chained(s0, x, y):
            def body(s, _):
                s2, loss = step(s, x, y)
                return s2, loss
            s_final, lossses = jax.lax.scan(body, s0, None, length=50)
            return jnp.sum(lossses)

        t0 = time.perf_counter()
        float(chained(state, x, y))
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(chained(state, x, y))
            best = min(best, time.perf_counter() - t0)
        step_ms = best * 1e3 / 50
        from robotic_discovery_platform_tpu.utils import flops as flops_lib

        step_flops = flops_lib.unet_train_step_flops(batch, IMG)
        out[f"batch{batch}"] = {
            "step_ms": round(step_ms, 3),
            "steps_per_s": round(1000.0 / step_ms, 2),
            "images_per_s": round(batch * 1000.0 / step_ms, 2),
            "compile_s": round(compile_s, 1),
            # conv-only analytic FLOPs (3x forward for fwd+dx+dw) over the
            # v5e bf16 peak -- utils/flops.py states the basis
            "mfu": round(flops_lib.mfu(step_flops, step_ms / 1e3), 4),
        }
    return out


def bench_tpu_convergence(tmp: Path) -> dict:
    import jax

    from robotic_discovery_platform_tpu.training import trainer
    from robotic_discovery_platform_tpu.utils.config import (
        ModelConfig,
        TrainConfig,
    )

    cfg = TrainConfig(
        epochs=EPOCHS, batch_size=BATCH, img_size=IMG, learning_rate=1e-4,
        seed=SEED, validation_split=0.25,
        tracking_uri=f"file:{tmp}/mlruns", checkpoint_dir=f"{tmp}/ckpt",
    )
    res = trainer.train_model(cfg, ModelConfig(), arrays=dataset(),
                              register=False)
    return {
        "backend": jax.default_backend(),
        "epochs": EPOCHS,
        "wall_clock_s": round(res.wall_clock_s, 2),
        "epoch_s": round(res.wall_clock_s / EPOCHS, 2),
        "val_miou": round(res.final_metrics.get("miou", float("nan")), 4),
        "val_dice": round(res.final_metrics.get("dice", float("nan")), 4),
        "best_val_loss": round(res.best_val_loss, 5),
    }


def bench_torch_convergence() -> dict:
    """Reference-equivalent torch training at the same config, scored with
    the same numpy mIoU (reference: scripts/train_segmenter.py:103-210)."""
    import torch

    from bench_reference import build_torch_unet

    xs, ys = dataset()
    n_val = N_IMAGES // 4
    rng = np.random.default_rng(SEED)
    order = rng.permutation(N_IMAGES)
    tr, va = order[n_val:], order[:n_val]
    x = torch.from_numpy(xs.transpose(0, 3, 1, 2))
    y = torch.from_numpy(ys.transpose(0, 3, 1, 2))
    model = build_torch_unet().train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)
    loss_fn = torch.nn.BCEWithLogitsLoss()
    t0 = time.perf_counter()
    for _ in range(EPOCHS):
        for i in range(0, len(tr), BATCH):
            idx = tr[i:i + BATCH]
            opt.zero_grad()
            loss = loss_fn(model(x[idx]), y[idx])
            loss.backward()
            opt.step()
    wall = time.perf_counter() - t0
    model.eval()
    probs, targs = [], []
    with torch.no_grad():
        for i in range(0, len(va), BATCH):
            idx = va[i:i + BATCH]
            probs.append(torch.sigmoid(model(x[idx])).numpy())
            targs.append(y[idx].numpy())
    prob = np.concatenate(probs)
    targ = np.concatenate(targs)
    return {
        "backend": "torch-cpu",
        "epochs": EPOCHS,
        "wall_clock_s": round(wall, 2),
        "epoch_s": round(wall / EPOCHS, 2),
        "val_miou": round(miou_np(prob, targ), 4),
        "val_dice": round(dice_np(prob, targ), 4),
    }


def main() -> None:
    import tempfile

    only = sys.argv[1] if len(sys.argv) > 1 else "all"
    out_path = REPO / "TRAINBENCH.json"
    result = {}
    if out_path.exists():
        result = json.loads(out_path.read_text())
    result.setdefault("config", {
        "n_images": N_IMAGES, "img_size": IMG, "batch_size": BATCH,
        "epochs": EPOCHS, "optimizer": "adam(1e-4)", "loss": "bce",
        "dataset": f"training.synthetic.generate_arrays(seed={SEED})",
    })
    if only in ("all", "tpu"):
        result["tpu_step_throughput"] = bench_tpu_step_throughput()
        with tempfile.TemporaryDirectory() as tmp:
            result["tpu_convergence"] = bench_tpu_convergence(Path(tmp))
    if only in ("all", "torch"):
        result["torch_reference"] = bench_torch_convergence()
    if "tpu_convergence" in result and "torch_reference" in result:
        result["speedup_wall_clock"] = round(
            result["torch_reference"]["wall_clock_s"]
            / result["tpu_convergence"]["wall_clock_s"], 2,
        )
        result["miou_delta"] = round(
            result["tpu_convergence"]["val_miou"]
            - result["torch_reference"]["val_miou"], 4,
        )
    result["measured_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    out_path.write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
