"""Open-loop tail-latency harness for the live gRPC analysis server.

``bench.py`` measures CLOSED-loop throughput: every stream waits for its
previous frame before sending the next, so the server never sees more
load than it can absorb and queueing delay is invisible. Production
serving is judged the other way around -- requests arrive whether or not
the server is keeping up (InferLine's SLO-driven planning and Clockwork's
predictable-tail argument, PAPERS.md) -- so this harness generates
**open-loop** arrivals (Poisson, or a replayed inter-arrival trace)
against the live server and reports what the tail actually looks like:

- p50 / p95 / p99 / p99.9 latency per offered-load level, measured from
  each request's *scheduled* arrival time (queueing delay counts;
  no coordinated omission);
- SLO violation rate against ``--slo-ms`` (errors and sheds count as
  violations -- a failed frame never met its objective);
- goodput (ok responses/sec) vs offered load.

Results go to ``LOADBENCH.json`` (one row per offered-load level) and the
driver contract from bench.py holds: exactly ONE JSON summary line on
stdout, structured errors instead of tracebacks.

Overload-control comparison (PR 7): ``--controller {off,on,both}`` runs
the same offered-load ladder against a server with the overload control
plane off (FIFO admission, no reactive controller -- the PR 2 behavior)
and/or on (deadline-aware admission + the serving/controller.py reactive
tuner), tagging every LOADBENCH.json row with its leg. Loads may be
given relative to measured capacity (``--loads 0.75x,1.75x``: a short
closed-loop burst measures capacity first), which is how the policy is
validated open-loop at a known overload factor instead of by closed-loop
FPS. ``--deadline-ms`` puts a real per-request gRPC deadline on every
arrival (default 2x the SLO) so deadline-aware shedding has deadlines to
work with; ``--chips N`` boots the smoke server over N faked CPU mesh
chips, which is how CI's quarantine leg drives ``serving.chip.<i>.
dispatch`` faults through failover.

Usage:
    python bench_load.py --smoke                # self-hosted CPU server
    python bench_load.py --server host:50051 --loads 50,100,200
    python bench_load.py --smoke --trace gaps.json   # replay (ms gaps)
    python bench_load.py --smoke --controller both --loads 0.75x,1.75x

``--smoke`` boots an in-process CPU server (tiny model, 64x64 frames,
micro-batching on so the flight recorder and the ``serving.batch.*``
fault sites are exercised) and is what CI's ``load-smoke`` and
``overload-smoke`` jobs run -- including under fault injection, where
injected failures must surface as counted violations, never a crash.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np

#: (percentile, row key) for every reported quantile
PERCENTILES = ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms"),
               (99.9, "p999_ms"))

DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "1200"))

_result_printed = False
_EMIT_LOCK = threading.Lock()


def _emit_result(payload: dict) -> None:
    global _result_printed
    with _EMIT_LOCK:
        if _result_printed:
            return
        print(json.dumps(payload), flush=True)
        _result_printed = True


def _error_payload(kind: str, detail: str) -> dict:
    return {
        "metric": "open_loop_tail_latency",
        "value": 0.0,
        "unit": "ms",
        "error": kind,
        "detail": detail[-800:],
    }


def _arm_deadline() -> None:
    def fire() -> None:
        _emit_result(_error_payload(
            "bench_deadline_exceeded",
            f"no result after {DEADLINE_S:.0f}s",
        ))
        os._exit(0)

    t = threading.Timer(DEADLINE_S, fire)
    t.daemon = True
    t.start()


# -- arrival processes -------------------------------------------------------


def poisson_arrivals(rate_hz: float, duration_s: float,
                     rng: np.random.Generator) -> list[float]:
    """Arrival offsets (seconds from window start) of a Poisson process:
    exponential inter-arrival gaps at ``rate_hz``."""
    out: list[float] = []
    t = float(rng.exponential(1.0 / rate_hz))
    while t < duration_s:
        out.append(t)
        t += float(rng.exponential(1.0 / rate_hz))
    return out


def trace_arrivals(path: str) -> list[float]:
    """Replayed arrivals from a recorded trace: either a bare JSON
    array of inter-arrival gaps in MILLISECONDS (the shape a production
    access log reduces to) or the object form
    ``{"gaps_ms": [...], "models": [...]}`` that
    ``tools/journal_to_trace.py`` writes and the fleet simulator
    (``sim/workload.py``) replays -- one trace file drives both the
    live bench and the sim. The open-loop bench is single-model, so
    per-arrival model labels are ignored here."""
    gaps_ms = json.loads(Path(path).read_text())
    if isinstance(gaps_ms, dict):
        gaps_ms = gaps_ms.get("gaps_ms")
    if not isinstance(gaps_ms, list) or not gaps_ms:
        raise ValueError(f"{path}: expected a non-empty JSON array of "
                         "inter-arrival milliseconds (bare or under "
                         "'gaps_ms')")
    out, t = [], 0.0
    for g in gaps_ms:
        t += float(g) / 1e3
        out.append(t)
    return out


# -- measurement -------------------------------------------------------------


def parse_loads(spec: str) -> list[tuple[float, bool]]:
    """Offered-load entries: plain frames/sec, or capacity multiples
    suffixed ``x`` (``1.5x`` = 1.5 times the measured closed-loop
    capacity). Returns (value, is_multiplier) pairs."""
    out = []
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        if token.lower().endswith("x"):
            out.append((float(token[:-1]), True))
        else:
            out.append((float(token), False))
    if not out:
        raise ValueError(f"no loads in {spec!r}")
    return out


def measure_capacity(stub, request, seconds: float = 2.0,
                     streams: int = 4) -> float:
    """Closed-loop capacity estimate: ``streams`` workers each fire
    one-frame requests back-to-back for ``seconds``; capacity is the
    aggregate completed ok/sec. Used to anchor ``Nx`` offered loads at a
    known overload factor."""
    stop_t = time.perf_counter() + seconds
    counts = [0] * streams

    def worker(i: int) -> None:
        while time.perf_counter() < stop_t:
            try:
                for resp in stub.AnalyzeActuatorPerformance(iter([request])):
                    if not resp.status.startswith("ERROR"):
                        counts[i] += 1
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(streams)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return sum(counts) / wall if wall > 0 else 0.0


def summarize_level(lat_ms: list[float], errors: int, offered_rps: float,
                    wall_s: float, slo_ms: float | None) -> dict:
    """One LOADBENCH.json row: tail percentiles + violation rate +
    goodput for one offered-load level."""
    arr = np.asarray(sorted(lat_ms), dtype=float)
    n_total = int(arr.size) + errors
    row = {
        "offered_rps": round(offered_rps, 3),
        "arrivals": n_total,
        "n": int(arr.size),
        "errors": errors,
        "achieved_rps": round(n_total / wall_s, 3) if wall_s > 0 else 0.0,
        "goodput_rps": round(arr.size / wall_s, 3) if wall_s > 0 else 0.0,
        "wall_s": round(wall_s, 3),
    }
    for pct, key in PERCENTILES:
        row[key] = (round(float(np.percentile(arr, pct)), 3)
                    if arr.size else None)
    if slo_ms is not None:
        violations = int(np.count_nonzero(arr > slo_ms)) + errors
        row["slo_ms"] = slo_ms
        row["violations"] = violations
        row["violation_rate"] = (round(violations / n_total, 4)
                                 if n_total else 0.0)
    return row


def run_level(stub, request, arrivals: list[float], workers: int,
              deadline_s: float | None = None
              ) -> tuple[list[float], int, float]:
    """Fire one offered-load level: every arrival opens a one-frame
    stream at its scheduled time (late workers start late and the delay
    COUNTS -- latency is measured from the scheduled arrival, the
    open-loop discipline that makes queueing visible). ``deadline_s``
    puts a real gRPC deadline on each request, so server-side
    deadline-aware shedding sees the budget the client actually has."""
    lat_ms: list[float] = []
    errors = 0
    lock = threading.Lock()
    t0 = time.perf_counter()

    def one(offset_s: float) -> None:
        nonlocal errors
        target = t0 + offset_s
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        ok = False
        try:
            status = None
            for resp in stub.AnalyzeActuatorPerformance(
                    iter([request]), timeout=deadline_s):
                status = resp.status
            ok = status is not None and not status.startswith("ERROR")
        except Exception:
            ok = False
        done = time.perf_counter()
        with lock:
            if ok:
                lat_ms.append((done - target) * 1e3)
            else:
                errors += 1

    with ThreadPoolExecutor(max_workers=workers) as pool:
        for offset in arrivals:
            pool.submit(one, offset)
    wall = time.perf_counter() - t0
    return lat_ms, errors, wall


# -- fleet mode --------------------------------------------------------------


def _one_shot(stub, request, deadline_s=None) -> bool:
    """One single-frame stream; True when it completed OK."""
    try:
        status = None
        for resp in stub.AnalyzeActuatorPerformance(iter([request]),
                                                    timeout=deadline_s):
            status = resp.status
        return status is not None and not status.startswith("ERROR")
    except Exception:
        return False


def _warm_fleet(stub, request, fe, endpoints, tries: int = 40) -> int:
    """Warm EVERY live replica through the front-end (each pays its own
    XLA compile on its first frame): fire concurrent single-frame streams
    until each placeable replica has served at least one, counting (not
    failing on) errors -- an armed one-shot RDP_FAULTS on one replica is
    absorbed here, exactly like the single-server warm phase."""
    errors = 0
    want = set(endpoints)
    for _ in range(tries):
        served = {r.endpoint for r in fe.router.replicas
                  if r.endpoint in want and r.frames > 0}
        live = {r.endpoint for r in fe.router.replicas
                if r.endpoint in want and r.placeable}
        if live and live <= served:
            break
        with ThreadPoolExecutor(max_workers=2 * len(want)) as pool:
            results = list(pool.map(
                lambda _: _one_shot(stub, request),
                range(2 * len(want)),
            ))
        errors += sum(1 for ok in results if not ok)
    return errors


def run_fleet_mode(cli, slo_ms: float, deadline_s: float | None,
                   load_spec, duration: float, frame_wh) -> None:
    """The ``--fleet N`` legs: N replica subprocesses (each a full
    serving/server.py process on faked CPU devices, sharing one tiny
    registry) behind the in-process fleet front-end.

    Three legs, identical Poisson arrivals (same seed) so goodput is
    comparable: ``1-replica`` (front-end over one replica -- the
    scaling/parity anchor), ``N-replica`` (the whole fleet), and
    ``replica-kill`` (one replica SIGKILLed mid-level: every accepted
    frame must still terminate, the victim must drop out of placement
    via grpc.health.v1, and -- once respawned on its old port -- rejoin
    through the half-open probe). Rows land in LOADBENCH.json tagged
    ``fleet_leg`` under the usual one-JSON-line contract."""
    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.serving import (
        client as client_lib,
        frontend as frontend_lib,
        replica as replica_lib,
    )
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc
    from robotic_discovery_platform_tpu.utils.config import ServerConfig

    n = cli.fleet
    w, h = frame_wh
    loads = [v for v, mult in load_spec if not mult] or [10.0]
    if len(loads) != len(load_spec):
        raise ValueError("--fleet legs need absolute loads (no 'Nx' "
                         "capacity multiples)")

    tmp = Path(tempfile.mkdtemp(prefix="rdp-fleet-bench-"))
    uri = replica_lib.register_tiny_model(tmp / "mlruns", img_size=w)
    per_env = {}
    if cli.fleet_fault:
        # arm the fault on replica 0 ONLY: the point of the fleet fault
        # leg is one degraded member inside a healthy fleet
        per_env[0] = {"RDP_FAULTS": cli.fleet_fault}
    replicas = replica_lib.spawn_local_replicas(
        n, uri, img_size=w, slo_ms=slo_ms, per_replica_env=per_env,
        metrics_port=-1,  # ephemeral /metrics: the federation scrape
                          # target for the obs-overhead legs
    )
    endpoints = [r.endpoint for r in replicas]
    replica_lib.wait_serving(endpoints)

    source = SyntheticSource(width=w, height=h, seed=cli.seed, n_frames=1)
    source.start()
    color, depth = source.get_frames()
    source.stop()
    request = client_lib.encode_request(color, depth)

    legs = [("1-replica", endpoints[:1], False),
            (f"{n}-replica", endpoints, False),
            ("replica-kill", endpoints, True)]
    rows: list[dict] = []
    leg_summaries: dict[str, dict] = {}
    warm_errors = 0
    kill_report: dict = {}
    try:
        for leg_name, eps, kill in legs:
            fcfg = ServerConfig(
                address="localhost:0",
                fleet_replicas=",".join(eps),
                fleet_poll_s=0.15,
                fleet_probe_timeout_s=1.0,
                fleet_breaker_failures=1,
                fleet_breaker_reset_s=1.0,
            )
            f_server, fe = frontend_lib.build_frontend(fcfg)
            fport = f_server.add_insecure_port("localhost:0")
            f_server.start()
            channel = grpc.insecure_channel(f"localhost:{fport}")
            stub = vision_grpc.VisionAnalysisServiceStub(channel)
            try:
                if not fe.router.wait_live(len(eps), timeout_s=60):
                    raise RuntimeError(
                        f"leg {leg_name}: only {fe.router.live_count} of "
                        f"{len(eps)} replicas became placeable")
                warm_errors += _warm_fleet(stub, request, fe, eps)
                # identical arrival schedule per leg: fresh rng, same seed
                rng = np.random.default_rng(cli.seed)
                leg_rows = []
                pinned: dict[str, int] = {"sent": 0, "responses": 0,
                                          "errors": 0,
                                          "stream_failures": 0}
                pinned_lock = threading.Lock()

                def pinned_stream():
                    """One long-lived stream held OPEN across the kill:
                    with one of these per replica (ring walk spreads
                    them), the victim always has a live stream whose
                    frames must fail over -- deterministic failover
                    evidence at any offered load."""
                    def gen():
                        end = time.monotonic() + duration + 1.0
                        while time.monotonic() < end:
                            with pinned_lock:
                                pinned["sent"] += 1
                            yield request
                            time.sleep(0.15)

                    try:
                        for resp in stub.AnalyzeActuatorPerformance(
                                gen(), timeout=duration + 30):
                            with pinned_lock:
                                pinned["responses"] += 1
                                if resp.status.startswith("ERROR"):
                                    pinned["errors"] += 1
                    except Exception:
                        with pinned_lock:
                            pinned["stream_failures"] += 1

                for rate in loads:
                    arrivals = poisson_arrivals(rate, duration, rng)
                    if not arrivals:
                        continue
                    dropout_seen = threading.Event()
                    victim = replicas[-1]
                    pinned_threads: list[threading.Thread] = []
                    if kill:
                        for _ in eps:
                            t = threading.Thread(target=pinned_stream,
                                                 daemon=True)
                            t.start()
                            pinned_threads.append(t)
                        time.sleep(0.3)  # both streams placed pre-kill

                        def do_kill(victim=victim, fe=fe):
                            victim.kill()
                            deadline = time.monotonic() + 5.0
                            while time.monotonic() < deadline:
                                if fe.router.live_count < len(eps):
                                    dropout_seen.set()
                                    return
                                time.sleep(0.05)

                        killer = threading.Timer(0.45 * duration, do_kill)
                        killer.daemon = True
                        killer.start()
                    lat_ms, errors, wall = run_level(
                        stub, request, arrivals, cli.workers, deadline_s)
                    row = summarize_level(lat_ms, errors, rate, wall,
                                          slo_ms)
                    row["fleet_leg"] = leg_name
                    row["replicas"] = len(eps)
                    leg_rows.append(row)
                    print(f"# fleet leg={leg_name} offered={rate:.1f}rps "
                          f"n={len(lat_ms)} errors={errors} "
                          f"p99={row['p99_ms']}", file=sys.stderr)
                    if kill:
                        killer.join(timeout=duration)
                        for t in pinned_threads:
                            t.join(timeout=duration + 60)
                rows.extend(leg_rows)
                top = leg_rows[-1] if leg_rows else {}
                leg_summaries[leg_name] = {
                    "offered_rps": top.get("offered_rps"),
                    "arrivals": top.get("arrivals"),
                    "n": top.get("n"),
                    "errors": top.get("errors"),
                    "goodput_rps": top.get("goodput_rps"),
                    "p99_ms": top.get("p99_ms"),
                    "violation_rate": top.get("violation_rate"),
                    "balance": [r.frames for r in fe.router.replicas],
                }
                if kill:
                    kill_report = {
                        "dropped_out": dropout_seen.is_set(),
                        "pinned": dict(pinned),
                        "failovers": fe.router.failovers_total,
                        "failover_frames_rerouted":
                            fe.router.failover_frames_rerouted,
                        "failover_frames_error_completed":
                            fe.router.failover_frames_error_completed,
                        "rejoined": False,
                    }
                    # respawn the victim on its old port: the static
                    # endpoint list has not changed, so health-gated
                    # rejoin through the half-open probe is the whole
                    # recovery story
                    fresh = replica_lib.respawn_replica(replicas[-1])
                    replicas[-1] = fresh
                    replica_lib.wait_serving([fresh.endpoint])
                    kill_report["rejoined"] = fe.router.wait_live(
                        len(eps), timeout_s=30)
            finally:
                channel.close()
                f_server.stop(grace=None)
                fe.close()

        # -- observability overhead: federation + journal on vs off ------
        # Identical arrivals against the full fleet twice: once with the
        # observability plane quiet (journal disabled, no federated
        # scraping) and once with it fully hot (journal on, the
        # federator's cache poll running AND a scraper rendering
        # /federate every 250 ms -- the realistic Prometheus load). The
        # p99 delta is what the plane costs on the hot path; CI gates it
        # to a small bound.
        from robotic_discovery_platform_tpu.observability import (
            journal as journal_lib,
        )

        obs_rows: dict[str, dict] = {}
        federate_renders = 0
        for leg_name, plane_on in (("obs-off", False), ("obs-on", True)):
            fcfg = ServerConfig(
                address="localhost:0",
                fleet_replicas=",".join(endpoints),
                fleet_poll_s=0.15,
                fleet_probe_timeout_s=1.0,
                fleet_breaker_failures=1,
                fleet_breaker_reset_s=1.0,
            )
            f_server, fe = frontend_lib.build_frontend(fcfg)
            fport = f_server.add_insecure_port("localhost:0")
            f_server.start()
            channel = grpc.insecure_channel(f"localhost:{fport}")
            stub = vision_grpc.VisionAnalysisServiceStub(channel)
            journal_lib.JOURNAL.set_enabled(plane_on)
            scraper_stop = threading.Event()
            scraper = None
            if plane_on:
                fe.federator.start()  # the last-good cache poll

                def scrape_loop(fed=fe.federator):
                    while not scraper_stop.wait(0.25):
                        try:
                            fed.render()
                        except Exception:  # noqa: BLE001 - keep scraping
                            pass

                scraper = threading.Thread(target=scrape_loop,
                                           daemon=True)
                scraper.start()
            try:
                if not fe.router.wait_live(n, timeout_s=60):
                    raise RuntimeError(
                        f"leg {leg_name}: fleet never became placeable")
                warm_errors += _warm_fleet(stub, request, fe, endpoints)
                rng = np.random.default_rng(cli.seed)
                arrivals = poisson_arrivals(loads[-1], duration, rng)
                lat_ms, errors, wall = run_level(
                    stub, request, arrivals, cli.workers, deadline_s)
                row = summarize_level(lat_ms, errors, loads[-1], wall,
                                      slo_ms)
                row["fleet_leg"] = leg_name
                row["replicas"] = n
                rows.append(row)
                obs_rows[leg_name] = row
                if plane_on:
                    federate_renders = fe.federator.renders
                print(f"# fleet leg={leg_name} offered={loads[-1]:.1f}rps "
                      f"n={len(lat_ms)} errors={errors} "
                      f"p99={row['p99_ms']}", file=sys.stderr)
            finally:
                scraper_stop.set()
                if scraper is not None:
                    scraper.join(timeout=5)
                journal_lib.JOURNAL.set_enabled(True)
                channel.close()
                f_server.stop(grace=None)
                fe.close()
    finally:
        replica_lib.stop_replicas(replicas)

    p99_off = obs_rows.get("obs-off", {}).get("p99_ms")
    p99_on = obs_rows.get("obs-on", {}).get("p99_ms")
    p50_off = obs_rows.get("obs-off", {}).get("p50_ms")
    p50_on = obs_rows.get("obs-on", {}).get("p50_ms")
    obs_overhead = {
        "p99_off_ms": p99_off,
        "p99_on_ms": p99_on,
        "delta_ms": (round(p99_on - p99_off, 3)
                     if p99_on is not None and p99_off is not None
                     else None),
        "p50_off_ms": p50_off,
        "p50_on_ms": p50_on,
        "p50_delta_ms": (round(p50_on - p50_off, 3)
                         if p50_on is not None and p50_off is not None
                         else None),
        "federate_renders": federate_renders,
    }

    one = leg_summaries.get("1-replica", {})
    full = leg_summaries.get(f"{n}-replica", {})
    fleet_block = {
        "replicas": n,
        "legs": leg_summaries,
        "kill": kill_report,
        "scaling_vs_1": (round(full["goodput_rps"] / one["goodput_rps"],
                               3)
                         if one.get("goodput_rps") else None),
        "fault": cli.fleet_fault or None,
        "obs_overhead": obs_overhead,
    }

    payload = {
        "metric": "open_loop_tail_latency",
        "backend": "cpu",
        "unit": "ms",
        "arrivals": "poisson",
        "smoke": True,
        "slo_ms": slo_ms,
        "deadline_ms": (deadline_s * 1e3 if deadline_s else 0.0),
        "workers": cli.workers,
        "frame": [w, h],
        "fleet": fleet_block,
        "rows": rows,
    }
    Path(cli.out).write_text(json.dumps(payload, indent=2) + "\n")

    top = rows[-1] if rows else {}
    p99 = top.get("p99_ms")
    _emit_result({
        "metric": "open_loop_tail_latency",
        "backend": "cpu",
        "value": p99 if p99 is not None and math.isfinite(p99) else 0.0,
        "unit": "ms",
        "offered_rps": top.get("offered_rps", 0.0),
        "goodput_rps": top.get("goodput_rps", 0.0),
        "violation_rate": top.get("violation_rate", 0.0),
        "errors": warm_errors + sum(r["errors"] for r in rows),
        "warm_errors": warm_errors,
        "levels": len(rows),
        "fleet": fleet_block,
        "out": cli.out,
        "smoke": True,
    })


# -- host-path profile -------------------------------------------------------

#: the rdp_host_stage_split_seconds stages, handler order ("entropy" is
#: the split-decode host half, observed alongside "decode" for
#: format=coef frames -- NOT added into host_us, that would double-count)
HOST_SPLIT_STAGES = ("decode", "entropy", "admit", "stage_host", "h2d",
                     "launch", "device", "d2h", "encode")
#: the "host-side per-frame microseconds" headline: decode work + pooled
#: staging + the explicit H2D enqueue (what the ingest overhaul attacks)
HOST_US_STAGES = ("decode", "stage_host", "h2d")


def _host_snapshot() -> dict[str, tuple[float, int]]:
    """(sum_seconds, count) per tracked family/stage, read straight from
    the in-process REGISTRY (the smoke server shares our process, so no
    scrape parse); host_profile_delta diffs two of these."""
    from robotic_discovery_platform_tpu.observability import (
        instruments as obs,
    )

    snap: dict[str, tuple[float, int]] = {}
    for stage in HOST_SPLIT_STAGES:
        child = obs.HOST_STAGE_SPLIT.labels(stage=stage)
        snap[f"split.{stage}"] = (child.sum, child.count)
    for stage in ("decode", "device", "encode", "total"):
        child = obs.STAGE_LATENCY.labels(stage=stage)
        snap[f"stage.{stage}"] = (child.sum, child.count)
    return snap


def host_profile_delta(before: dict, after: dict) -> dict:
    """One measured window's per-frame microsecond split: every stage's
    (sum delta) / (frames delta), so per-dispatch and per-frame
    observations normalize identically."""
    frames = after["stage.total"][1] - before["stage.total"][1]
    per_us = {}
    for key in after:
        ds = after[key][0] - before[key][0]
        per_us[key] = round(1e6 * ds / frames, 2) if frames else 0.0
    split_us = {s: per_us[f"split.{s}"] for s in HOST_SPLIT_STAGES}
    handler_us = {s: per_us[f"stage.{s}"]
                  for s in ("decode", "device", "encode")}
    total_us = per_us["stage.total"]
    return {
        "frames": int(frames),
        "split_us": split_us,
        "handler_us": handler_us,
        "total_us": total_us,
        # the CI sanity gate: the handler-side stages are a partition of
        # the per-frame total (response assembly is the remainder)
        "handler_sum_us": round(sum(handler_us.values()), 2),
        "host_us": round(sum(split_us[s] for s in HOST_US_STAGES), 2),
    }


def run_host_profile(cli, slo_ms: float, deadline_s: float | None,
                     load_spec, duration: float, frame_wh) -> None:
    """``--host-profile``: the ingest overhaul's before/after proof.

    Three legs at the SAME offered load (same Poisson seed): ``before``
    = the pre-overhaul host path (inline decode in the handler thread,
    JPEG/PNG wire payloads), ``after`` = the overhauled path (decode
    worker pool + raw-format zero-copy payloads), and ``coef`` = the
    split-decode wire (format=2 coefficient payloads; the host's whole
    color decode is frombuffer views, dequant+IDCT+upsample+convert run
    on-device ahead of the analyzer). Each leg's per-frame microseconds
    are split into decode / entropy / admit / stage-host / H2D / launch
    / device / D2H / encode by diffing the in-process
    ``rdp_host_stage_split_seconds`` and ``rdp_stage_latency_seconds``
    families around the measured window, and all splits land in
    LOADBENCH.json rows tagged ``host_leg`` together with each leg's
    ``wire_bytes_per_frame``. The headlines: the before->after reduction
    in host-side microseconds (decode + staging) and the before->coef
    reduction in host-side DECODE microseconds (the JPEG-wire leg's
    imdecode cost vs the coefficient leg's byte routing).

    Four more legs profile the EGRESS overhaul on the raw ingest wire:
    ``egress_before`` (device pack stage off, inline PNG encode),
    ``egress_png`` / ``egress_bits`` / ``egress_rle`` (packed D2H, the
    encode pool, response mask_format 0/1/2). The headline is the
    before->bits reduction in per-frame D2H + encode microseconds plus
    the per-format response mask payload bytes; both land under
    ``host_profile.egress`` for the CI egress-smoke gate."""
    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.serving import client as client_lib
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc

    w, h = frame_wh
    abs_loads = [v for v, mult in load_spec if not mult]
    rate = abs_loads[0] if abs_loads else 15.0
    after_workers = (cli.decode_workers if cli.decode_workers
                     else 4)
    legs = (("before", 0, "encoded"),
            ("after", after_workers, "raw"),
            ("coef", after_workers, "coef"))
    rows: list[dict] = []
    profiles: dict[str, dict] = {}
    wire_bytes: dict[str, int] = {}
    warm_errors = 0
    source = SyntheticSource(width=w, height=h, seed=cli.seed, n_frames=1)
    source.start()
    color, depth = source.get_frames()
    source.stop()
    for name, workers, fmt in legs:
        server, servicer, address = boot_smoke_server(
            slo_ms, decode_workers=workers)
        channel = grpc.insecure_channel(address)
        stub = vision_grpc.VisionAnalysisServiceStub(channel)
        try:
            request = client_lib.encode_request(color, depth, fmt=fmt)
            for _ in range(3):
                try:
                    resps = list(
                        stub.AnalyzeActuatorPerformance(iter([request]))
                    )
                    if any(r.status.startswith("ERROR") for r in resps):
                        warm_errors += 1
                except Exception:
                    warm_errors += 1
            servicer.warmup(w, h)
            if fmt == "coef":
                # this leg's clients ship format=2 against a
                # pixel-decode server: warm the coefficient-lane
                # buckets too, or their first dispatches pay the fused
                # decode+analyze compilation inside the measured window
                servicer.warmup_coef(w, h)
            snap0 = _host_snapshot()
            arrivals = poisson_arrivals(
                rate, duration, np.random.default_rng(cli.seed))
            lat_ms, errors, wall = run_level(
                stub, request, arrivals, cli.workers, deadline_s)
            prof = host_profile_delta(snap0, _host_snapshot())
            row = summarize_level(lat_ms, errors, rate, wall, slo_ms)
            row["host_leg"] = name
            row["decode_workers"] = workers
            row["wire_format"] = fmt
            row["wire_bytes_per_frame"] = request.ByteSize()
            row["host_profile"] = prof
            rows.append(row)
            profiles[name] = prof
            wire_bytes[name] = request.ByteSize()
            print(f"# host leg={name} workers={workers} fmt={fmt} "
                  f"wire={request.ByteSize()}B "
                  f"host_us={prof['host_us']} split={prof['split_us']}",
                  file=sys.stderr)
        finally:
            channel.close()
            server.stop(grace=None)
            servicer.close()

    # -- egress legs (PR 20): the response-path mirror of the ingest
    # comparison, all on the raw ingest wire so decode cost is constant.
    # "egress_before" disables the device pack stage (the pre-pack
    # FrameAnalysis multi-leaf fetch + inline PNG encode); the packed
    # legs differ only in the response mask_format (0=PNG through the
    # encode pool, 1=bits, 2=RLE). The gated numbers: per-frame d2h +
    # encode microseconds (before vs bits) and the response mask payload
    # bytes per leg (PNG vs packed).
    egress_legs = (
        ("egress_before", {"egress_pack": False, "egress_workers": 0}, 0),
        ("egress_png", {"egress_workers": 4}, 0),
        ("egress_bits", {"egress_workers": 4}, 1),
        ("egress_rle", {"egress_workers": 4}, 2),
    )
    egress_profiles: dict[str, dict] = {}
    mask_bytes: dict[str, int] = {}
    for name, extra, mf in egress_legs:
        server, servicer, address = boot_smoke_server(
            slo_ms, decode_workers=after_workers, extra_cfg=extra)
        channel = grpc.insecure_channel(address)
        stub = vision_grpc.VisionAnalysisServiceStub(channel)
        try:
            request = client_lib.encode_request(
                color, depth, fmt="raw", mask_format=mf)
            for _ in range(3):
                try:
                    resps = list(
                        stub.AnalyzeActuatorPerformance(iter([request]))
                    )
                    if any(r.status.startswith("ERROR") for r in resps):
                        warm_errors += 1
                except Exception:
                    warm_errors += 1
            servicer.warmup(w, h)
            # one probe response records the leg's mask payload size
            # (identical frame on every leg, so the ratios are exact)
            probe = list(stub.AnalyzeActuatorPerformance(iter([request])))
            if probe and not probe[0].status.startswith("ERROR"):
                mask_bytes[name] = len(probe[0].mask)
            snap0 = _host_snapshot()
            arrivals = poisson_arrivals(
                rate, duration, np.random.default_rng(cli.seed))
            lat_ms, errors, wall = run_level(
                stub, request, arrivals, cli.workers, deadline_s)
            prof = host_profile_delta(snap0, _host_snapshot())
            row = summarize_level(lat_ms, errors, rate, wall, slo_ms)
            row["host_leg"] = name
            row["decode_workers"] = after_workers
            row["wire_format"] = "raw"
            row["mask_format"] = mf
            row["wire_bytes_per_frame"] = request.ByteSize()
            row["response_mask_bytes"] = mask_bytes.get(name, 0)
            row["host_profile"] = prof
            rows.append(row)
            egress_profiles[name] = prof
            print(f"# host leg={name} mask_format={mf} "
                  f"resp_mask={mask_bytes.get(name, 0)}B "
                  f"d2h_us={prof['split_us']['d2h']} "
                  f"encode_us={prof['split_us']['encode']}",
                  file=sys.stderr)
        finally:
            channel.close()
            server.stop(grace=None)
            servicer.close()

    before, after = profiles["before"], profiles["after"]
    coef = profiles.get("coef")
    reduction = (1.0 - after["host_us"] / before["host_us"]
                 if before["host_us"] > 0 else 0.0)
    host_block = {
        "offered_rps": rate,
        "frame": [w, h],
        "before": before,
        "after": after,
        "host_us_before": before["host_us"],
        "host_us_after": after["host_us"],
        "reduction_pct": round(100.0 * reduction, 1),
        "wire_bytes_per_frame": wire_bytes,
    }
    if coef is not None:
        # split-decode headline: the JPEG-wire leg's per-frame host
        # DECODE microseconds (imdecode + cvtColor) vs the coefficient
        # leg's (frombuffer views; the "entropy" stage is a labeled VIEW
        # of the same work, not an addend) -- the number the CI
        # decode-smoke gate reads
        decode_before = before["split_us"]["decode"]
        decode_coef = coef["split_us"]["decode"]
        host_block["coef"] = coef
        host_block["decode_us_before"] = decode_before
        host_block["decode_us_coef"] = round(decode_coef, 2)
        host_block["coef_decode_reduction_pct"] = round(
            100.0 * (1.0 - decode_coef / decode_before)
            if decode_before > 0 else 0.0, 1)
        host_block["coef_host_reduction_pct"] = round(
            100.0 * (1.0 - coef["host_us"] / before["host_us"])
            if before["host_us"] > 0 else 0.0, 1)

    if egress_profiles:
        # egress headline: per-frame response-path host microseconds
        # (D2H fetch + mask encode) on the pre-pack leg vs the packed
        # bits leg, and the response mask payload per format. The CI
        # egress-smoke gate reads egress_reduction_pct (>= 30) and
        # wire_ratio_png_over_rle (>= 4; RLE, not bits -- bitpacked rows
        # are fixed-size and can exceed PNG on sparse masks).
        def _d2h_encode(p: dict) -> float:
            return p["split_us"]["d2h"] + p["split_us"]["encode"]

        eg_before = _d2h_encode(egress_profiles["egress_before"])
        eg_packed = _d2h_encode(egress_profiles["egress_bits"])
        egress_block = {
            "legs": egress_profiles,
            "d2h_encode_us": {n: round(_d2h_encode(p), 2)
                              for n, p in egress_profiles.items()},
            "d2h_encode_us_before": round(eg_before, 2),
            "d2h_encode_us_packed": round(eg_packed, 2),
            "egress_reduction_pct": round(
                100.0 * (1.0 - eg_packed / eg_before)
                if eg_before > 0 else 0.0, 1),
            "response_mask_bytes": mask_bytes,
        }
        png_b = mask_bytes.get("egress_png", 0)
        rle_b = mask_bytes.get("egress_rle", 0)
        bits_b = mask_bytes.get("egress_bits", 0)
        if png_b and rle_b:
            egress_block["wire_ratio_png_over_rle"] = round(
                png_b / rle_b, 2)
        if png_b and bits_b:
            egress_block["wire_ratio_png_over_bits"] = round(
                png_b / bits_b, 2)
        host_block["egress"] = egress_block

    import jax

    payload = {
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        "unit": "ms",
        "arrivals": "poisson",
        "smoke": True,
        "slo_ms": slo_ms,
        "deadline_ms": (deadline_s * 1e3 if deadline_s else 0.0),
        "workers": cli.workers,
        "frame": [w, h],
        "host_profile": host_block,
        "rows": rows,
    }
    Path(cli.out).write_text(json.dumps(payload, indent=2) + "\n")

    top = rows[-1] if rows else {}
    p99 = top.get("p99_ms")
    _emit_result({
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        "value": p99 if p99 is not None and math.isfinite(p99) else 0.0,
        "unit": "ms",
        "offered_rps": rate,
        "goodput_rps": top.get("goodput_rps", 0.0),
        "violation_rate": top.get("violation_rate", 0.0),
        "errors": warm_errors + sum(r["errors"] for r in rows),
        "warm_errors": warm_errors,
        "levels": len(rows),
        "host": host_block,
        "out": cli.out,
        "smoke": True,
    })


# -- multi-model statistical multiplexing ------------------------------------


def modulated_poisson_arrivals(mean_rate: float, duration_s: float,
                               period_s: float, phase: float,
                               rng: np.random.Generator,
                               peak_frac: float = 0.9) -> list[float]:
    """Square-wave-modulated Poisson arrivals: the model is BURSTY --
    rate_hi during its active half-period, rate_lo otherwise, with
    ``peak_frac`` of the traffic landing in the active half. Two models
    with phases 0.0 and 0.5 are perfectly anti-correlated: one peaks
    exactly while the other sleeps (the AlpaServe multiplexing case)."""
    hi = 2.0 * mean_rate * peak_frac
    lo = max(2.0 * mean_rate * (1.0 - peak_frac), 1e-3)
    out: list[float] = []
    t = 0.0
    while True:
        cycle = ((t / period_s) + phase) % 1.0
        rate = hi if cycle < 0.5 else lo
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append(t)


def run_mixed_level(stub, requests: dict, schedule: list[tuple[float, str]],
                    workers: int, deadline_s: float | None,
                    slo_ms: float) -> dict:
    """Fire one mixed-model offered-load level: ``schedule`` is a merged
    [(offset_s, model)] list; latency/violation bookkeeping is kept PER
    MODEL (the multi-tenant question is who burned whose budget)."""
    per: dict[str, dict] = {
        m: {"lat_ms": [], "errors": 0} for m in requests
    }
    lock = threading.Lock()
    t0 = time.perf_counter()

    def one(offset_s: float, model: str) -> None:
        target = t0 + offset_s
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        ok = False
        try:
            status = None
            for resp in stub.AnalyzeActuatorPerformance(
                    iter([requests[model]]), timeout=deadline_s):
                status = resp.status
            ok = status is not None and not status.startswith("ERROR")
        except Exception:
            ok = False
        done = time.perf_counter()
        with lock:
            if ok:
                per[model]["lat_ms"].append((done - target) * 1e3)
            else:
                per[model]["errors"] += 1

    with ThreadPoolExecutor(max_workers=workers) as pool:
        for offset, model in schedule:
            pool.submit(one, offset, model)
    wall = time.perf_counter() - t0

    models = {}
    all_lat: list[float] = []
    total_errors = 0
    for m, d in per.items():
        offered = sum(1 for _, mm in schedule if mm == m) / max(wall, 1e-9)
        models[m] = summarize_level(d["lat_ms"], d["errors"], offered,
                                    wall, slo_ms)
        all_lat.extend(d["lat_ms"])
        total_errors += d["errors"]
    row = summarize_level(all_lat, total_errors,
                          len(schedule) / max(wall, 1e-9), wall, slo_ms)
    row["models"] = models
    return row


def run_multimodel_mode(cli, slo_ms: float, deadline_s: float | None,
                        duration: float, frame_wh) -> None:
    """``--models seg,aux``: the statistical-multiplexing proof.

    Two (or more) zoo models receive phase-shifted (anti-correlated)
    square-wave Poisson arrivals against three server shapes at the SAME
    total chip count:

    - ``baseline-<m>`` -- each model ALONE on the full mesh at its own
      schedule (the pre-contention violation ceiling);
    - ``multiplexed``  -- one zoo server, shared placement: every
      model's burst may use every chip (AlpaServe co-location);
    - ``dedicated``    -- the same zoo server with the static
      chips/M-per-model partition (silicon per model).

    The claim gated in CI: multiplexed aggregate goodput >= dedicated at
    equal chips, with each model's multiplexed violation rate under its
    single-model baseline ceiling. ``--zoo-fault SPEC`` adds a fourth
    leg with the fault armed (e.g. serving.model.aux.dispatch:exc:-1)
    proving zero cross-model frame loss."""
    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.resilience import configure_faults
    from robotic_discovery_platform_tpu.serving import client as client_lib
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc

    models = [m.strip() for m in cli.models.split(",") if m.strip()]
    if len(models) < 2:
        raise ValueError("--models needs at least two zoo models")
    chips = cli.chips if cli.chips > 1 else 4
    rate = cli.model_rate
    period = cli.period or max(2.0, duration / 2.0)
    zoo_spec = ",".join(models)
    w, h = frame_wh

    source = SyntheticSource(width=w, height=h, seed=cli.seed, n_frames=1)
    source.start()
    color, depth = source.get_frames()
    source.stop()
    requests = {
        m: client_lib.encode_request(color, depth,
                                     model=("" if m == models[0] else m))
        for m in models
    }

    def schedules() -> dict[str, list[float]]:
        """Identical per-model arrival schedules for every leg (fresh
        rng, same seed), phases spread so the models anti-correlate."""
        rng = np.random.default_rng(cli.seed)
        return {
            m: modulated_poisson_arrivals(
                rate, duration, period, i / len(models), rng)
            for i, m in enumerate(models)
        }

    def boot(zoo, placement, fault=None):
        if fault:
            configure_faults(fault)
        return boot_smoke_server(
            slo_ms, chips=chips, zoo_models=zoo,
            zoo_placement=placement,
            # placer timing fine enough to resolve the burst phases:
            # each half-period must span several rate intervals, or the
            # correlation estimate aliases and a mis-detected positive
            # correlation confines an anti-correlated model mid-run
            extra_cfg={
                "zoo_rate_interval_s": max(0.25, period / 8.0),
                "zoo_rebalance_s": max(1.0, period / 2.0),
                # the correlation window must cover the MEASURED phase
                # only: stretching it back over the warm phase's shared
                # silence correlates every model positively with every
                # other and buries the anti-phase signal
                "zoo_rate_window": max(
                    8, int(duration / max(0.25, period / 8.0))),
            },
        )

    def warm(stub, servicer, reqs):
        errors = 0
        for req in reqs:
            for _ in range(2):
                try:
                    resps = list(
                        stub.AnalyzeActuatorPerformance(iter([req])))
                    if any(r.status.startswith("ERROR") for r in resps):
                        errors += 1
                except Exception:
                    errors += 1
        servicer.warmup(w, h)
        return errors

    legs: list[tuple[str, str, list[str], str | None]] = [
        *[(f"baseline-{m}", zoo_spec, [m], None) for m in models],
        ("multiplexed", zoo_spec, models, None),
        ("dedicated", zoo_spec, models, None),
    ]
    if cli.zoo_fault:
        legs.append(("fault", zoo_spec, models, cli.zoo_fault))

    rows: list[dict] = []
    leg_rows: dict[str, dict] = {}
    warm_errors = 0
    try:
        for leg_name, zoo, active, fault in legs:
            placement = ("dedicated" if leg_name == "dedicated"
                         else "shared")
            server, servicer, address = boot(zoo, placement, fault)
            channel = grpc.insecure_channel(address)
            stub = vision_grpc.VisionAnalysisServiceStub(channel)
            try:
                warm_errors += warm(stub, servicer,
                                    [requests[m] for m in active])
                sched = schedules()
                merged = sorted(
                    [(t, m) for m in active for t in sched[m]]
                )
                row = run_mixed_level(stub, requests, merged,
                                      cli.workers, deadline_s, slo_ms)
                row["multimodel_leg"] = leg_name
                row["chips"] = chips
                row["placement"] = placement
                row["active_models"] = active
                if servicer.placer is not None:
                    row["placer"] = servicer.placer.snapshot()
                rows.append(row)
                leg_rows[leg_name] = row
                per = {m: (row["models"][m]["violation_rate"],
                           row["models"][m]["goodput_rps"])
                       for m in active}
                print(f"# multimodel leg={leg_name} placement={placement} "
                      f"goodput={row['goodput_rps']} per-model "
                      f"(viol, goodput)={per}", file=sys.stderr)
            finally:
                channel.close()
                server.stop(grace=None)
                servicer.close()
                if fault:
                    configure_faults(None)
    finally:
        configure_faults(None)

    mux = leg_rows.get("multiplexed", {})
    ded = leg_rows.get("dedicated", {})
    ceilings = {
        m: leg_rows.get(f"baseline-{m}", {}).get("models", {}).get(
            m, {}).get("violation_rate")
        for m in models
    }
    fault_row = leg_rows.get("fault")
    mux_placer = mux.get("placer", {})
    corr = mux_placer.get("correlation", {})
    gates = {
        # (a) multiplexing vs the dedicated partition at equal chips.
        # NOTE the honest caveat this container imposes: the faked CPU
        # "chips" share ONE core, so partitioning cannot reduce a
        # model's available COMPUTE here and the capacity half of the
        # AlpaServe claim is only measurable on real hardware (same
        # standing TPU-window item as multi-chip scaling). What the
        # smoke CAN prove: at equal total chips the shared placement
        # matches the partition's goodput while absorbing each model's
        # bursts with a materially better tail (the burst rides every
        # window the quiet model is not using).
        "goodput_multiplexed": mux.get("goodput_rps"),
        "goodput_dedicated": ded.get("goodput_rps"),
        "multiplexed_ge_dedicated": (
            mux.get("goodput_rps", 0.0)
            >= 0.95 * ded.get("goodput_rps", 0.0)
        ),
        "p99_multiplexed_ms": mux.get("p99_ms"),
        "p99_dedicated_ms": ded.get("p99_ms"),
        # the anti-correlation must actually have been MEASURED (the
        # placer's co-location decision is evidence-driven, not luck)
        "measured_correlation": corr,
        "anti_correlated": all(v < 0 for v in corr.values()) if corr
                           else None,
        "shared_placement_held": (
            all(len(chips_) == chips for chips_ in
                mux_placer.get("placement", {}).values())
            if mux_placer else None
        ),
        # (b) each model's multiplexed violation rate vs its
        # single-model baseline ceiling
        "per_model_violation_multiplexed": {
            m: mux.get("models", {}).get(m, {}).get("violation_rate")
            for m in models
        },
        "baseline_ceilings": ceilings,
        # (c) zero cross-model loss: in the fault leg, every model the
        # fault does NOT name must complete all its frames OK
        "cross_model_losses": (
            {m: fault_row["models"][m]["errors"] for m in models
             if fault_row is not None
             and f".{m}." not in (cli.zoo_fault or "")}
            if fault_row is not None else None
        ),
    }
    block = {
        "models": models,
        "chips": chips,
        "rate_per_model": rate,
        "period_s": period,
        "duration_s": duration,
        "legs": {k: {kk: v[kk] for kk in
                     ("goodput_rps", "violation_rate", "errors", "n",
                      "p99_ms") if kk in v}
                 for k, v in leg_rows.items()},
        "gates": gates,
    }

    import jax

    payload = {
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        "unit": "ms",
        "arrivals": "modulated-poisson",
        "smoke": True,
        "slo_ms": slo_ms,
        "deadline_ms": (deadline_s * 1e3 if deadline_s else 0.0),
        "workers": cli.workers,
        "frame": [w, h],
        "multimodel": block,
        "rows": rows,
    }
    Path(cli.out).write_text(json.dumps(payload, indent=2) + "\n")

    _emit_result({
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        "value": (mux.get("p99_ms") or 0.0),
        "unit": "ms",
        "goodput_rps": mux.get("goodput_rps", 0.0),
        "violation_rate": mux.get("violation_rate", 0.0),
        "errors": warm_errors + sum(r["errors"] for r in rows),
        "warm_errors": warm_errors,
        "levels": len(rows),
        "multimodel": block,
        "out": cli.out,
        "smoke": True,
    })


# -- smoke server ------------------------------------------------------------


def boot_smoke_server(slo_ms: float, controller: bool = False,
                      chips: int = 1, decode_workers: int = 0,
                      zoo_models: str = "", zoo_placement: str = "shared",
                      zoo_eager_warm: int = -1,
                      extra_cfg: dict | None = None):
    """An in-process CPU server shaped like tools/metrics_smoke.py's:
    tiny registered model, micro-batching ON (so the dispatcher, the
    flight recorder, and the serving.batch.* fault sites are all in the
    measured path), metrics endpoint on an ephemeral port.

    ``controller=True`` boots the full overload control plane
    (deadline-aware admission + the reactive controller, tightened to
    smoke-scale time constants); False boots the control-off comparison
    leg (FIFO admission, static knobs -- the PR 2 behavior). ``chips``
    routes the dispatch window across that many faked CPU mesh chips
    (the quarantine leg's topology). ``decode_workers`` sizes the ingest
    decode pool (0 = the historical inline decode). ``zoo_models`` /
    ``zoo_placement`` shape the model zoo (serving/zoo.py): every named
    variant is registered into the smoke registry."""
    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=8 if chips > 1 else 1)

    from robotic_discovery_platform_tpu.models import (
        variants as variants_lib,
    )
    from robotic_discovery_platform_tpu.serving import (
        replica as replica_lib,
    )
    from robotic_discovery_platform_tpu.serving import server as server_lib
    from robotic_discovery_platform_tpu.utils.config import ServerConfig

    roster = variants_lib.resolve_zoo_models(zoo_models)
    tmp = Path(tempfile.mkdtemp(prefix="rdp-load-bench-"))
    uri = replica_lib.register_tiny_model(
        Path(tmp) / "mlruns", img_size=64, models=roster,
    )
    cfg = ServerConfig(
        address="localhost:0",
        tracking_uri=uri,
        model_img_size=64,
        metrics_csv=str(tmp / "metrics.csv"),
        metrics_flush_every=64,
        calibration_path=str(tmp / "missing.npz"),
        batch_window_ms=2.0,
        max_batch=4,
        metrics_port=-1,
        reload_poll_s=0.0,
        slo_ms=slo_ms,
        # burn must react within a few-second smoke level -- and with a
        # 128-frame window a 1% budget would let two slow frames read as
        # "objective breached"; 5% keeps the smoke's brownout trigger at
        # real overload, not scheduler noise
        slo_window=128,
        slo_budget=0.05,
        serving_mesh=chips if chips > 1 else 0,
        # the comparison legs: full overload control plane vs the PR 2
        # static/FIFO behavior
        admission_policy="deadline" if controller else "fifo",
        controller_enabled=controller,
        controller_interval_s=0.1,
        controller_sustain_s=0.3,
        controller_cooldown_s=0.5,
        chip_breaker_failures=3 if controller or chips > 1 else 0,
        chip_breaker_reset_s=2.0,
        decode_workers=decode_workers,
        zoo_models=zoo_models,
        zoo_placement=zoo_placement,
        # full eager warm per zoo model: the bench measures steady-state
        # multiplexing, not first-burst compile stalls
        zoo_eager_warm=zoo_eager_warm,
        **(extra_cfg or {}),
    )
    # no warmup_shape here on purpose: an armed serving.batch.complete
    # fault would fire inside build_server's warm-up frame and abort the
    # boot; the harness's own warm phase absorbs (and counts) it instead
    server, servicer = server_lib.build_server(cfg)
    port = server.add_insecure_port("localhost:0")
    server.start()
    return server, servicer, f"localhost:{port}"


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="boot an in-process CPU server (tiny model, "
                             "64x64 frames) and run short levels")
    parser.add_argument("--server", default=None,
                        help="address of an already-running server "
                             "(host:port); mutually exclusive with --smoke")
    parser.add_argument("--loads", default=None,
                        help="comma-separated offered loads in frames/sec, "
                             "or capacity multiples suffixed 'x' (1.5x = "
                             "1.5 times measured closed-loop capacity) "
                             "(default: 5,10,20 smoke / 50,100,200 full)")
    parser.add_argument("--controller", choices=("off", "on", "both"),
                        default="off",
                        help="overload-control comparison legs: 'off' = "
                             "FIFO admission + static knobs, 'on' = "
                             "deadline admission + reactive controller, "
                             "'both' = run both legs at the same loads "
                             "(smoke only; rows are tagged per leg)")
    parser.add_argument("--chips", type=int, default=1,
                        help="smoke-server mesh width (faked CPU devices); "
                             ">1 exercises multi-chip routing and the "
                             "serving.chip.<i>.dispatch quarantine path")
    parser.add_argument("--fleet", type=int, default=0, metavar="N",
                        help="boot N replica server subprocesses behind "
                             "the in-process fleet front-end and run the "
                             "1-replica / N-replica / replica-kill legs "
                             "(serving/frontend.py); needs --smoke")
    parser.add_argument("--fleet-fault", default=None, metavar="SPEC",
                        help="RDP_FAULTS spec armed on replica 0 ONLY "
                             "(one degraded member inside a healthy "
                             "fleet), e.g. serving.batch.complete:exc:1")
    parser.add_argument("--models", default=None, metavar="A,B",
                        help="multi-model statistical-multiplexing legs "
                             "(zoo variants, e.g. seg,aux): phase-"
                             "shifted anti-correlated arrivals against "
                             "baseline / multiplexed / dedicated "
                             "placements at equal total chips; needs "
                             "--smoke (chips default 4 here)")
    parser.add_argument("--model-rate", type=float, default=40.0,
                        help="mean per-model offered load (frames/sec) "
                             "for the --models legs; each model bursts "
                             "to ~1.8x this during its active half-"
                             "period")
    parser.add_argument("--period", type=float, default=None,
                        help="burst period (seconds) for the --models "
                             "legs (default: half the level duration)")
    parser.add_argument("--zoo-fault", default=None, metavar="SPEC",
                        help="RDP_FAULTS spec armed for one extra "
                             "--models leg (e.g. serving.model.aux."
                             "dispatch:exc:-1): the named model's "
                             "frames must fail loudly while every "
                             "other model completes clean (zero "
                             "cross-model loss)")
    parser.add_argument("--host-profile", action="store_true",
                        help="host-path before/after profile: run the "
                             "same offered load against the pre-overhaul "
                             "ingest (inline decode, JPEG/PNG wire) and "
                             "the overhauled one (decode pool + raw "
                             "payloads), splitting per-frame microseconds "
                             "into decode/admit/stage-host/H2D/launch/"
                             "device/D2H/encode; needs --smoke")
    parser.add_argument("--decode-workers", type=int, default=None,
                        help="ingest decode-pool width for the smoke "
                             "server ('after' leg of --host-profile, "
                             "default 4 there; other smoke legs default "
                             "to 0 = the historical inline decode)")
    parser.add_argument("--wire-format", default="encoded",
                        choices=("encoded", "raw", "coef"),
                        help="request wire format for the plain smoke "
                             "legs (encoded = JPEG/PNG, raw = zero-copy "
                             "RGB8/z16, coef = split-decode format=2 "
                             "coefficient payloads); --host-profile "
                             "sweeps all three itself")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-request gRPC deadline (default: the "
                             "SLO itself -- a client with a 250ms "
                             "objective gives up at 250ms) -- the budget "
                             "deadline-aware shedding works against; 0 "
                             "disables")
    parser.add_argument("--duration", type=float, default=None,
                        help="seconds per load level (default: 2.5 smoke "
                             "/ 20 full)")
    parser.add_argument("--trace", default=None,
                        help="replay arrivals from a JSON array of "
                             "inter-arrival milliseconds instead of "
                             "Poisson levels")
    parser.add_argument("--slo-ms", type=float, default=None,
                        help="client-side latency objective for the "
                             "violation-rate column (default: RDP_SLO_MS "
                             "or 250 smoke / 50 full)")
    parser.add_argument("--workers", type=int, default=32,
                        help="max concurrent in-flight requests (the "
                             "simulated client-fleet width)")
    parser.add_argument("--frame-size", type=int, default=None,
                        help="square frame edge (default 64 smoke / 480 "
                             "full; full mode sends 640x480)")
    parser.add_argument("--out", default="LOADBENCH.json",
                        help="result file (default LOADBENCH.json)")
    parser.add_argument("--seed", type=int, default=0)
    cli = parser.parse_args()
    if not cli.smoke and not cli.server:
        parser.error("one of --smoke or --server is required")
    if cli.controller == "both" and not cli.smoke:
        parser.error("--controller both boots one server per leg; it "
                     "needs --smoke")
    if cli.chips > 1 and not cli.smoke:
        parser.error("--chips shapes the smoke server; it needs --smoke")
    if cli.host_profile:
        if not cli.smoke:
            parser.error("--host-profile boots per-leg smoke servers; it "
                         "needs --smoke")
        if cli.fleet or cli.controller != "off":
            parser.error("--host-profile is its own comparison; drop "
                         "--fleet/--controller")
    if cli.models:
        if not cli.smoke:
            parser.error("--models boots per-leg zoo smoke servers; it "
                         "needs --smoke")
        if cli.fleet or cli.host_profile or cli.controller != "off":
            parser.error("--models is its own comparison; drop "
                         "--fleet/--host-profile/--controller")
    if cli.fleet:
        if not cli.smoke:
            parser.error("--fleet boots local CPU replicas; it needs "
                         "--smoke")
        if cli.fleet < 2:
            parser.error("--fleet needs at least 2 replicas (the legs "
                         "compare N vs 1 and kill one mid-run)")
        if cli.controller != "off":
            parser.error("--controller tunes the single-server legs; "
                         "fleet replicas run their own control plane")
    legs = ["off", "on"] if cli.controller == "both" else [cli.controller]

    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.serving import client as client_lib
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc

    env_slo = os.environ.get("RDP_SLO_MS", "").strip()
    slo_ms = (cli.slo_ms if cli.slo_ms is not None
              else float(env_slo) if env_slo
              else (250.0 if cli.smoke else 50.0))
    load_spec = (parse_loads(cli.loads) if cli.loads
                 else [(v, False) for v in
                       ([5.0, 10.0, 20.0] if cli.smoke
                        else [50.0, 100.0, 200.0])])
    needs_capacity = any(mult for _, mult in load_spec)
    duration = cli.duration or (2.5 if cli.smoke else 20.0)
    if cli.frame_size:
        w = h = cli.frame_size
    else:
        w, h = (64, 64) if cli.smoke else (640, 480)
    deadline_ms = (cli.deadline_ms if cli.deadline_ms is not None
                   else slo_ms)
    deadline_s = deadline_ms / 1e3 if deadline_ms > 0 else None

    if cli.models:
        run_multimodel_mode(cli, slo_ms, deadline_s,
                            cli.duration or 8.0, (w, h))
        return

    if cli.host_profile:
        run_host_profile(cli, slo_ms, deadline_s, load_spec, duration,
                         (w, h))
        return

    if cli.fleet:
        run_fleet_mode(cli, slo_ms, deadline_s, load_spec, duration,
                       (w, h))
        return

    rng = np.random.default_rng(cli.seed)
    request = None
    rows: list[dict] = []
    legs_summary: dict[str, dict] = {}
    capacity = None
    warm_errors = 0
    quarantines_total = 0
    for leg in legs:
        server = servicer = None
        if cli.smoke:
            server, servicer, address = boot_smoke_server(
                slo_ms, controller=(leg == "on"), chips=cli.chips,
                decode_workers=(cli.decode_workers or 0),
            )
        else:
            address = cli.server
        channel = grpc.insecure_channel(address)
        stub = vision_grpc.VisionAnalysisServiceStub(channel)
        try:
            if request is None:
                source = SyntheticSource(width=w, height=h, seed=cli.seed,
                                         n_frames=1)
                source.start()
                color, depth = source.get_frames()
                source.stop()
                request = client_lib.encode_request(
                    color, depth, fmt=cli.wire_format)
            # warm phase, off the measured window: pays XLA compilation
            # for the single-frame bucket and ABSORBS any armed one-shot
            # fault (CI's graceful-degradation leg) -- errors are
            # counted, not fatal
            for _ in range(3):
                try:
                    resps = list(
                        stub.AnalyzeActuatorPerformance(iter([request]))
                    )
                    if any(r.status.startswith("ERROR") for r in resps):
                        warm_errors += 1
                except Exception:
                    warm_errors += 1
            if servicer is not None:
                # pre-compile every reachable batch bucket so the
                # measured tail reflects serving, not one-off XLA
                # compilation
                servicer.warmup(w, h)
                if cli.wire_format == "coef":
                    # format=2 wire: the coefficient lane has its own
                    # fused decode+analyze graphs per bucket -- warm
                    # them too so the measured tail stays compile-free
                    servicer.warmup_coef(w, h)
            if needs_capacity and capacity is None:
                # anchor 'Nx' loads once, on the FIRST leg's server, so
                # every leg sees the same absolute offered loads
                capacity = measure_capacity(stub, request)
                print(f"# measured capacity ~{capacity:.1f} rps",
                      file=sys.stderr)
            loads = [v * capacity if mult else v for v, mult in load_spec]
            leg_rows: list[dict] = []
            if cli.trace:
                arrivals = trace_arrivals(cli.trace)
                offered = (len(arrivals) / arrivals[-1]
                           if arrivals[-1] else 0.0)
                lat_ms, errors, wall = run_level(
                    stub, request, arrivals, cli.workers, deadline_s)
                leg_rows.append(summarize_level(lat_ms, errors, offered,
                                                wall, slo_ms))
            else:
                for rate in loads:
                    arrivals = poisson_arrivals(rate, duration, rng)
                    if not arrivals:
                        continue
                    lat_ms, errors, wall = run_level(
                        stub, request, arrivals, cli.workers, deadline_s)
                    leg_rows.append(summarize_level(lat_ms, errors, rate,
                                                    wall, slo_ms))
                    print(f"# leg={leg} offered={rate:.1f}rps "
                          f"n={len(lat_ms)} errors={errors} "
                          f"p50={leg_rows[-1]['p50_ms']} "
                          f"p99={leg_rows[-1]['p99_ms']}",
                          file=sys.stderr)
            for row in leg_rows:
                row["controller"] = leg
            rows.extend(leg_rows)
            top = leg_rows[-1] if leg_rows else {}
            summary = {k: top.get(k) for k in (
                "offered_rps", "p99_ms", "goodput_rps", "violation_rate",
                "errors")}
            if servicer is not None:
                dispatcher = servicer.dispatcher
                router = (dispatcher.router
                          if dispatcher is not None else None)
                summary["quarantines"] = (router.quarantines_total
                                          if router is not None else 0)
                quarantines_total += summary["quarantines"]
                if servicer.controller is not None:
                    summary["controller_actions"] = (
                        servicer.controller.actions_total)
                    summary["brownout_level"] = servicer.controller.level
            legs_summary[leg] = summary
        finally:
            channel.close()
            if server is not None:
                server.stop(grace=None)
            if servicer is not None:
                servicer.close()

    import jax

    payload = {
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        "unit": "ms",
        "arrivals": "trace" if cli.trace else "poisson",
        "smoke": bool(cli.smoke),
        "slo_ms": slo_ms,
        "deadline_ms": deadline_ms,
        "workers": cli.workers,
        "frame": [w, h],
        "chips": cli.chips,
        "capacity_rps": (round(capacity, 3) if capacity is not None
                         else None),
        "legs": legs_summary,
        "rows": rows,
    }
    Path(cli.out).write_text(json.dumps(payload, indent=2) + "\n")

    total_errors = warm_errors + sum(r["errors"] for r in rows)
    top = rows[-1] if rows else {}
    p99 = top.get("p99_ms")
    _emit_result({
        "metric": "open_loop_tail_latency",
        "backend": jax.default_backend(),
        # headline: p99 at the highest offered load that was measured
        # (the LAST leg's top row: the controller-on leg under 'both')
        "value": p99 if p99 is not None and math.isfinite(p99) else 0.0,
        "unit": "ms",
        "offered_rps": top.get("offered_rps", 0.0),
        "goodput_rps": top.get("goodput_rps", 0.0),
        "violation_rate": top.get("violation_rate", 0.0),
        "errors": total_errors,
        "warm_errors": warm_errors,
        "levels": len(rows),
        "legs": legs_summary,
        "capacity_rps": (round(capacity, 3) if capacity is not None
                         else None),
        "quarantines": quarantines_total,
        "out": cli.out,
        "smoke": bool(cli.smoke),
    })


if __name__ == "__main__":
    _arm_deadline()
    try:
        main()
    except Exception as e:  # noqa: BLE001 -- structured artifact by design
        import traceback

        traceback.print_exc()
        _emit_result(_error_payload(
            "bench_error", f"{type(e).__name__}: {e}"))
        sys.exit(0)
