"""Pallas-vs-XLA micro-benchmark at the deployed U-Net's layer shapes.

The claim behind ops/pallas (SURVEY.md Phase 2: kernels for the reference's
hot blocks, pkg/segmentation_model.py:24-40,54-65) is checked empirically
here: for every 3x3 conv+BN+ReLU shape in the 256x256 inference forward,
plus the 1x1 head and the 2x2 stride-2 transpose conv, time the fused
Pallas kernel against the plain-XLA equivalent on the real chip, then time
the whole-net forward (auto-dispatched Pallas net vs Flax/XLA). Writes
PALLASBENCH.json -- the in-repo evidence for the per-shape dispatch
threshold in ops/pallas/unet_infer.py (PALLAS_MAX_ELEMS).

Same chained-scan timing as bench.py (see its docstring): K data-dependent
kernel applications inside one compiled ``lax.scan``, one host fetch, minus
the independently measured fetch round-trip. bf16 inputs / f32 accumulation,
matching serving.

Caveat: the per-shape chains need a shape-preserving feedback transform
(tile/slice) whose overhead rides on both sides of each comparison; at
sub-millisecond scales the per-shape ratios vary noticeably between runs.
Treat individual rows as indicative, the aggregate picture and the
``full_forward_b1_256`` row (the real dispatch-policy evidence, stable
across runs) as the conclusions.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

CHAIN = 100

# Every distinct (H, W, Cin, Cout) the deployed bilinear-variant forward
# runs through conv3x3_bn_relu at batch 1, 256x256 input
# (models/unet.py channel ladder 64..512, halved decoder mids).
CONV3X3_SHAPES = [
    (256, 256, 3, 64), (256, 256, 64, 64),
    (128, 128, 64, 128), (128, 128, 128, 128),
    (64, 64, 128, 256), (64, 64, 256, 256),
    (32, 32, 256, 512), (32, 32, 512, 512),
    (16, 16, 512, 512),
    (32, 32, 1024, 512), (32, 32, 512, 256),
    (64, 64, 512, 256), (64, 64, 256, 128),
    (128, 128, 256, 128), (128, 128, 128, 64),
    (256, 256, 128, 64),
]


def _roundtrip_ms() -> float:
    @jax.jit
    def trivial(x):
        return x + 1.0

    x = jnp.ones((8,))
    float(trivial(x)[0])
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        float(trivial(x)[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts) * 1e3)


def _roofline_fields(roof: dict, pallas_ms: float | None,
                     xla_ms: float | None) -> dict:
    """The per-shape roofline block every PALLASBENCH row carries:
    analytic flops + minimal HBM traffic (utils/flops.py), the
    compute-vs-bandwidth classification, and -- when a measurement is
    present -- percent of the roofline bound achieved (the chain's
    feedback-transform overhead rides on the measured time, so the
    percentages are conservative)."""
    out = {
        "flops": roof["flops"],
        "hbm_bytes": roof["bytes"],
        "roofline_ms": round(roof["bound_ms"], 4),
        "bound_by": roof["bound_by"],
    }
    if pallas_ms:
        out["pallas_pct_of_bound"] = round(
            100 * roof["bound_ms"] / pallas_ms, 1)
    if pallas_ms and xla_ms:
        out["best_pct_of_bound"] = round(
            100 * roof["bound_ms"] / min(pallas_ms, xla_ms), 1)
    return out


def _time_chain(fn, x0, rt_ms: float, reps: int = 3) -> float:
    """Per-application ms of ``fn`` chained CHAIN times (x must map to an
    output that can be fed back; callers wrap to keep shapes fixed)."""

    @jax.jit
    def chained(x):
        final, _ = lax.scan(lambda c, _: (fn(c), None), x, None, length=CHAIN)
        return final

    np.asarray(jax.block_until_ready(chained(x0)))  # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(chained(x0))
        best = min(best, time.perf_counter() - t0)
    if not np.isfinite(best) or best <= 0.0:
        # the BENCH_r05 failure mode: a wedged tunnel can "complete" the
        # fetch instantly -- recording that as a time would write 0.0 rows
        raise RuntimeError(
            f"non-positive chain time {best!r}s (tunnel wedged mid-run?)"
        )
    return max((best * 1e3 - rt_ms) / CHAIN, 1e-6)


def bench_conv3x3(rt_ms: float) -> list[dict]:
    from robotic_discovery_platform_tpu.ops.pallas import (
        conv3x3_bn_relu, conv3x3_bn_relu_xla)
    from robotic_discovery_platform_tpu.utils import flops as flops_lib

    rng = np.random.default_rng(0)
    rows = []
    for h, w, ci, co in CONV3X3_SHAPES:
        x = jnp.asarray(rng.normal(size=(1, h, w, ci)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(3, 3, ci, co)) * 0.1, jnp.float32)
        scale = jnp.ones((co,), jnp.float32)
        bias = jnp.zeros((co,), jnp.float32)
        # feed a Cin-slice of the output back in so the chain is
        # data-dependent but shape-stable
        reps_in = -(-ci // co)  # ceil

        def step(c, kernel=k, s=scale, b=bias, cin=ci, r=reps_in):
            y = conv3x3_bn_relu(c, kernel, s, b, relu=True)
            return jnp.tile(y, (1, 1, 1, r))[..., :cin].astype(jnp.bfloat16)

        def step_xla(c, kernel=k, s=scale, b=bias, cin=ci, r=reps_in):
            y = conv3x3_bn_relu_xla(c, kernel, s, b, relu=True)
            return jnp.tile(y, (1, 1, 1, r))[..., :cin].astype(jnp.bfloat16)

        t_pallas = _time_chain(step, x, rt_ms)
        t_xla = _time_chain(step_xla, x, rt_ms)
        # roofline: how close the better implementation runs to the chip's
        # compute/bandwidth bound for this shape (utils/flops.py; the
        # chain's feedback tile/slice overhead rides on the measured time,
        # so pct_of_bound is understated -- a conservative bound)
        roof = flops_lib.conv3x3_roofline_ms(h, w, ci, co)
        rows.append({
            "op": "conv3x3_bn_relu", "h": h, "w": w, "cin": ci, "cout": co,
            "pallas_ms": round(t_pallas, 4), "xla_ms": round(t_xla, 4),
            "speedup": round(t_xla / t_pallas, 3),
            **_roofline_fields(roof, t_pallas, t_xla),
        })
        print(f"# 3x3 {h}x{w} {ci}->{co}: pallas={t_pallas:.3f}ms "
              f"xla={t_xla:.3f}ms x{t_xla / t_pallas:.2f} "
              f"roof={roof['bound_ms']:.3f}ms ({roof['bound_by']})",
              file=sys.stderr)
    return rows


def bench_heads(rt_ms: float) -> list[dict]:
    from robotic_discovery_platform_tpu.ops.pallas import (
        conv1x1, conv1x1_xla, conv_transpose2x2, conv_transpose2x2_xla)
    from robotic_discovery_platform_tpu.utils import flops as flops_lib

    rng = np.random.default_rng(1)
    rows = []

    # 1x1 head at full resolution: 256x256, 64 -> 1 (OutConv). conv1x1
    # takes the [Cin, Cout] kernel (the [0, 0] slice of the HWIO tree, same
    # as unet_infer's call site).
    x = jnp.asarray(rng.normal(size=(1, 256, 256, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(64, 1)) * 0.1, jnp.float32)
    s, b = jnp.ones((1,), jnp.float32), jnp.zeros((1,), jnp.float32)

    def head(c):
        y = conv1x1(c, k, s, b)
        return (c + y.astype(jnp.bfloat16))  # broadcast dependency

    def head_xla(c):
        y = conv1x1_xla(c, k, s, b)
        return (c + y.astype(jnp.bfloat16))

    t_p, t_x = _time_chain(head, x, rt_ms), _time_chain(head_xla, x, rt_ms)
    rows.append({"op": "conv1x1", "h": 256, "w": 256, "cin": 64, "cout": 1,
                 "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
                 "speedup": round(t_x / t_p, 3),
                 **_roofline_fields(
                     flops_lib.conv1x1_roofline_ms(256, 256, 64, 1),
                     t_p, t_x)})
    print(f"# 1x1 head: pallas={t_p:.3f}ms xla={t_x:.3f}ms", file=sys.stderr)

    # transpose-conv decoder step (non-bilinear variant): 32x32 512 -> 256
    x = jnp.asarray(rng.normal(size=(1, 32, 32, 512)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(2, 2, 512, 256)) * 0.1, jnp.float32)
    bias = jnp.zeros((256,), jnp.float32)

    def tc(c):
        y = conv_transpose2x2(c, k, bias)  # [1,64,64,256]
        y = y.reshape(1, 32, 2, 32, 2, 256).mean((2, 4))  # back to 32x32
        return jnp.tile(y, (1, 1, 1, 2)).astype(jnp.bfloat16)

    def tc_xla(c):
        y = conv_transpose2x2_xla(c, k, bias)
        y = y.reshape(1, 32, 2, 32, 2, 256).mean((2, 4))
        return jnp.tile(y, (1, 1, 1, 2)).astype(jnp.bfloat16)

    t_p, t_x = _time_chain(tc, x, rt_ms), _time_chain(tc_xla, x, rt_ms)
    rows.append({"op": "conv_transpose2x2", "h": 32, "w": 32, "cin": 512,
                 "cout": 256, "pallas_ms": round(t_p, 4),
                 "xla_ms": round(t_x, 4), "speedup": round(t_x / t_p, 3),
                 **_roofline_fields(
                     flops_lib.conv_transpose2x2_roofline_ms(
                         32, 32, 512, 256),
                     t_p, t_x)})
    print(f"# 2x2^T: pallas={t_p:.3f}ms xla={t_x:.3f}ms", file=sys.stderr)
    return rows


def bench_geometry(rt_ms: float) -> list[dict]:
    """Fused geometry/B-spline kernels (ops/pallas/geometry.py) vs their
    XLA reference chains, at the deployed analyzer shapes: the 480x640
    deproject+edge-stats pass (stride 1 and the pooled stride-2 view) and
    the B-spline design/curvature stages (N = num_bins * max_per_bin =
    6400 edge budget, C = 16 control points, 100 curvature samples)."""
    import jax.numpy as jnp

    from robotic_discovery_platform_tpu.ops import bspline, geometry
    from robotic_discovery_platform_tpu.ops.pallas import (
        geometry as pgeom,
    )
    from robotic_discovery_platform_tpu.utils import flops as flops_lib
    from robotic_discovery_platform_tpu.utils.config import GeometryConfig

    rng = np.random.default_rng(3)
    rows = []
    cfg = GeometryConfig()
    big = jnp.float32(1e30)

    # deproject + edge stats: feed the z map back as depth (z = depth *
    # scale, so the chain is data-dependent and shape-stable); the tiny
    # tanh(stat) term keeps the reductions live on both sides.
    for stride in (1, 2):
        h, w = 480 // stride, 640 // stride
        mask = jnp.asarray(rng.random((h, w)) > 0.4, jnp.uint8)
        d0 = jnp.asarray(rng.random((h, w)) * 800 + 200, jnp.float32)
        fx = fy = jnp.float32(600.0)
        cx, cy = jnp.float32(w / 2), jnp.float32(h / 2)

        def step_pallas(d, stride=stride, mask=mask, fx=fx, fy=fy,
                        cx=cx, cy=cy):
            _, _, z, _, st = pgeom.deproject_edge_stats(
                mask, d, fx, fy, cx, cy, 0.001, stride=stride
            )
            return z * 1000.0 + jnp.tanh(st[0])

        def step_xla(d, stride=stride, mask=mask, fx=fx, fy=fy,
                     cx=cx, cy=cy):
            x, y, z, v = geometry.deproject(
                mask, d, fx, fy, cx, cy, 0.001, stride=stride
            )
            xs, ys, vf = x.reshape(-1), y.reshape(-1), v.reshape(-1)
            x_min = jnp.min(jnp.where(vf, xs, big))
            jnp.max(jnp.where(vf, xs, -big))
            return z * 1000.0 + jnp.tanh(x_min)

        t_p = _time_chain(step_pallas, d0, rt_ms)
        t_x = _time_chain(step_xla, d0, rt_ms)
        roof = flops_lib.deproject_roofline_ms(h, w)
        rows.append({
            "op": "deproject_edge_stats", "h": h, "w": w, "stride": stride,
            "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
            "speedup": round(t_x / t_p, 3),
            **_roofline_fields(roof, t_p, t_x),
        })
        print(f"# deproject {h}x{w} s{stride}: pallas={t_p:.3f}ms "
              f"xla={t_x:.3f}ms x{t_x / t_p:.2f}", file=sys.stderr)

    # B-spline design + curvature at the deployed fit shapes
    n, c = cfg.num_bins * cfg.max_per_bin, cfg.num_ctrl
    knots = bspline.clamped_uniform_knots(c, cfg.spline_degree)
    pts0 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    wts = jnp.asarray(rng.random(n) > 0.3, jnp.float32)

    def design_pallas(pts, wts=wts):
        u = bspline.chord_length_params(pts, wts)
        _, rhs = pgeom.bspline_design(
            pts, wts, u, pgeom.static_knots(knots), cfg.spline_degree
        )
        reps = -(-n // rhs.shape[0])
        return pts + 1e-3 * jnp.tanh(jnp.tile(rhs, (reps, 1))[:n])

    def design_xla(pts, wts=wts):
        u = bspline.chord_length_params(pts, wts)
        b = bspline.bspline_basis(u, knots, cfg.spline_degree)
        bw = b * wts[:, None]
        rhs = bspline._mm(bw.T, pts)
        bspline._mm(bw.T, b)
        reps = -(-n // rhs.shape[0])
        return pts + 1e-3 * jnp.tanh(jnp.tile(rhs, (reps, 1))[:n])

    t_p = _time_chain(design_pallas, pts0, rt_ms)
    t_x = _time_chain(design_xla, pts0, rt_ms)
    roof = flops_lib.bspline_design_roofline_ms(n, c)
    rows.append({
        "op": "bspline_design", "n": n, "c": c,
        "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
        "speedup": round(t_x / t_p, 3),
        **_roofline_fields(roof, t_p, t_x),
    })
    print(f"# bspline_design n{n} c{c}: pallas={t_p:.3f}ms "
          f"xla={t_x:.3f}ms x{t_x / t_p:.2f}", file=sys.stderr)

    ns = cfg.num_samples
    u_fine = jnp.linspace(0.0, 1.0, ns)
    ctrl0 = jnp.asarray(rng.normal(size=(c, 3)), jnp.float32)

    def curv_pallas(ctrl):
        kappa, _, r = pgeom.bspline_curvature(
            ctrl, u_fine, pgeom.static_knots(knots), cfg.spline_degree
        )
        return ctrl + 1e-3 * jnp.tanh(r[:c] + kappa[:c, None])

    def curv_xla(ctrl):
        kappa, _, r = bspline.curvature_profile(
            ctrl, knots, u_fine, cfg.spline_degree
        )
        return ctrl + 1e-3 * jnp.tanh(r[:c] + kappa[:c, None])

    t_p = _time_chain(curv_pallas, ctrl0, rt_ms)
    t_x = _time_chain(curv_xla, ctrl0, rt_ms)
    roof = flops_lib.bspline_curvature_roofline_ms(ns, c)
    rows.append({
        "op": "bspline_curvature", "n": ns, "c": c,
        "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
        "speedup": round(t_x / t_p, 3),
        **_roofline_fields(roof, t_p, t_x),
    })
    print(f"# bspline_curvature n{ns} c{c}: pallas={t_p:.3f}ms "
          f"xla={t_x:.3f}ms x{t_x / t_p:.2f}", file=sys.stderr)
    return rows


def bench_decode(rt_ms: float) -> list[dict]:
    """Split-JPEG decode stage (ops/pallas/decode.py + ops/pipeline.py)
    vs the XLA reference, at the serving frame shape (480x640 4:2:0).

    Two row families: the fused dequant+IDCT launch alone (Pallas kernel
    vs the XLA basis-matmul reference, both bitwise-identical so the race
    is pure schedule), and the whole ``decode_coef_batch`` stage
    (dequant+IDCT x3 planes + fancy upsample + color convert). The gate:
    the whole-stage roofline must classify as bandwidth-bound (``bound_by
    == "memory"``) -- on-chip decode rides the analyzer's HBM streams, it
    must not compete for MXU time -- and this section asserts that, so a
    flops.py regression that flips the classification fails the bench."""
    from robotic_discovery_platform_tpu.ops import pipeline as pipeline_lib
    from robotic_discovery_platform_tpu.ops.pallas import decode as pdecode
    from robotic_discovery_platform_tpu.utils import flops as flops_lib

    rng = np.random.default_rng(4)
    rows = []
    h, w = 480, 640
    ybh, ybw = h // 8, w // 8          # 60 x 80 luma blocks
    cbh, cbw = h // 16, w // 16        # 4:2:0 chroma grid

    # fused dequant+IDCT alone: [B, N, 64] coefficients through the two
    # basis matmuls; the output samples (0..255) level-shift back to a
    # coefficient-shaped int16 feed, so the chain is data-dependent and
    # shape-stable on both sides.
    for b in (1, 8):
        n = ybh * ybw
        coefs = jnp.asarray(
            rng.integers(-64, 64, (b, n, 64)), jnp.int16)
        q = jnp.asarray(rng.integers(2, 24, (b, 64)), jnp.uint16)

        def step_pallas(c, q=q):
            y = pdecode.dequant_idct(c, q, impl="pallas")
            return (y - 128).astype(jnp.int16)

        def step_xla(c, q=q):
            y = pdecode.dequant_idct(c, q, impl="xla")
            return (y - 128).astype(jnp.int16)

        t_p = _time_chain(step_pallas, coefs, rt_ms)
        t_x = _time_chain(step_xla, coefs, rt_ms)
        roof = flops_lib.jpeg_idct_roofline_ms(n, batch=b)
        rows.append({
            "op": "jpeg_dequant_idct", "b": b, "n_blocks": n,
            "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
            "speedup": round(t_x / t_p, 3),
            **_roofline_fields(roof, t_p, t_x),
        })
        print(f"# dequant_idct b{b} n{n}: pallas={t_p:.3f}ms "
              f"xla={t_x:.3f}ms x{t_x / t_p:.2f} "
              f"roof={roof['bound_ms']:.3f}ms ({roof['bound_by']})",
              file=sys.stderr)

    # whole decode stage: coefficients -> RGB. Feed the decoded luma
    # channel back through the inverse block assembly as the next luma
    # coefficient plane (chroma/quant ride as closed-over constants).
    b = 8
    ny, nc = ybh * ybw, cbh * cbw
    y0 = jnp.asarray(rng.integers(-64, 64, (b, ny, 64)), jnp.int16)
    cb0 = jnp.asarray(rng.integers(-32, 32, (b, nc, 64)), jnp.int16)
    cr0 = jnp.asarray(rng.integers(-32, 32, (b, nc, 64)), jnp.int16)
    qy = jnp.asarray(rng.integers(2, 24, (b, 64)), jnp.uint16)
    qc = jnp.asarray(rng.integers(2, 32, (b, 64)), jnp.uint16)

    def _decode_step(impl):
        def step(y):
            rgb = pipeline_lib.decode_coef_batch(
                y, cb0, cr0, qy, qc, height=h, width=w,
                subsampling="420", impl=impl)
            lum = rgb[..., 0].astype(jnp.int32) - 128
            blocks = lum.reshape(b, ybh, 8, ybw, 8).transpose(
                0, 1, 3, 2, 4).reshape(b, ny, 64)
            return blocks.astype(jnp.int16)
        return step

    t_p = _time_chain(_decode_step("pallas"), y0, rt_ms)
    t_x = _time_chain(_decode_step("xla"), y0, rt_ms)
    roof = flops_lib.jpeg_decode_roofline_ms(h, w, batch=b,
                                             subsampling="420")
    # the gate: on-chip decode must be bandwidth-bound at serving shapes
    assert roof["bound_by"] == "memory", (
        f"decode stage classified {roof['bound_by']!r}-bound at "
        f"{h}x{w} b{b}; the split-decode design requires it to ride "
        "the HBM streams (see utils/flops.jpeg_decode_roofline_ms)"
    )
    rows.append({
        "op": "decode_coef_batch", "b": b, "h": h, "w": w,
        "subsampling": "420",
        "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
        "speedup": round(t_x / t_p, 3),
        **_roofline_fields(roof, t_p, t_x),
    })
    print(f"# decode b{b} {h}x{w}: pallas={t_p:.3f}ms xla={t_x:.3f}ms "
          f"x{t_x / t_p:.2f} roof={roof['bound_ms']:.3f}ms "
          f"({roof['bound_by']})", file=sys.stderr)
    return rows


def bench_egress(rt_ms: float) -> list[dict]:
    """Egress mask bitpack (ops/pallas/pack.bitpack_mask) vs the XLA
    fallback at the serving mask shape (480x640) -- the device half of
    the one-fetch egress wire (serving/egress.py).

    Both backends run the same _pack_math arithmetic (results bitwise
    identical; tests/test_egress.py), so the race is pure schedule. The
    gate: the roofline must classify as bandwidth-bound (``bound_by ==
    "memory"``) -- packing is one HBM pass over the mask and must ride
    free under the analyzer's compute, the same contract the decode
    stage pins on the way in."""
    from robotic_discovery_platform_tpu.ops.pallas import pack as pack_lib
    from robotic_discovery_platform_tpu.utils import flops as flops_lib

    rng = np.random.default_rng(5)
    rows = []
    h, w = 480, 640
    wb = pack_lib.packed_row_bytes(w)
    for b in (1, 8):
        mask0 = jnp.asarray(rng.integers(0, 2, (b, h, w)), jnp.uint8)

        def step_for(impl, b=b):
            def step(m):
                p = pack_lib.bitpack_mask(m, impl=impl)
                # unpack in-graph back to a mask-shaped feed, so the
                # chain is data-dependent and shape-stable
                bits = (p[..., None] >> jnp.arange(7, -1, -1,
                                                   dtype=jnp.uint8)) & 1
                return bits.reshape(b, h, wb * 8)[..., :w]
            return step

        t_p = _time_chain(step_for("pallas"), mask0, rt_ms)
        t_x = _time_chain(step_for("xla"), mask0, rt_ms)
        roof = flops_lib.mask_bitpack_roofline_ms(h, w, batch=b)
        # the gate: packing must be bandwidth-bound at serving shapes
        assert roof["bound_by"] == "memory", (
            f"mask bitpack classified {roof['bound_by']!r}-bound at "
            f"{h}x{w} b{b}; the egress design requires one bandwidth-"
            "bound HBM pass (see utils/flops.mask_bitpack_roofline_ms)"
        )
        rows.append({
            "op": "mask_bitpack", "b": b, "h": h, "w": w,
            "pallas_ms": round(t_p, 4), "xla_ms": round(t_x, 4),
            "speedup": round(t_x / t_p, 3),
            **_roofline_fields(roof, t_p, t_x),
        })
        print(f"# mask_bitpack b{b} {h}x{w}: pallas={t_p:.3f}ms "
              f"xla={t_x:.3f}ms x{t_x / t_p:.2f} "
              f"roof={roof['bound_ms']:.3f}ms ({roof['bound_by']})",
              file=sys.stderr)
    return rows


def bench_full_forward(rt_ms: float) -> dict:
    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet
    from robotic_discovery_platform_tpu.ops.pallas import make_pallas_unet
    from robotic_discovery_platform_tpu.utils.config import ModelConfig

    model = build_unet(ModelConfig())
    variables = init_unet(model, jax.random.key(0))
    pnet = make_pallas_unet(model, variables)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.uniform(size=(1, 256, 256, 3)), jnp.bfloat16)

    def flax_fwd(c):
        y = model.apply(variables, c, train=False)  # [1,256,256,1]
        return jnp.concatenate([c[..., :2], y.astype(jnp.bfloat16)], -1)

    def pallas_fwd(c):
        y = pnet(c)
        return jnp.concatenate([c[..., :2], y.astype(jnp.bfloat16)], -1)

    t_flax = _time_chain(flax_fwd, x, rt_ms)
    t_pallas = _time_chain(pallas_fwd, x, rt_ms)
    print(f"# full forward 256x256: pallas-auto={t_pallas:.3f}ms "
          f"flax/xla={t_flax:.3f}ms", file=sys.stderr)
    return {"flax_xla_ms": round(t_flax, 4),
            "pallas_auto_ms": round(t_pallas, 4),
            "speedup": round(t_flax / t_pallas, 3)}


def autotune(rt_ms: float, focus=None) -> dict:
    """Sweep every budget-feasible (tile_h, tile_co, dx_major) per conv
    shape (ops/pallas/tuning.candidates) with the chained-scan timing; a
    config is recorded as an override only when it beats BOTH the analytic
    heuristic and a re-measured XLA anchor by >3% (otherwise the entry is
    dropped so the uniform-dispatch decision stays evidence-based). Writes
    PALLAS_TUNE.json, which unet_infer's dispatch consults per launch."""
    from robotic_discovery_platform_tpu.ops.pallas import (
        conv3x3_bn_relu, conv3x3_bn_relu_xla, tuning)

    rng = np.random.default_rng(0)
    entries, report = {}, []
    shapes = focus or CONV3X3_SHAPES
    for h, w, ci, co in shapes:
        x = jnp.asarray(rng.normal(size=(1, h, w, ci)), jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(3, 3, ci, co)) * 0.1, jnp.float32)
        scale = jnp.ones((co,), jnp.float32)
        bias = jnp.zeros((co,), jnp.float32)
        reps_in = -(-ci // co)

        def step_for(tiling, kernel=k, s=scale, b=bias, cin=ci, r=reps_in):
            def step(c):
                y = conv3x3_bn_relu(c, kernel, s, b, relu=True,
                                    tiling=tiling)
                return jnp.tile(y, (1, 1, 1, r))[..., :cin].astype(
                    jnp.bfloat16)
            return step

        def step_xla(c, kernel=k, s=scale, b=bias, cin=ci, r=reps_in):
            y = conv3x3_bn_relu_xla(c, kernel, s, b, relu=True)
            return jnp.tile(y, (1, 1, 1, r))[..., :cin].astype(jnp.bfloat16)

        cands = tuning.candidates(h, w, ci, co)
        t_heur = _time_chain(step_for(None), x, rt_ms)
        t_xla = _time_chain(step_xla, x, rt_ms)
        best_t, best_cfg = t_heur, cands[0]
        for cand in cands[1:]:
            try:
                t = _time_chain(step_for(cand), x, rt_ms)
            except Exception as exc:  # infeasible config (compile/VMEM)
                print(f"#   {h}x{w} {ci}->{co} {cand}: {type(exc).__name__}",
                      file=sys.stderr)
                continue
            if t < best_t:
                best_t, best_cfg = t, cand
        improved = best_t < t_heur * 0.97 and best_t < t_xla * 0.97
        row = {
            "h": h, "w": w, "cin": ci, "cout": co,
            "heuristic": {"cfg": list(cands[0]),
                          "ms": round(t_heur, 4)},
            "best": {"cfg": list(best_cfg), "ms": round(best_t, 4)},
            "xla_ms": round(t_xla, 4),
            "tuned": bool(improved),
            "n_candidates": len(cands),
        }
        report.append(row)
        print(f"# tune {h}x{w} {ci}->{co}: heur={t_heur:.3f}ms "
              f"best={best_t:.3f}ms ({best_cfg}) xla={t_xla:.3f}ms "
              f"{'TUNED' if improved else 'keep-heuristic'}",
              file=sys.stderr)
        if improved:
            th, tc, dxm = best_cfg
            entries[tuning.key(h, w, ci, co)] = {
                "tile_h": th, "tile_co": tc, "dx_major": dxm,
                "ms": round(best_t, 4),
                "heuristic_ms": round(t_heur, 4),
                "xla_ms": round(t_xla, 4),
            }
    meta = {
        "device": jax.devices()[0].device_kind,
        "chain": CHAIN,
        "roundtrip_ms": round(rt_ms, 1),
        "criterion": ">3% faster than heuristic AND xla",
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if focus:
        # focused re-tune: merge over the existing table -- replace every
        # swept shape's entry (tuned or dropped), keep the rest
        prev = dict(tuning._table())
        for h, w, ci, co in shapes:
            prev.pop(tuning.key(h, w, ci, co), None)
        prev.update(entries)
        entries = prev
    path = tuning.save_entries(entries, meta)
    print(f"# wrote {path} with {len(entries)} overrides", file=sys.stderr)
    return {"entries": len(entries), "report": report}


def _section(name: str, fn, *args):
    """Run one bench section, degrading a mid-run tunnel failure into a
    structured ``{"skipped": "tunnel"}`` marker instead of losing the
    whole artifact (the BENCH_r04 crash mode): sections that already
    measured stay in PALLASBENCH.json."""
    try:
        return fn(*args)
    except Exception as exc:  # noqa: BLE001 -- structured artifact
        print(f"# section {name} skipped: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return {"skipped": "tunnel",
                "detail": f"{type(exc).__name__}: {exc}"[-400:]}


def main() -> None:
    # honor an inherited JAX_PLATFORMS pin BEFORE the backend query below:
    # without it, the query on this image enters TPU-tunnel discovery even
    # when the caller asked for CPU, and a wedged tunnel hangs the guard
    # instead of letting it exit (utils/platforms.py)
    from robotic_discovery_platform_tpu.utils.platforms import (
        apply_env_platform,
    )

    apply_env_platform()
    if jax.default_backend() != "tpu":
        print("PALLASBENCH needs the TPU backend (kernels interpret-only "
              "on CPU)", file=sys.stderr)
        sys.exit(1)
    # short-timeout warm-up probe in a killable subprocess BEFORE the
    # measured section: backend bring-up on a wedged tunnel HANGS rather
    # than raising (the BENCH_r04/r05 artifacts), so prove the chip
    # answers a trivial op at all -- and emit a structured skipped row
    # instead of crashing or recording 0.0 when it does not.
    import bench as bench_lib

    try:
        bench_lib._probe_backend()
    except Exception as exc:  # noqa: BLE001 -- terminal, structured
        print(json.dumps({
            "skipped": "tunnel",
            "error": "tpu_unavailable",
            "detail": str(exc)[-800:],
        }))
        return
    rt_ms = _roundtrip_ms()
    if len(sys.argv) > 1 and sys.argv[1] == "autotune":
        # optional shape filter: "autotune 32" tunes only 32x32 layers
        focus = None
        if len(sys.argv) > 2:
            want = int(sys.argv[2])
            focus = [s for s in CONV3X3_SHAPES if s[0] == want]
            if not focus:
                sys.exit(f"no conv shape with H={want} "
                         f"(have {sorted({s[0] for s in CONV3X3_SHAPES})})")
        out = autotune(rt_ms, focus)
        print(json.dumps({"autotuned_overrides": out["entries"]}))
        return
    result = {
        "backend": jax.default_backend(),
        "device": jax.devices()[0].device_kind,
        "chain": CHAIN,
        "roundtrip_ms": round(rt_ms, 1),
        "dtype": "bfloat16 in / f32 accumulate",
        "conv3x3": _section("conv3x3", bench_conv3x3, rt_ms),
        "heads": _section("heads", bench_heads, rt_ms),
        "geometry": _section("geometry", bench_geometry, rt_ms),
        "decode": _section("decode", bench_decode, rt_ms),
        "egress": _section("egress", bench_egress, rt_ms),
        "full_forward_b1_256": _section(
            "full_forward", bench_full_forward, rt_ms),
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out = REPO / "PALLASBENCH.json"
    out.write_text(json.dumps(result, indent=2) + "\n")
    print(json.dumps({"wrote": str(out),
                      "full_forward": result["full_forward_b1_256"]}))


if __name__ == "__main__":
    main()
