#!/usr/bin/env python
"""CI scrape smoke: boot the real gRPC server with the metrics endpoint
enabled, stream a few synthetic frames through the real client, scrape
``GET /metrics`` (with curl when available, so the job exercises the same
path an external Prometheus would), and assert the required metric
families are present with live samples.

Run: ``env JAX_PLATFORMS=cpu RDP_METRICS_PORT=9464 python
tools/metrics_smoke.py`` (any port; ``-1`` binds an ephemeral one).
Exit code 0 on success, 1 with a diagnostic on any missing family.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

# runnable straight from a checkout, with or without `pip install -e .`
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# the central registries (observability/families.py, events.py) are the
# source of truth for every family/kind name -- never retype the strings
from robotic_discovery_platform_tpu.observability import (  # noqa: E402
    events,
    families,
)

REQUIRED_FAMILIES = (
    families.FRAMES,
    families.STAGE_LATENCY,
    families.BATCH_QUEUE_DEPTH,
    families.BREAKER_STATE,
    # streaming-quantile summaries + SLO families (PR 6)
    families.STAGE_LATENCY_SUMMARY,
    families.FRAME_LATENCY_SUMMARY,
    families.SLO_OBJECTIVE,
    families.SLO_VIOLATIONS,
    families.SLO_BURN,
    # drift observability (PR 9)
    families.DRIFT_SCORE,
    families.DRIFT_RECOMMENDATIONS,
    families.DRIFT_REFERENCE_AGE,
    families.MODEL_CONFIDENCE_MARGIN,
    families.METRICS_ROWS_SKIPPED,
    # host-path ingest (PR 12)
    families.DECODE_SECONDS,
    families.DECODE_QUEUE_DEPTH,
    families.GEOMETRY_CACHE_HITS,
    families.GEOMETRY_CACHE_MISSES,
    families.HOST_STAGE_SPLIT,
    # host-path egress (PR 20)
    families.ENCODE_SECONDS,
    families.EGRESS_BYTES,
    families.EGRESS_QUEUE_DEPTH,
    families.EGRESS_POOL_SIZE,
    # model zoo (PR 14)
    families.ZOO_MODELS,
    families.MODEL_DISPATCHES,
    families.MODEL_ARRIVAL_RATE,
    # fleet observability plane (PR 15): the journal counts events on
    # every server; the federation/roll-up families are declared
    # everywhere and populated on the front-end's /federate renders
    families.JOURNAL_EVENTS,
    families.JOURNAL_DROPPED,
    families.REPLICA_UP,
    families.REPLICA_SCRAPE_AGE,
    families.FLEET_BURN,
    families.FLEET_FRAMES,
    families.FLEET_MODEL_ARRIVAL_RATE,
)
#: every /debug endpoint the 404 help text must enumerate
DEBUG_ENDPOINTS = (
    "/metrics",
    "/federate",
    "/debug/spans",
    "/debug/tracez",
    "/debug/trace",
    "/debug/events",
    "/debug/drift",
    "/debug/rollout",
    "/debug/zoo",
    "/debug/profile",
)
#: the signals the online drift monitor must expose in /debug/drift
DRIFT_SIGNALS = (
    "mask_coverage",
    "mean_curvature",
    "max_curvature",
    "depth_valid_fraction",
    "confidence_margin",
)
REQUIRED_SAMPLES = (
    f'{families.STAGE_LATENCY}_count{{stage="total"}}',
    families.FRAMES + '{status="',
    families.BREAKER_STATE + '{breaker="registry:',
    f'{families.STAGE_LATENCY_SUMMARY}{{stage="total",quantile="0.5"}}',
    f'{families.FRAME_LATENCY_SUMMARY}{{quantile="0.99"}}',
    f'{families.SLO_OBJECTIVE}{{objective="e2e"}}',
    # the burn family carries a model label now (model="" = aggregate)
    f'{families.SLO_BURN}{{objective="e2e",model=""}}',
    # per-model labels on the hot families (multi-tenancy): every frame
    # is attributed to the zoo model that served it -- "seg" is the
    # default binary segmenter even on a single-model server
    f"{families.ZOO_MODELS} 1",
    # every streamed frame observes its confidence margin
    f"{families.MODEL_CONFIDENCE_MARGIN}_count",
    # host-path ingest: every frame's decode work is measured and the
    # steady-state stream hits the geometry cache after its first frame
    f'{families.DECODE_SECONDS}_count{{format="encoded"}}',
    f'{families.HOST_STAGE_SPLIT}_count{{stage="decode"}}',
    # host-path egress: every response mask encode is measured by format
    # and the completer's packed fetch splits out the D2H leg
    f'{families.HOST_STAGE_SPLIT}_count{{stage="encode"}}',
    f'{families.HOST_STAGE_SPLIT}_count{{stage="d2h"}}',
    f'{families.ENCODE_SECONDS}_count{{format="png"}}',
    f'{families.EGRESS_BYTES}{{format="png"}}',
    # the journal records readiness as a structured event on every boot
    f'{families.JOURNAL_EVENTS}{{kind="{events.SERVER_READY}"}}',
)


def quantile_values(text: str, family: str) -> dict[str, float]:
    """{quantile: value} samples of an unlabeled summary family."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith(f'{family}{{quantile="'):
            key, value = line.rsplit(" ", 1)
            out[key.split('"')[1]] = float(value)
    return out


def scrape(port: int) -> str:
    url = f"http://127.0.0.1:{port}/metrics"
    curl = shutil.which("curl")
    if curl:
        return subprocess.run(
            [curl, "-sf", url], check=True, capture_output=True, text=True,
            timeout=30,
        ).stdout
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read().decode()


def main() -> int:
    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import jax

    from robotic_discovery_platform_tpu import tracking
    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.models.unet import (
        build_unet,
        init_unet,
    )
    from robotic_discovery_platform_tpu.serving import client as client_lib
    from robotic_discovery_platform_tpu.serving import server as server_lib
    from robotic_discovery_platform_tpu.utils.config import (
        ClientConfig,
        ModelConfig,
        ServerConfig,
    )

    tmp = Path(tempfile.mkdtemp(prefix="rdp-metrics-smoke-"))
    uri = f"file:{tmp}/mlruns"
    tracking.set_tracking_uri(uri)
    tracking.set_experiment("Actuator Segmentation")
    mcfg = ModelConfig(base_features=8, compute_dtype="float32")
    model = build_unet(mcfg)
    variables = init_unet(model, jax.random.key(0), img_size=64)
    with tracking.start_run():
        version = tracking.log_model(
            variables, mcfg, registered_model_name="Actuator-Segmenter"
        )
    tracking.Client().set_registered_model_alias(
        "Actuator-Segmenter", "staging", version
    )

    cfg = ServerConfig(
        address="localhost:0",
        tracking_uri=uri,
        metrics_csv=str(tmp / "metrics.csv"),
        metrics_flush_every=1,
        calibration_path=str(tmp / "missing.npz"),
        metrics_port=-1,  # RDP_METRICS_PORT (set by CI) overrides this
        slo_ms=250.0,  # SLO tracking on, so the rdp_slo_* families render
        # micro-batching on, so the dispatcher completer's packed-egress
        # fetch renders the stage="d2h" host-split sample
        batch_window_ms=15.0,
        max_batch=4,
    )
    server, servicer = server_lib.build_server(cfg)
    port = server.add_insecure_port("localhost:0")
    server.start()
    try:
        if servicer.metrics_server is None:
            print("FAIL: metrics server did not start (set "
                  "RDP_METRICS_PORT or ServerConfig.metrics_port)")
            return 1
        client_lib.run_client(
            ClientConfig(server_address=f"localhost:{port}",
                         calibration_path="none.npz"),
            source=SyntheticSource(width=160, height=120, seed=1,
                                   n_frames=4),
            max_frames=4,
        )
        text = scrape(servicer.metrics_server.port)
        # /debug/drift must serve parseable JSON listing every configured
        # drift signal (the monitor is still self-baselining after 4
        # frames; tools/drift_smoke.py exercises the full scoring path)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{servicer.metrics_server.port}/debug/drift",
            timeout=30,
        ) as resp:
            drift_payload = json.loads(resp.read().decode())
        # every decoded frame records an ingest timeline whose "decode"
        # span joins the dispatch timelines at /debug/spans
        with urllib.request.urlopen(
            f"http://127.0.0.1:{servicer.metrics_server.port}/debug/spans",
            timeout=30,
        ) as resp:
            spans_payload = json.loads(resp.read().decode())
        # the structured event journal tails from a cursor; a booted
        # server has at least its server.ready event
        with urllib.request.urlopen(
            f"http://127.0.0.1:{servicer.metrics_server.port}"
            "/debug/events?since=0",
            timeout=30,
        ) as resp:
            events_payload = json.loads(resp.read().decode())
        # the 404 help text enumerates the grown /debug surface
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{servicer.metrics_server.port}/nope",
                timeout=30,
            )
            help_text = ""
        except urllib.error.HTTPError as err:
            help_text = err.read().decode()
    finally:
        server.stop(grace=None)
        servicer.close()

    event_kinds = [e.get("kind") for e in events_payload.get("events", [])]
    if events.SERVER_READY not in event_kinds:
        print(f"FAIL: /debug/events holds no {events.SERVER_READY} event "
              f"(kinds: {event_kinds})")
        return 1
    if events_payload.get("next_cursor", 0) < 1:
        print(f"FAIL: /debug/events cursor never advanced: "
              f"{events_payload}")
        return 1
    missing_endpoints = [e for e in DEBUG_ENDPOINTS if e not in help_text]
    if missing_endpoints:
        print(f"FAIL: 404 help text is missing endpoints "
              f"{missing_endpoints}: {help_text!r}")
        return 1

    decode_spans = [
        s for t in spans_payload.get("recent", [])
        for s in t.get("spans", []) if s.get("name") == "decode"
    ]
    if not decode_spans:
        print("FAIL: no 'decode' span in /debug/spans timelines")
        return 1

    if not drift_payload.get("enabled"):
        print(f"FAIL: /debug/drift reports disabled: {drift_payload}")
        return 1
    missing_signals = [s for s in DRIFT_SIGNALS
                       if s not in drift_payload.get("signals", {})]
    if missing_signals:
        print(f"FAIL: /debug/drift is missing signals {missing_signals}")
        print(json.dumps(drift_payload, indent=1)[:2000])
        return 1

    missing = [f for f in REQUIRED_FAMILIES if f"# TYPE {f} " not in text]
    missing += [s for s in REQUIRED_SAMPLES if s not in text]
    # per-model frame attribution: every rdp_frames_total sample names
    # the serving zoo model (default = "seg")
    frame_lines = [ln for ln in text.splitlines()
                   if ln.startswith(families.FRAMES + "{")]
    if not frame_lines:
        missing.append(families.FRAMES + "{...} samples")
    elif not all('model="' in ln for ln in frame_lines):
        missing.append(
            f'model="..." label on every {families.FRAMES} sample')
    if missing:
        print("FAIL: /metrics is missing:")
        for m in missing:
            print(f"  {m}")
        print("---- scraped payload ----")
        print(text)
        return 1
    # summary quantiles must be structurally monotone: exposition clamps
    # the independent P^2 estimators to non-decreasing order
    q = quantile_values(text, families.FRAME_LATENCY_SUMMARY)
    ladder = [q[k] for k in ("0.5", "0.95", "0.99", "0.999")]
    if ladder != sorted(ladder) or not all(v > 0 for v in ladder):
        print(f"FAIL: frame-latency quantiles not positive-monotone: {q}")
        return 1
    n_lines = len(text.strip().splitlines())
    print(f"OK: scraped {n_lines} exposition lines; all "
          f"{len(REQUIRED_FAMILIES)} required families present; "
          f"p50={ladder[0]*1e3:.1f}ms <= p99.9={ladder[-1]*1e3:.1f}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
