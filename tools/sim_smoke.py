#!/usr/bin/env python
"""CI smoke for the fleet simulator: a 1000-replica, multi-hour drill.

Simulates two virtual hours of diurnal traffic against a 1000-replica,
3-front-end fleet -- the REAL routers, registries, breakers, controllers
and gossip, only the device modeled -- with correlated faults scripted
mid-run:

- t+30min: two of three front-ends SIGKILLed at once (the quorum-loss
  shape); restarted 10 virtual minutes later with EMPTY lease tables,
  recovering through the boot-time gossip seed.
- t+60min: 20 replicas killed in one instant (a rack loss), restarted
  10 minutes later.

Asserts the run is deterministic (two runs, byte-identical event logs,
on a short prefix window), completes under the CPU budget, recovers to
full membership, and keeps the violation rate bounded. Exits non-zero on any
failure; CI runs it with RDP_LOCKCHECK=strict so every lock the real
objects take under the sim is discipline-checked too.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from robotic_discovery_platform_tpu.sim import workload  # noqa: E402
from robotic_discovery_platform_tpu.sim.cluster import (  # noqa: E402
    SimConfig,
    SimFleet,
)
from robotic_discovery_platform_tpu.sim.engine import Engine  # noqa: E402
from robotic_discovery_platform_tpu.sim.model import (  # noqa: E402
    ServiceTimeModel,
)
from robotic_discovery_platform_tpu.sim.scenario import (  # noqa: E402
    Scenario,
)


def build(seed: int, n_replicas: int, duration_s: float):
    try:
        service = ServiceTimeModel.fit_loadbench()
    except (OSError, ValueError):
        service = ServiceTimeModel.synthetic()
    eng = Engine(seed=seed)
    cfg = SimConfig(
        n_replicas=n_replicas, n_frontends=3,
        streams=2 * n_replicas,
        fleet_poll_s=30.0, gossip_poll_s=30.0,
        controller_tick_s=15.0, renew_every_s=30.0, lease_ttl_s=90.0)
    fleet = SimFleet(cfg, eng, service=service)
    t_fe = duration_s * 0.25
    t_rep = duration_s * 0.5
    scenario = (Scenario("ci-smoke")
                .kill_frontend(t_fe, 0)
                .kill_frontend(t_fe + 5.0, 1)
                .restart_frontend(t_fe + 600.0, 0)
                .restart_frontend(t_fe + 600.0, 1)
                .kill_replicas(t_rep, 20)
                .restart_replicas(t_rep + 600.0, 20))
    sched = workload.diurnal(15.0, 80.0, duration_s / 2.0, duration_s,
                             eng.rng, models=("seg", "aux"))
    return fleet, sched, scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=1000)
    ap.add_argument("--duration-s", type=float, default=7200.0,
                    help="virtual seconds (default: two hours)")
    ap.add_argument("--budget-s", type=float, default=60.0,
                    help="wall-clock CPU budget for the main run")
    ap.add_argument("--determinism-window-s", type=float, default=120.0,
                    help="virtual seconds for the two-run determinism "
                         "check (kept short; the main run covers scale)")
    ap.add_argument("--max-violation-rate", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=20260807)
    args = ap.parse_args(argv)
    logging.disable(logging.WARNING)  # membership chatter at 1000 replicas

    failures: list[str] = []

    # determinism first, on a short window: byte-identical logs
    def short_run() -> str:
        fleet, sched, scenario = build(args.seed, 50,
                                       args.determinism_window_s)
        res = fleet.run([a for a in sched
                         if a[0] < args.determinism_window_s],
                        args.determinism_window_s, scenario=scenario)
        return res.log_text

    if short_run() != short_run():
        failures.append("determinism: two same-seed runs diverged")

    t0 = time.time()
    fleet, sched, scenario = build(args.seed, args.replicas,
                                   args.duration_s)
    res = fleet.run(sched, args.duration_s, scenario=scenario)
    wall = time.time() - t0

    row = res.rows["__all__"]
    summary = {
        "replicas": args.replicas,
        "virtual_s": args.duration_s,
        "wall_s": round(wall, 2),
        "speedup": round(args.duration_s / wall, 1),
        "events": res.counters["events_run"],
        "arrivals": row["arrivals"],
        "errors": row["errors"],
        "p50_ms": row["p50_ms"],
        "p99_ms": row["p99_ms"],
        "violation_rate": row["violation_rate"],
        "replicas_live": res.counters["replicas_live"],
        "leases_active": res.counters["leases_active"],
    }
    print(json.dumps(summary, indent=2))

    if wall > args.budget_s:
        failures.append(f"CPU budget: {wall:.1f}s > {args.budget_s}s")
    if res.counters["replicas_live"] != args.replicas:
        failures.append(
            f"recovery: {res.counters['replicas_live']} live replicas "
            f"!= {args.replicas}")
    if res.counters["leases_active"] != args.replicas:
        failures.append(
            f"recovery: {res.counters['leases_active']} active leases "
            f"!= {args.replicas} (front-end restarts did not re-adopt)")
    if row["violation_rate"] > args.max_violation_rate:
        failures.append(
            f"violation rate {row['violation_rate']} > "
            f"{args.max_violation_rate}")
    if row["arrivals"] == 0:
        failures.append("no arrivals simulated")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print(f"sim-smoke: {'FAILED' if failures else 'OK'} "
          f"({args.duration_s / 3600:.1f} virtual hours, "
          f"{args.replicas} replicas, {wall:.1f}s wall)",
          file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
