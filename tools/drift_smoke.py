#!/usr/bin/env python
"""CI drift smoke: the full online drift-signal path, end to end.

Boots the real gRPC server (drift monitor self-baselining, metrics
endpoint up), streams **nominal** synthetic frames through the real client
and asserts every drift score stays under threshold with zero
recommendations; then streams **distribution-shifted** frames (darkened
images + degraded depth -- the input-shift scenario) and asserts:

- per-signal ``rdp_drift_score`` rises above the PSI threshold,
- exactly ONE sustained retrain recommendation fires (hysteresis gates
  flapping: more shifted traffic must not fire a second one),
- the recommendation is counted (``rdp_drift_recommendations_total``),
  pinned in the flight recorder (``/debug/spans``), and visible in
  ``GET /debug/drift``,
- the OFFLINE detector (monitoring/drift.py) reaches the same verdict
  from the same run's metrics CSV -- the two paths share their scoring.

The served model's segmentation head is scaled/biased so its mask
coverage is genuinely brightness-sensitive (a random init saturates to
empty masks, which would hide the prediction-shift signals).

Run: ``env JAX_PLATFORMS=cpu python tools/drift_smoke.py``. Exit 0 on
success, 1 with a diagnostic.
"""

from __future__ import annotations

import json
import sys
import tempfile
import urllib.request
from pathlib import Path

import numpy as np

# runnable straight from a checkout, with or without `pip install -e .`
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

H, W = 120, 160
BASELINE_FRAMES = 20
NOMINAL_FRAMES = 56  # 20 self-baseline + 36 scored live frames
SHIFT_FRAMES = 64
EXTRA_SHIFT_FRAMES = 32  # hysteresis leg: must NOT fire a second rec


class DriftSource:
    """Synthetic camera whose distribution can be shifted mid-run:
    ``shifted=True`` darkens the scene to 25% brightness and zeroes every
    other depth row (sensor degradation)."""

    def __init__(self, seed: int, n_frames: int, shifted: bool):
        self.seed, self.n_frames, self.shifted = seed, n_frames, shifted
        self._rng = np.random.default_rng(seed)
        self._count = 0

    def start(self) -> None:
        self._rng = np.random.default_rng(self.seed)
        self._count = 0

    def stop(self) -> None:
        pass

    @property
    def depth_scale(self) -> float:
        return 0.001

    def intrinsics(self) -> np.ndarray:
        f = 0.94 * W
        return np.array([[f, 0, W / 2], [0, f, H / 2], [0, 0, 1]],
                        np.float64)

    def get_frames(self):
        from robotic_discovery_platform_tpu.training.synthetic import (
            render_scene,
        )

        if self._count >= self.n_frames:
            return None, None
        self._count += 1
        img_rgb, _, depth = render_scene(self._rng, H, W)
        if self.shifted:
            img_rgb = (img_rgb.astype(np.float32) * 0.25).astype(np.uint8)
            depth = depth.copy()
            depth[::2] = 0
        return img_rgb[..., ::-1].copy(), depth  # BGR like a real camera


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as resp:
        return json.loads(resp.read().decode())


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30
    ) as resp:
        return resp.read().decode()


def _fail(msg: str, payload=None) -> int:
    print(f"FAIL: {msg}")
    if payload is not None:
        print(json.dumps(payload, indent=1)[:4000])
    return 1


def main() -> int:
    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import copy

    import jax
    from flax.core import unfreeze

    from robotic_discovery_platform_tpu import tracking
    from robotic_discovery_platform_tpu.models.unet import (
        build_unet,
        init_unet,
    )
    from robotic_discovery_platform_tpu.monitoring.drift import analyze_drift
    from robotic_discovery_platform_tpu.serving import client as client_lib
    from robotic_discovery_platform_tpu.serving import server as server_lib
    from robotic_discovery_platform_tpu.utils.config import (
        ClientConfig,
        DriftConfig,
        ModelConfig,
        ServerConfig,
    )

    tmp = Path(tempfile.mkdtemp(prefix="rdp-drift-smoke-"))
    uri = f"file:{tmp}/mlruns"
    tracking.set_tracking_uri(uri)
    tracking.set_experiment("Actuator Segmentation")
    mcfg = ModelConfig(base_features=8, compute_dtype="float32")
    model = build_unet(mcfg)
    variables = unfreeze(
        jax.device_get(init_unet(model, jax.random.key(0), img_size=64))
    )
    # brightness-sensitive head: logits straddle the 0.5 threshold, so
    # darkening the input genuinely moves mask coverage AND the
    # confidence margin (a raw random init saturates to empty masks)
    v = copy.deepcopy(variables)
    v["params"]["Conv_0"]["kernel"] = (
        np.asarray(v["params"]["Conv_0"]["kernel"]) * 40.0
    )
    v["params"]["Conv_0"]["bias"] = np.full((1,), 0.5, np.float32)
    with tracking.start_run():
        version = tracking.log_model(
            v, mcfg, registered_model_name="Actuator-Segmenter"
        )
    tracking.Client().set_registered_model_alias(
        "Actuator-Segmenter", "staging", version
    )

    csv = tmp / "metrics.csv"
    cfg = ServerConfig(
        address="localhost:0",
        tracking_uri=uri,
        model_img_size=64,
        metrics_csv=str(csv),
        metrics_flush_every=1,
        calibration_path=str(tmp / "missing.npz"),
        metrics_port=-1,  # ephemeral /metrics + /debug/* endpoint
        reload_poll_s=0.0,
        # fast drift knobs for a short smoke: small self-baseline, tight
        # scoring stride, sub-second sustain, long cooldown (so a second
        # recommendation inside this run can only mean broken hysteresis)
        drift_baseline_frames=BASELINE_FRAMES,
        drift_window=64,
        drift_score_every=8,
        drift_psi_threshold=0.25,
        drift_sustain_s=0.2,
        drift_cooldown_s=600.0,
    )
    server, servicer = server_lib.build_server(cfg)
    grpc_port = server.add_insecure_port("localhost:0")
    server.start()
    try:
        if servicer.metrics_server is None:
            return _fail("metrics server did not start")
        port = servicer.metrics_server.port
        ccfg = ClientConfig(server_address=f"localhost:{grpc_port}",
                            calibration_path="none.npz")

        # -- phase 1: nominal traffic --------------------------------------
        client_lib.run_client(
            ccfg, source=DriftSource(seed=1, n_frames=NOMINAL_FRAMES,
                                     shifted=False),
            max_frames=NOMINAL_FRAMES,
        )
        snap = _get_json(port, "/debug/drift")
        if not snap.get("enabled") or snap.get("state") != "scoring":
            return _fail("monitor not scoring after nominal phase", snap)
        scored = {name: s for name, s in snap["signals"].items()
                  if s["psi"] is not None}
        if not scored:
            return _fail("no signal was scored in the nominal phase", snap)
        hot = {n: (s["psi"], s["noise_floor"]) for n, s in scored.items()
               if s["above_threshold"]}
        if hot:
            return _fail(f"nominal traffic flagged over threshold: {hot}",
                         snap)
        if snap["recommendations"]["count"] != 0:
            return _fail("recommendation fired on nominal traffic", snap)
        print(f"nominal ok: {len(scored)} signals scored, none above "
              f"threshold+floor (max psi "
              f"{max(s['psi'] for s in scored.values()):.3f}), "
              "0 recommendations")

        # -- phase 2: shifted traffic --------------------------------------
        client_lib.run_client(
            ccfg, source=DriftSource(seed=2, n_frames=SHIFT_FRAMES,
                                     shifted=True),
            max_frames=SHIFT_FRAMES,
        )
        snap = _get_json(port, "/debug/drift")
        drifted = {n: s["psi"] for n, s in snap["signals"].items()
                   if s["above_threshold"]}
        if not drifted:
            return _fail("no signal crossed the PSI threshold under "
                         "shifted traffic", snap)
        if snap["recommendations"]["count"] != 1:
            return _fail(
                f"expected exactly 1 recommendation, got "
                f"{snap['recommendations']['count']}", snap)
        rec = snap["recommendations"]["last"]
        if not rec or not rec["signals"]:
            return _fail("recommendation carries no signals", snap)
        print(f"shift ok: drifted={ {k: round(v, 3) for k, v in drifted.items()} }, "
              f"1 recommendation on {rec['signals']}")

        # -- phase 3: hysteresis (no second recommendation) ----------------
        client_lib.run_client(
            ccfg, source=DriftSource(seed=3, n_frames=EXTRA_SHIFT_FRAMES,
                                     shifted=True),
            max_frames=EXTRA_SHIFT_FRAMES,
        )
        snap = _get_json(port, "/debug/drift")
        if snap["recommendations"]["count"] != 1:
            return _fail(
                f"hysteresis failed: {snap['recommendations']['count']} "
                "recommendations after continued shift", snap)
        print("hysteresis ok: continued shift fired no second "
              "recommendation")

        # -- exported metric families --------------------------------------
        text = _scrape(port)
        for family in ("rdp_drift_score", "rdp_drift_recommendations_total",
                       "rdp_drift_reference_age_seconds",
                       "rdp_model_confidence_margin"):
            if f"# TYPE {family} " not in text:
                return _fail(f"/metrics is missing {family}")
        if "rdp_drift_recommendations_total 1" not in text:
            return _fail("rdp_drift_recommendations_total != 1",
                         [ln for ln in text.splitlines() if "drift" in ln])
        score_lines = [ln for ln in text.splitlines()
                       if ln.startswith("rdp_drift_score{")]
        if not any(float(ln.rsplit(" ", 1)[1]) > cfg.drift_psi_threshold
                   for ln in score_lines):
            return _fail("no rdp_drift_score sample above threshold",
                         score_lines)
        print(f"metrics ok: {len(score_lines)} rdp_drift_score samples, "
              "recommendation counted")

        # -- the recommendation is pinned flight-recorder evidence ---------
        spans = _get_json(port, "/debug/spans")
        pinned = [t for t in spans.get("pinned", [])
                  if t.get("name") == "serving.drift_recommendation"]
        if len(pinned) != 1:
            return _fail(
                f"expected 1 pinned drift_recommendation timeline, got "
                f"{len(pinned)}", spans.get("pinned"))
        print("recorder ok: recommendation pinned in /debug/spans")
    finally:
        server.stop(grace=None)
        servicer.close()

    # -- the offline path agrees from the same run's CSV -------------------
    report = analyze_drift(
        DriftConfig(metrics_csv=str(csv), min_rows=40,
                    baseline_fraction=0.4), render=False,
    )
    if not (report.analyzed and report.drifted):
        return _fail(f"offline analyze_drift disagrees: {report}")
    print(f"offline ok: drifted=True from the same CSV "
          f"(mean {report.baseline_mean:.1f} -> {report.recent_mean:.1f}, "
          f"psi {report.psi:.3f}, {report.n_rows} rows, "
          f"{report.n_dropped} dropped)")
    print("DRIFT SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
