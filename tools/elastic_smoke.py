#!/usr/bin/env python
"""CI elastic-fleet smoke: the self-healing loop end to end.

Boots ONE static seed replica and TWO replicated front-ends (peered
gossip mesh, lease registry on each, autoscaler on the first), then
drives the three elastic legs in order:

1. **Scale-up**: Poisson frame arrivals push measured demand past the
   capacity fit (a synthetic LOADBENCH capacity file keeps the trigger
   deterministic); the autoscaler must spawn a SECOND replica that
   self-registers over the lease RPCs -- no config change anywhere --
   and both front-ends must converge on 2 placeable members (one via
   gossip adoption, not direct registration).
2. **Front-end chaos**: SIGKILL the second front-end mid-stream; the
   client retries against the surviving sibling and finishes with ZERO
   lost accepted frames. The killed front-end's journal survives as its
   ``RDP_JOURNAL_PATH`` JSONL file, readable post-mortem with
   tools/journal_tail.py.
3. **Scale-down**: cut the load; once the demand window drains the
   autoscaler must retire the member it spawned through the graceful
   drain path (never the static seed), and the round trip must be
   visible in ``GET /debug/events`` (planner.plan + autoscaler.action
   scale_up/scale_down + fleet.lease) and in
   ``rdp_autoscaler_actions_total`` on the front-end's /metrics.

Run under both strict sanitizers:
``env JAX_PLATFORMS=cpu RDP_LOCKCHECK=strict RDP_TRANSFER_GUARD=strict
python tools/elastic_smoke.py``. Exit 0 on success.
"""

from __future__ import annotations

import json
import queue
import random
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from robotic_discovery_platform_tpu.observability import (  # noqa: E402
    events as event_kinds,
    families,
)

#: synthetic capacity fit: ~2 rps per replica keeps the Poisson trigger
#: deterministic on any CI box (the real LOADBENCH measures hundreds)
CAPACITY_GOODPUT_RPS = 2.0
LOAD_RATE_HZ = 8.0


def _get(port: int, path: str) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read().decode()


def _fail(msg: str, extra=None) -> int:
    print(f"FAIL: {msg}")
    if extra is not None:
        print(json.dumps(extra, indent=1, default=str)[:4000])
    return 1


def _frontend_stats(fleet_lib, grpc, endpoint: str) -> dict:
    """One stats-RPC Get against a front-end: its live count, lease
    table, and placement loads (the same payload siblings gossip)."""
    with grpc.insecure_channel(endpoint) as channel:
        stub = fleet_lib.ReplicaStatsStub(channel)
        return json.loads(stub.Get(b"", timeout=10).decode("utf-8"))


def _wait(predicate, timeout_s: float, poll_s: float = 0.3):
    """Poll until ``predicate()`` returns a truthy value; returns it
    (or the last falsy value after the deadline)."""
    deadline = time.monotonic() + timeout_s
    value = None
    while time.monotonic() < deadline:
        try:
            value = predicate()
        except Exception:  # noqa: BLE001 - a booting member refuses RPCs
            value = None
        if value:
            return value
        time.sleep(poll_s)
    return value


def main() -> int:
    import os

    os.environ.pop("RDP_METRICS_PORT", None)

    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.serving import (
        client as client_lib,
        fleet as fleet_lib,
        frontend as frontend_lib,
        replica as replica_lib,
    )
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc

    tmp = Path(tempfile.mkdtemp(prefix="rdp-elastic-"))
    uri = replica_lib.register_tiny_model(tmp / "mlruns", img_size=64)
    capacity_path = tmp / "CAPACITY.json"
    capacity_path.write_text(json.dumps({
        "slo_ms": 250.0,
        "rows": [{"goodput_rps": CAPACITY_GOODPUT_RPS,
                  "violation_rate": 0.0, "chips": 1,
                  "placement": "shared"}],
    }))

    replicas = replica_lib.spawn_local_replicas(
        1, uri, img_size=64, slo_ms=250.0, metrics_port=-1)
    seed_ep = replicas[0].endpoint
    frontends: list = []
    stop_load = threading.Event()
    load_thread = None
    rc = 1
    try:
        replica_lib.wait_serving([seed_ep])
        frontends = frontend_lib.spawn_local_frontends(
            2,
            replicas=seed_ep,
            tracking_uri=uri,
            elastic=True,
            lease_ttl_s=2.0,
            poll_s=0.2,
            autoscaler=True,
            autoscaler_min=1,
            autoscaler_max=2,
            sustain_s=1.0,
            cooldown_s=5.0,
            headroom=0.7,
            capacity_path=str(capacity_path),
            metrics_port=-1,
            env_overlay={
                "RDP_JOURNAL_PATH": str(tmp / "fe-{index}.jsonl"),
            },
        )
        fe1, fe2 = frontends
        if not fe1.metrics_port:
            return _fail("autoscaler front-end has no metrics port")

        # both front-ends must see the static seed before load starts
        for fe in frontends:
            stats = _wait(
                lambda fe=fe: (_frontend_stats(fleet_lib, grpc,
                                               fe.endpoint)
                               .get("live_replicas", 0) >= 1 or None),
                timeout_s=60)
            if not stats:
                return _fail(f"front-end {fe.endpoint} never saw the "
                             "seed replica")

        src = SyntheticSource(width=64, height=48, seed=5, n_frames=1)
        src.start()
        color, depth = src.get_frames()
        src.stop()
        request = client_lib.encode_request(color, depth)

        # -- leg 1: Poisson load -> autoscaler spawns a leased member --
        counts = {"sent": 0, "acked": 0}

        def poisson_load():
            rng = random.Random(11)
            while not stop_load.is_set():
                outbox: queue.Queue = queue.Queue()

                def gen(q=outbox):
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        yield item

                try:
                    with grpc.insecure_channel(fe1.endpoint) as channel:
                        stub = vision_grpc.VisionAnalysisServiceStub(
                            channel)
                        responses = stub.AnalyzeActuatorPerformance(
                            gen(), timeout=300)
                        while not stop_load.is_set():
                            outbox.put(request)
                            counts["sent"] += 1
                            resp = next(responses)
                            if resp.status.startswith(
                                    ("OK", "DEGRADED")):
                                counts["acked"] += 1
                            stop_load.wait(
                                rng.expovariate(LOAD_RATE_HZ))
                        outbox.put(None)
                        for _ in responses:
                            pass
                except Exception:  # noqa: BLE001 - reopen the stream
                    time.sleep(0.2)

        load_thread = threading.Thread(
            target=poisson_load, name="poisson-load", daemon=True)
        load_thread.start()

        def scaled_up():
            stats = _frontend_stats(fleet_lib, grpc, fe1.endpoint)
            leased = [ep for ep, lease in stats.get("leases", {}).items()
                      if lease.get("state") == "active"
                      and ep != seed_ep]
            if stats.get("live_replicas", 0) >= 2 and leased:
                return leased
            return None

        leased = _wait(scaled_up, timeout_s=120)
        if not leased:
            return _fail(
                "autoscaler never grew the fleet to 2 under load",
                _frontend_stats(fleet_lib, grpc, fe1.endpoint))
        spawned_ep = leased[0]
        print(f"scale-up ok: {spawned_ep} self-registered "
              f"(seed {seed_ep} untouched)")

        # the SIBLING converges on the same member via gossip adoption
        # (it was never the registrar)
        def sibling_sees():
            stats = _frontend_stats(fleet_lib, grpc, fe2.endpoint)
            lease = stats.get("leases", {}).get(spawned_ep, {})
            return (stats.get("live_replicas", 0) >= 2
                    and lease.get("state") == "active") or None

        if not _wait(sibling_sees, timeout_s=60):
            return _fail(
                "sibling front-end never adopted the leased member",
                _frontend_stats(fleet_lib, grpc, fe2.endpoint))
        print("gossip ok: sibling front-end adopted the leased member")

        metrics = _get(fe1.metrics_port, "/metrics")
        if f'{families.AUTOSCALER_ACTIONS}{{action="scale_up"}}' \
                not in metrics:
            return _fail("rdp_autoscaler_actions_total{action="
                         "\"scale_up\"} missing from /metrics")

        # -- leg 2: SIGKILL a front-end mid-stream; retry on sibling --
        chaos = {"sent": 0, "acked": 0}

        def chaos_stream(endpoint: str, frames: int,
                         kill_after: int | None) -> int:
            """Serial send/ack stream; returns acked count. Raises
            grpc.RpcError where the caller must fail over."""
            outbox: queue.Queue = queue.Queue()

            def gen():
                while True:
                    item = outbox.get()
                    if item is None:
                        return
                    yield item

            acked = 0
            with grpc.insecure_channel(endpoint) as channel:
                stub = vision_grpc.VisionAnalysisServiceStub(channel)
                responses = stub.AnalyzeActuatorPerformance(
                    gen(), timeout=120)
                for i in range(frames):
                    outbox.put(request)
                    chaos["sent"] += 1
                    if kill_after is not None and i == kill_after:
                        fe2.kill()  # SIGKILL, mid-stream, frame in flight
                    resp = next(responses)
                    if not resp.status.startswith(("OK", "DEGRADED")):
                        raise RuntimeError(
                            f"chaos frame errored: {resp.status}")
                    acked += 1
                    chaos["acked"] += 1
                outbox.put(None)
                for _ in responses:
                    pass
            return acked

        pending = 4
        try:
            done = chaos_stream(fe2.endpoint, frames=4, kill_after=3)
            pending -= done
        except grpc.RpcError:
            pending = chaos["sent"] - chaos["acked"]
        if fe2.alive():
            return _fail("front-end survived its SIGKILL")
        if pending > 0:
            # the unacked in-flight frames resume on the sibling: the
            # retry is the CLIENT's (stateless front-ends share nothing
            # but gossip), and no accepted frame may be lost
            chaos_stream(fe1.endpoint, frames=pending, kill_after=None)
        if chaos["acked"] < 4:
            return _fail(f"lost accepted frames: {chaos}")
        print(f"front-end chaos ok: {chaos['acked']}/4 frames accepted "
              f"across the SIGKILL (retried {max(pending, 0)} on the "
              "sibling)")

        # the killed front-end's journal outlived it: post-mortem merge
        out = subprocess.run(
            [sys.executable,
             str(Path(__file__).resolve().parent / "journal_tail.py"),
             "--json", str(tmp / "fe-0.jsonl"), str(tmp / "fe-1.jsonl")],
            capture_output=True, text=True, timeout=60)
        if out.returncode != 0:
            return _fail("journal_tail failed on the persisted "
                         f"journals: {out.stderr}")
        post_mortem = json.loads(out.stdout)
        dead_events = [e for e in post_mortem
                       if e.get("source", "").endswith("fe-1.jsonl")]
        if not dead_events:
            return _fail("SIGKILLed front-end left no persisted "
                         "journal events")
        print(f"post-mortem ok: {len(dead_events)} journal events from "
              "the killed front-end via journal_tail")

        # -- leg 3: cut the load -> graceful drain scale-down ---------
        stop_load.set()
        load_thread.join(timeout=30)

        def scaled_down():
            stats = _frontend_stats(fleet_lib, grpc, fe1.endpoint)
            lease = stats.get("leases", {}).get(spawned_ep, {})
            gone = lease.get("state") in (None, "left", "expired")
            return (stats.get("live_replicas", 0) == 1
                    and gone) or None

        if not _wait(scaled_down, timeout_s=180):
            return _fail(
                "autoscaler never drained back to the seed after the "
                "load cut", _frontend_stats(fleet_lib, grpc,
                                            fe1.endpoint))
        print(f"scale-down ok: {spawned_ep} drained and retired, "
              f"seed {seed_ep} still serving")

        metrics = _get(fe1.metrics_port, "/metrics")
        if f'{families.AUTOSCALER_ACTIONS}{{action="scale_down"}}' \
                not in metrics:
            return _fail("rdp_autoscaler_actions_total{action="
                         "\"scale_down\"} missing from /metrics")

        # -- the whole round trip is one readable event stream --------
        events = json.loads(
            _get(fe1.metrics_port, "/debug/events?since=0"))["events"]
        actions = [e["attrs"].get("action") for e in events
                   if e["kind"] == event_kinds.AUTOSCALER_ACTION]
        if "scale_up" not in actions or "scale_down" not in actions:
            return _fail(f"autoscaler round trip not in /debug/events: "
                         f"{actions}")
        if not any(e["kind"] == event_kinds.PLANNER_PLAN
                   for e in events):
            return _fail("no planner.plan evidence in /debug/events")
        lease_regs = [e for e in events
                      if e["kind"] == event_kinds.FLEET_LEASE
                      and e["attrs"].get("endpoint") == spawned_ep]
        if not lease_regs:
            return _fail("spawned member's lease transitions missing "
                         "from /debug/events")
        up_seq = min(e["seq"] for e in events
                     if e["kind"] == event_kinds.AUTOSCALER_ACTION
                     and e["attrs"].get("action") == "scale_up")
        down_seq = max(e["seq"] for e in events
                       if e["kind"] == event_kinds.AUTOSCALER_ACTION
                       and e["attrs"].get("action") == "scale_down")
        if not up_seq < down_seq:
            return _fail("scale_up/scale_down out of causal order")

        print("OK: lease-registered scale-up, gossip convergence, "
              "SIGKILLed front-end with zero lost accepted frames + "
              "post-mortem journal, drain-driven scale-down; "
              f"round trip journaled (scale_up#{up_seq} < "
              f"scale_down#{down_seq}); load stream "
              f"acked {counts['acked']}/{counts['sent']}")
        rc = 0
        return rc
    finally:
        stop_load.set()
        if load_thread is not None:
            load_thread.join(timeout=10)
        frontend_lib.stop_frontends(frontends)
        replica_lib.stop_replicas(replicas)


if __name__ == "__main__":
    sys.exit(main())
