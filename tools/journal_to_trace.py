#!/usr/bin/env python
"""Convert persisted event journals into an arrival trace.

The shared trace format -- ``{"gaps_ms": [...], "models": [...]}`` (or a
bare JSON array of gaps for single-model traces) -- is consumed by BOTH
load harnesses: ``bench_load.py --trace`` replays it against a live
server, ``robotic_discovery_platform_tpu.sim.workload.from_trace``
replays it through the fleet simulator. This tool closes the loop from
production to either one: point it at the ``RDP_JOURNAL_PATH`` JSONL
files of a real fleet and it reconstructs what the fleet was asked to
serve, so yesterday's incident can be replayed under the sim's scripted
faults or tomorrow's canary bench.

Two reconstruction modes:

- **Envelope (default).** Frames are deliberately not journaled (too
  hot), but every ``planner.plan`` event records the demand meter's
  ``demand_rps``. The envelope mode treats consecutive plan events as a
  piecewise-constant rate function and synthesizes a seeded Poisson
  process through it -- statistically faithful arrivals, deterministic
  given ``--seed``.
- **Direct (``--direct-kind``).** When a deployment journals one event
  per arrival-like occurrence (drills, replayed benches), each matching
  event becomes one arrival at its ``unix_ts``, with the model label
  read from ``--model-attr``.

Usage::

    python tools/journal_to_trace.py /tmp/fe-*.jsonl --out trace.json
    python tools/journal_to_trace.py drill.jsonl --direct-kind \\
        fleet.failover --out failover_replay.json
    bench_load.py --trace trace.json ...     # live replay
    # sim replay: workload.from_trace("trace.json")
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from journal_tail import merge_journals  # noqa: E402

PLAN_KIND = "planner.plan"


def demand_envelope(events: list[dict], kind: str = PLAN_KIND,
                    ) -> list[tuple[float, float]]:
    """(unix_ts, demand_rps) knots from the planner's journal trail,
    time-sorted. Events without a parsable demand are skipped."""
    knots: list[tuple[float, float]] = []
    for ev in events:
        if ev.get("kind") != kind:
            continue
        ts = ev.get("unix_ts")
        attrs = ev.get("attrs") or {}
        try:
            demand = float(attrs.get("demand_rps"))
            ts = float(ts)
        except (TypeError, ValueError):
            continue
        knots.append((ts, demand))
    knots.sort()
    return knots


def synthesize_from_envelope(knots: list[tuple[float, float]], *,
                             seed: int = 0,
                             models: list[str] | None = None,
                             tail_s: float | None = None,
                             ) -> tuple[list[float], list[str] | None]:
    """Seeded Poisson arrivals through a piecewise-constant rate
    envelope. The final knot's rate runs for ``tail_s`` (default: the
    median knot spacing) so the last plan interval is represented."""
    if len(knots) < 1:
        raise ValueError("no demand knots: journals carry no "
                         f"'{PLAN_KIND}' events with demand_rps")
    rng = random.Random(seed)
    spans = [b[0] - a[0] for a, b in zip(knots, knots[1:])]
    if tail_s is None:
        tail_s = sorted(spans)[len(spans) // 2] if spans else 1.0
    segments = [(t, rate, (knots[i + 1][0] if i + 1 < len(knots)
                           else t + tail_s))
                for i, (t, rate) in enumerate(knots)]
    t0 = segments[0][0]
    arrivals: list[float] = []
    for start, rate, end in segments:
        if rate <= 0 or end <= start:
            continue
        t = start + rng.expovariate(rate)
        while t < end:
            arrivals.append(t - t0)
            t += rng.expovariate(rate)
    gaps_ms: list[float] = []
    prev = 0.0
    for t in arrivals:
        gaps_ms.append(round((t - prev) * 1e3, 6))
        prev = t
    labels = None
    if models:
        labels = [models[i % len(models)] for i in range(len(gaps_ms))]
    return gaps_ms, labels


def direct_arrivals(events: list[dict], *, kind: str,
                    model_attr: str = "model",
                    default_model: str = "seg",
                    ) -> tuple[list[float], list[str]]:
    """One arrival per matching journal event, gaps from wall-clock
    deltas."""
    hits = sorted(((float(ev["unix_ts"]), ev) for ev in events
                   if ev.get("kind") == kind
                   and ev.get("unix_ts") is not None),
                  key=lambda pair: pair[0])
    if not hits:
        raise ValueError(f"no '{kind}' events in the supplied journals")
    gaps_ms: list[float] = []
    labels: list[str] = []
    prev = hits[0][0]
    for ts, ev in hits:
        gaps_ms.append(round((ts - prev) * 1e3, 6))
        prev = ts
        attrs = ev.get("attrs") or {}
        labels.append(str(attrs.get(model_attr) or default_model))
    return gaps_ms, labels


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Reconstruct an arrival trace (bench_load --trace / "
                    "sim.workload format) from RDP_JOURNAL_PATH JSONL "
                    "files.")
    ap.add_argument("journals", nargs="+",
                    help="journal JSONL paths (rotation .1 generations "
                         "are picked up automatically)")
    ap.add_argument("--out", required=True, help="trace file to write")
    ap.add_argument("--seed", type=int, default=0,
                    help="envelope mode: Poisson synthesis seed")
    ap.add_argument("--models", default="",
                    help="envelope mode: comma-separated model labels "
                         "to round-robin over (empty = no labels)")
    ap.add_argument("--tail-s", type=float, default=None,
                    help="envelope mode: how long the final demand knot "
                         "runs (default: median knot spacing)")
    ap.add_argument("--direct-kind", default="",
                    help="direct mode: journal kind to treat as one "
                         "arrival per event")
    ap.add_argument("--model-attr", default="model",
                    help="direct mode: attr carrying the model label")
    ap.add_argument("--default-model", default="seg")
    args = ap.parse_args(argv)

    events = merge_journals(args.journals)
    if not events:
        print("no events loaded from any journal", file=sys.stderr)
        return 2
    try:
        if args.direct_kind:
            gaps_ms, labels = direct_arrivals(
                events, kind=args.direct_kind,
                model_attr=args.model_attr,
                default_model=args.default_model)
        else:
            models = [m for m in args.models.split(",") if m]
            gaps_ms, labels = synthesize_from_envelope(
                demand_envelope(events), seed=args.seed,
                models=models or None, tail_s=args.tail_s)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    payload: object = ({"gaps_ms": gaps_ms, "models": labels}
                       if labels else gaps_ms)
    Path(args.out).write_text(json.dumps(payload))
    print(f"wrote {len(gaps_ms)} arrivals "
          f"({sum(gaps_ms) / 1e3:.1f}s span) to {args.out}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
