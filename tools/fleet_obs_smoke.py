#!/usr/bin/env python
"""CI fleet-observability smoke: one query tells the story.

Boots a 2-replica local CPU fleet (subprocess replicas with ephemeral
metrics endpoints) behind the in-process front-end, streams frames under
one client trace, SIGKILLs the replica the stream is placed on
mid-stream, and asserts the whole observability plane from the
front-end's single port:

- ``GET /debug/trace?id=<trace_id>`` returns ONE stitched tree holding
  the front-end's relay timelines (including the failover hop span) AND
  BOTH replicas' dispatch timelines for the trace -- the dead replica's
  evidence served from the federator's last-good cache, marked stale;
- ``GET /federate`` marks the dead replica ``rdp_replica_up 0`` without
  dropping the survivor's samples (and keeps the victim's last families
  with a staleness age);
- ``GET /debug/events?since=0`` holds the quarantine (breaker open),
  failover, and -- after the victim respawns on its old port -- rejoin
  events in causal (cursor) order.

Run under both strict sanitizers:
``env JAX_PLATFORMS=cpu RDP_LOCKCHECK=strict RDP_TRANSFER_GUARD=strict
python tools/fleet_obs_smoke.py``. Exit 0 on success.
"""

from __future__ import annotations

import json
import queue
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# the central registries are the source of truth for family/kind names
from robotic_discovery_platform_tpu.observability import (  # noqa: E402
    events as event_kinds,
    families,
)


def _get(port: int, path: str) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return resp.read().decode()


def _fail(msg: str, extra=None) -> int:
    print(f"FAIL: {msg}")
    if extra is not None:
        print(json.dumps(extra, indent=1, default=str)[:4000])
    return 1


def main() -> int:
    import os

    # this process IS the front-end; an inherited fixed metrics port
    # would collide with the replicas' resolution of the same env var
    os.environ.pop("RDP_METRICS_PORT", None)

    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import grpc

    from robotic_discovery_platform_tpu.io.frames import SyntheticSource
    from robotic_discovery_platform_tpu.observability import (
        journal as journal_lib,
        trace,
    )
    from robotic_discovery_platform_tpu.serving import (
        client as client_lib,
        frontend as frontend_lib,
        replica as replica_lib,
    )
    from robotic_discovery_platform_tpu.serving.proto import vision_grpc
    from robotic_discovery_platform_tpu.utils.config import ServerConfig

    tmp = Path(tempfile.mkdtemp(prefix="rdp-fleet-obs-"))
    uri = replica_lib.register_tiny_model(tmp / "mlruns", img_size=64)
    replicas = replica_lib.spawn_local_replicas(
        2, uri, img_size=64, slo_ms=250.0, metrics_port=-1)
    endpoints = [r.endpoint for r in replicas]
    f_server = fe = channel = None
    rc = 1
    try:
        replica_lib.wait_serving(endpoints)
        fcfg = ServerConfig(
            address="localhost:0",
            fleet_replicas=",".join(endpoints),
            fleet_poll_s=0.15,
            fleet_probe_timeout_s=2.0,
            fleet_breaker_failures=1,
            fleet_breaker_reset_s=1.0,
            metrics_port=-1,  # ephemeral: the fleet's one-stop port
        )
        f_server, fe = frontend_lib.build_frontend(fcfg)
        fport = f_server.add_insecure_port("localhost:0")
        f_server.start()
        if fe.metrics_server is None:
            return _fail("front-end metrics server did not start")
        mport = fe.metrics_server.port
        if not fe.router.wait_live(2, timeout_s=60):
            return _fail("fleet never reached 2 placeable replicas")
        cursor0 = journal_lib.JOURNAL.snapshot()["next_cursor"]

        # one client trace for the whole stream
        src = SyntheticSource(width=64, height=48, seed=3, n_frames=1)
        src.start()
        color, depth = src.get_frames()
        src.stop()
        request = client_lib.encode_request(color, depth)
        client_ctx = trace.new_context()
        trace_id = client_ctx.trace_id

        channel = grpc.insecure_channel(f"localhost:{fport}")
        stub = vision_grpc.VisionAnalysisServiceStub(channel)
        outbox: queue.Queue = queue.Queue()

        def gen():
            while True:
                item = outbox.get()
                if item is None:
                    return
                yield item

        responses = stub.AnalyzeActuatorPerformance(
            gen(), timeout=120, metadata=trace.to_metadata(client_ctx))

        # a few frames land on the placed replica; the federator cache
        # (poll thread) picks up its dispatch timelines for this trace
        for _ in range(3):
            outbox.put(request)
            resp = next(responses)
            if not resp.status.startswith(("OK", "DEGRADED")):
                return _fail(f"pre-kill frame errored: {resp.status}")
        placed = [r for r in fe.router.replicas if r.inflight > 0]
        if len(placed) != 1:
            return _fail(f"expected 1 placed replica, got {placed}")
        victim = placed[0]
        survivor_ep = next(ep for ep in endpoints
                           if ep != victim.endpoint)
        deadline = time.monotonic() + 20.0
        pre = {}
        while time.monotonic() < deadline:
            pre = json.loads(
                _get(mport, f"/debug/trace?id={trace_id}"))
            victim_src = next(
                (s for s in pre["sources"]
                 if s.get("endpoint") == victim.endpoint), {})
            if victim_src.get("timelines"):
                break
            time.sleep(0.2)
        else:
            return _fail("victim's dispatch timelines never appeared in "
                         "the stitched trace pre-kill", pre)

        # SIGKILL the placed replica; the stream's next frame must fail
        # over to the survivor under the SAME trace
        victim_local = next(r for r in replicas
                            if r.endpoint == victim.endpoint)
        victim_local.kill()
        outbox.put(request)
        resp = next(responses)
        if not resp.status.startswith(("OK", "DEGRADED", "ERROR")):
            return _fail(f"failed-over frame lost: {resp.status!r}")
        failed_over_ok = resp.status.startswith(("OK", "DEGRADED"))
        outbox.put(request)
        resp2 = next(responses)  # the stream keeps serving post-failover
        outbox.put(None)
        leftovers = [r.status for r in responses]

        # -- the stitched trace: one query, whole story ------------------
        stitched = json.loads(_get(mport, f"/debug/trace?id={trace_id}"))
        tree = stitched.get("tree", {})
        by_endpoint = {s.get("endpoint"): s
                       for s in stitched.get("sources", [])}
        fe_src = by_endpoint.get(None, {})
        relay_tls = fe_src.get("timelines", [])
        if not relay_tls:
            return _fail("no front-end relay timelines in stitched "
                         "trace", stitched)
        hops = [s for tl in relay_tls for s in tl.get("spans", [])
                if s.get("name") == "failover"]
        if not hops:
            return _fail("stitched trace shows no failover hop", stitched)
        hop = hops[0]
        if (hop["attributes"].get("frm") != victim.endpoint
                or hop["attributes"].get("to") != survivor_ep):
            return _fail(f"failover hop names wrong replicas: "
                         f"{hop['attributes']}", stitched)
        for ep in endpoints:
            src_tls = by_endpoint.get(ep, {}).get("timelines", [])
            if not src_tls:
                return _fail(f"replica {ep} has no timelines in the "
                             "stitched trace", stitched)
            if by_endpoint[ep].get("role") != "replica":
                return _fail(f"replica {ep} not attributed role=replica",
                             by_endpoint[ep])
        if not by_endpoint[victim.endpoint].get("fresh") is False:
            return _fail("dead replica's timelines not marked stale",
                         by_endpoint[victim.endpoint])
        tree_eps = {c.get("endpoint") for c in tree.get("children", [])}
        if not {None, victim.endpoint, survivor_ep} <= tree_eps:
            return _fail(f"stitched tree is missing sources: {tree_eps}")

        # -- the federated scrape ----------------------------------------
        fed = _get(mport, "/federate")
        if f'{families.REPLICA_UP}{{replica="{victim.endpoint}"}} 0' not in fed:
            return _fail("dead replica not marked rdp_replica_up 0")
        if f'{families.REPLICA_UP}{{replica="{survivor_ep}"}} 1' not in fed:
            return _fail("survivor not marked rdp_replica_up 1")
        survivor_samples = [ln for ln in fed.splitlines()
                            if f'replica="{survivor_ep}"' in ln]
        victim_samples = [ln for ln in fed.splitlines()
                          if f'replica="{victim.endpoint}"' in ln
                          and ln.startswith(families.FRAMES)]
        if not any(ln.startswith(families.FRAMES)
                   for ln in survivor_samples):
            return _fail("survivor's samples missing from /federate")
        if not victim_samples:
            return _fail("victim's last-good families dropped from "
                         "/federate (staleness cache lost)")
        if (families.FLEET_FRAMES not in fed
                or families.FLEET_BURN not in fed):
            return _fail("fleet roll-up families missing from /federate")

        # -- the journal: quarantine -> failover in causal order ---------
        events = json.loads(
            _get(mport, f"/debug/events?since={cursor0}"))["events"]
        opened = [e for e in events
                  if e["kind"] == event_kinds.BREAKER_TRANSITION
                  and e["attrs"].get("to") == "open"
                  and victim.endpoint in e["attrs"].get("breaker", "")]
        failovers = [e for e in events if e["kind"] == event_kinds.FLEET_FAILOVER]
        if not opened:
            return _fail("no quarantine (breaker open) event for the "
                         "victim", events)
        if not failovers:
            return _fail("no fleet.failover event", events)
        if not opened[0]["seq"] < failovers[0]["seq"]:
            return _fail("quarantine and failover out of causal order",
                         events)
        if failovers[0]["trace_id"] != trace_id:
            return _fail("failover event not stamped with the stream's "
                         "trace", failovers[0])

        # -- rejoin: respawn on the old port, half-open probe readmits ---
        replicas[replicas.index(victim_local)] = (
            replica_lib.respawn_replica(victim_local))
        replica_lib.wait_serving([victim.endpoint])
        if not fe.router.wait_live(2, timeout_s=30):
            return _fail("victim never rejoined the ring")
        events = json.loads(
            _get(mport, f"/debug/events?since={cursor0}"))["events"]
        rejoins = [e for e in events
                   if e["kind"] == event_kinds.FLEET_MEMBERSHIP
                   and e["attrs"].get("replica") == victim.endpoint
                   and e["attrs"].get("state") == "joined"
                   and e["seq"] > failovers[0]["seq"]]
        if not rejoins:
            return _fail("no rejoin membership event after the failover",
                         events)

        print("OK: stitched /debug/trace holds frontend relay + both "
              "replicas' timelines (victim stale-cached), /federate "
              f"marks up=0/1 correctly, journal order quarantine#"
              f"{opened[0]['seq']} < failover#{failovers[0]['seq']} < "
              f"rejoin#{rejoins[0]['seq']}; failed-over frame "
              f"{'rerouted OK' if failed_over_ok else 'error-completed'},"
              f" post-failover frame {resp2.status.split(':')[0]!r}, "
              f"{len(leftovers)} leftover response(s)")
        rc = 0
        return rc
    finally:
        if channel is not None:
            channel.close()
        if f_server is not None:
            f_server.stop(grace=None)
        if fe is not None:
            fe.close()
        replica_lib.stop_replicas(replicas)


if __name__ == "__main__":
    sys.exit(main())
