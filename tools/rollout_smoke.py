#!/usr/bin/env python
"""CI rollout smoke: the full drift -> retrain -> shadow -> gate ->
promote loop, end to end on a live 2-replica fleet.

Boots two in-process replica servers behind the real fleet front-end and
keeps ONE client stream of frames flowing for the whole run, then:

1. streams **nominal** synthetic frames (the drift monitor
   self-baselines), shifts to **darkened** frames (the
   tools/drift_smoke.py recipe): exactly ONE retrain recommendation
   fires, and the attached RolloutManager drains the idle fleet member;
2. the injected "retraining" registers a **deliberately bad candidate**
   (zeroed weights -> empty masks): the shadow gate rejects it
   fail-closed -- the staging alias never moves, both replicas keep the
   old generation, ZERO frames are lost across drain/shadow/rollback,
   and the drained replica rejoins the placement ring;
3. traffic returns to nominal, the PR 9 hysteresis re-arms, a second
   excursion fires a second recommendation, and a **good candidate**
   (faithful weights) passes every gate and promotes: both replicas hot
   -reload to the new generation with the drift reference re-stamped
   ATOMICALLY (version/drift_generation pair over the stats RPC), and
   ``GET /debug/rollout`` shows the completed cycle history.

Run under the strict sanitizers in CI::

    env JAX_PLATFORMS=cpu RDP_LOCKCHECK=strict RDP_TRANSFER_GUARD=strict \
        python tools/rollout_smoke.py

Exit 0 on success, 1 with a diagnostic.
"""

from __future__ import annotations

import copy
import json
import queue
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np

# runnable straight from a checkout, with or without `pip install -e .`
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

H, W = 120, 160
BASELINE_FRAMES = 20
# one rollout cycle pays fresh XLA compiles (candidate warm-up + fixture
# reference analyzers) on top of the live traffic sharing the CPU --
# generous on purpose, the assertions are about ORDER not speed
WAIT_S = 600.0


def _fail(msg: str, payload=None) -> int:
    print(f"FAIL: {msg}")
    if payload is not None:
        print(json.dumps(payload, indent=1, default=str)[:4000])
    return 1


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as resp:
        return json.loads(resp.read().decode())


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30
    ) as resp:
        return resp.read().decode()


class DriftingStream:
    """ONE long-lived client stream through the front-end whose synthetic
    camera can be shifted mid-stream (darkened images + degraded depth,
    the drift_smoke recipe). Counts sent vs received: the zero-lost
    ledger for the whole smoke."""

    def __init__(self, endpoint: str):
        import grpc

        from robotic_discovery_platform_tpu.serving import (
            client as client_lib,
        )
        from robotic_discovery_platform_tpu.serving.proto import (
            vision_grpc,
        )

        self.shifted = False
        self.sent = 0
        self.received = 0
        self.errors = 0
        self._stop = threading.Event()
        self._outbox: queue.Queue = queue.Queue(maxsize=4)
        self._rng = np.random.default_rng(7)
        self._channel = grpc.insecure_channel(endpoint)
        stub = vision_grpc.VisionAnalysisServiceStub(self._channel)

        def render():
            from robotic_discovery_platform_tpu.training.synthetic import (
                render_scene,
            )

            img_rgb, _, depth = render_scene(self._rng, H, W)
            if self.shifted:
                img_rgb = (img_rgb.astype(np.float32) * 0.25
                           ).astype(np.uint8)
                depth = depth.copy()
                depth[::2] = 0
            return img_rgb[..., ::-1].copy(), depth  # BGR like a camera

        def feeder():
            while not self._stop.is_set():
                color, depth = render()
                req = client_lib.encode_request(color, depth)
                while not self._stop.is_set():
                    try:
                        self._outbox.put(req, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            self._outbox.put(None)

        def gen():
            while True:
                item = self._outbox.get()
                if item is None:
                    return
                self.sent += 1
                yield item
                # paced: the stream must keep flowing, not saturate the
                # CPU the rollout's compiles are sharing
                time.sleep(0.04)

        self._feeder = threading.Thread(target=feeder, daemon=True,
                                        name="smoke-feeder")
        self._feeder.start()
        call = stub.AnalyzeActuatorPerformance(gen())

        def drain():
            import grpc as _grpc

            try:
                for resp in call:
                    self.received += 1
                    if resp.status.startswith("ERROR"):
                        self.errors += 1
            except _grpc.RpcError:
                pass

        self._drainer = threading.Thread(target=drain, daemon=True,
                                         name="smoke-drainer")
        self._drainer.start()

    def wait_received(self, n: int, timeout_s: float = WAIT_S) -> bool:
        deadline = time.monotonic() + timeout_s
        while self.received < n and time.monotonic() < deadline:
            time.sleep(0.05)
        return self.received >= n

    def stop(self) -> None:
        self._stop.set()
        self._feeder.join(timeout=10)
        self._drainer.join(timeout=60)
        self._channel.close()


def main() -> int:
    from robotic_discovery_platform_tpu.utils.platforms import (
        force_cpu_platform,
    )

    force_cpu_platform(min_devices=1)

    import jax
    from flax.core import unfreeze

    from robotic_discovery_platform_tpu import tracking
    from robotic_discovery_platform_tpu.models.unet import (
        build_unet,
        init_unet,
    )
    from robotic_discovery_platform_tpu.serving import (
        frontend as frontend_lib,
        rollout as rollout_lib,
        server as server_lib,
    )
    from robotic_discovery_platform_tpu.utils.config import (
        ModelConfig,
        RolloutConfig,
        ServerConfig,
    )

    tmp = Path(tempfile.mkdtemp(prefix="rdp-rollout-smoke-"))
    uri = f"file:{tmp}/mlruns"
    tracking.set_tracking_uri(uri)
    tracking.set_experiment("Actuator Segmentation")
    mcfg = ModelConfig(base_features=8, compute_dtype="float32")
    model = build_unet(mcfg)
    variables = unfreeze(
        jax.device_get(init_unet(model, jax.random.key(0), img_size=64))
    )
    # brightness-sensitive head (drift_smoke recipe): darkening genuinely
    # moves coverage AND margin, and live masks are non-empty so a
    # zeroed candidate genuinely diverges
    good = copy.deepcopy(variables)
    good["params"]["Conv_0"]["kernel"] = (
        np.asarray(good["params"]["Conv_0"]["kernel"]) * 40.0
    )
    good["params"]["Conv_0"]["bias"] = np.full((1,), 0.5, np.float32)
    with tracking.start_run():
        v0 = int(tracking.log_model(
            good, mcfg, registered_model_name="Actuator-Segmenter"))
    tracking.Client().set_registered_model_alias(
        "Actuator-Segmenter", "staging", v0)

    def replica_cfg(name: str, metrics_port: int = 0) -> ServerConfig:
        return ServerConfig(
            address="localhost:0",
            tracking_uri=uri,
            model_img_size=64,
            metrics_csv=str(tmp / f"{name}.csv"),
            metrics_flush_every=1000,
            calibration_path=str(tmp / "missing.npz"),
            metrics_port=metrics_port,
            reload_poll_s=0.0,
            # fast drift knobs: small self-baseline, tight scoring
            # stride, sub-second sustain, SHORT cooldown so the re-armed
            # second excursion fits in a smoke run
            drift_baseline_frames=BASELINE_FRAMES,
            drift_window=64,
            drift_score_every=8,
            drift_psi_threshold=0.25,
            drift_sustain_s=0.2,
            drift_cooldown_s=2.0,
        )

    # the injected "retraining pipeline": registers a crafted candidate
    # under the shadow alias -- zeroed weights first (must be rejected),
    # faithful weights second (must promote)
    phase = {"zero": True}

    def train_fn(target):
        v = (jax.tree_util.tree_map(
            lambda a: np.zeros_like(np.asarray(a)), good)
            if phase["zero"] else copy.deepcopy(good))
        with tracking.start_run():
            version = int(tracking.log_model(
                v, mcfg, registered_model_name="Actuator-Segmenter"))
        tracking.Client().set_registered_model_alias(
            "Actuator-Segmenter", "shadow", version)
        kind = "zeroed-head (bad)" if phase["zero"] else "faithful (good)"
        print(f"train_fn: registered {kind} candidate v{version} on "
              f"drained replica {target.name}")

        class Result:
            succeeded = True

        Result.version = version
        return Result()

    servers, servicers = [], []
    f_server = fe = stream = None
    mgr = None
    try:
        endpoints = []
        for i in range(2):
            cfg = replica_cfg(f"r{i}", metrics_port=-1 if i == 0 else 0)
            server, servicer = server_lib.build_server(cfg)
            port = server.add_insecure_port("localhost:0")
            server.start()
            servers.append(server)
            servicers.append(servicer)
            endpoints.append(f"localhost:{port}")
        debug_port = servicers[0].metrics_server.port

        fcfg = ServerConfig(
            address="localhost:0",
            fleet_replicas=",".join(endpoints),
            fleet_poll_s=0.1,
        )
        f_server, fe = frontend_lib.build_frontend(fcfg)
        f_port = f_server.add_insecure_port("localhost:0")
        f_server.start()
        if not fe.router.wait_live(2, timeout_s=30):
            return _fail("fleet never reached 2 live replicas")

        mgr = rollout_lib.RolloutManager(
            [], RolloutConfig(
                shadow_fraction=1.0, shadow_min_frames=4,
                gate_fixture_frames=2, gate_fixture_min_iou=0.8,
                gate_shadow_min_iou=0.5, gate_shadow_max_psi=1.0,
                drain_timeout_s=60.0, retrain_timeout_s=300.0,
                shadow_timeout_s=180.0, promote_timeout_s=180.0,
            ),
            replica_cfg("mgr"), train_fn=train_fn,
        )
        rollout_lib.attach_rollout(mgr, servicers, names=endpoints)
        mgr.start()

        stream = DriftingStream(f"localhost:{f_port}")

        # -- phase 1: nominal traffic baselines + scores clean ----------
        if not stream.wait_received(BASELINE_FRAMES + 40):
            return _fail("nominal phase stalled "
                         f"(received {stream.received})")
        if mgr.snapshot()["cycles_total"] != 0:
            return _fail("a rollout cycle ran on NOMINAL traffic",
                         mgr.snapshot())
        print(f"nominal ok: {stream.received} frames served, no "
              "recommendation, rollout idle")

        # -- phase 2: drift fires ONE rec; bad candidate is rejected ----
        stream.shifted = True
        deadline = time.monotonic() + WAIT_S
        while (mgr.snapshot()["cycles_total"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.2)
        snap = mgr.snapshot()
        if snap["cycles_total"] < 1:
            return _fail("drift never drove a rollout cycle", snap)
        cycle1 = snap["history"][0]
        if cycle1["outcome"] != "rolled_back":
            return _fail("bad candidate was NOT rejected", cycle1)
        if cycle1["rolled_back_at"] != "canary":
            return _fail(
                f"expected rejection at the canary gate, got "
                f"{cycle1['rolled_back_at']}", cycle1)
        gates = cycle1["gates"] or {}
        if gates.get("shadow_iou", {}).get("pass", True):
            return _fail("shadow IoU gate passed a zeroed candidate",
                         gates)
        text = _scrape(debug_port)
        recs = [ln for ln in text.splitlines()
                if ln.startswith("rdp_drift_recommendations_total")]
        if not recs or not recs[0].endswith(" 1"):
            return _fail("expected exactly 1 drift recommendation", recs)
        store = tracking.store_for(uri)
        if store.get_alias("Actuator-Segmenter", "staging") != v0:
            return _fail("staging alias moved despite gate rejection")
        for i, sv in enumerate(servicers):
            if sv.current_version != v0:
                return _fail(f"replica {i} left the old generation "
                             "after a rejected candidate")
            if sv.is_draining:
                return _fail(f"replica {i} stuck DRAINING after rollback")
        deadline = time.monotonic() + 30
        while fe.router.live_count < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        if fe.router.live_count != 2:
            return _fail("drained replica never rejoined the ring")
        print(f"rejection ok: bad candidate v{cycle1['candidate_version']}"
              " rolled back at the canary gate, alias unchanged, replica "
              "rejoined")

        # -- phase 3: recover, re-arm, good candidate promotes ----------
        phase["zero"] = False
        stream.shifted = False
        base = stream.received
        if not stream.wait_received(base + 80):
            return _fail("recovery phase stalled")
        stream.shifted = True
        deadline = time.monotonic() + WAIT_S
        while (mgr.snapshot()["cycles_total"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.2)
        snap = mgr.snapshot()
        if snap["cycles_total"] < 2:
            return _fail("hysteresis never re-armed a second cycle "
                         "(PR 9 recovery + cooldown)", snap)
        cycle2 = snap["history"][1]
        if cycle2["outcome"] != "promoted":
            return _fail("good candidate did not promote", cycle2)
        v_new = cycle2["candidate_version"]
        for i, sv in enumerate(servicers):
            version, gen = sv.version_and_reference()
            if version != v_new:
                return _fail(f"replica {i} serves v{version}, expected "
                             f"promoted v{v_new}")
            if gen != v_new:
                return _fail(
                    f"replica {i} pairs engine v{version} with drift "
                    f"reference generation {gen} -- the atomic re-stamp "
                    "broke")
        if store.get_alias("Actuator-Segmenter", "staging") != v_new:
            return _fail("staging alias does not point at the promoted "
                         "version")
        print(f"promotion ok: good candidate v{v_new} serving on both "
              "replicas, drift reference re-stamped")

        # -- /debug/rollout + metric families ---------------------------
        debug = _get_json(debug_port, "/debug/rollout")
        if not debug.get("enabled") or debug.get("state") != "idle":
            return _fail("/debug/rollout not idle after the run", debug)
        outcomes = [c["outcome"] for c in debug.get("history", [])]
        if outcomes != ["rolled_back", "promoted"]:
            return _fail(f"/debug/rollout history {outcomes}", debug)
        text = _scrape(debug_port)
        for family in ("rdp_rollout_state", "rdp_rollout_transitions_total",
                       "rdp_rollout_shadow_frames_total",
                       "rdp_rollout_gate_verdicts_total",
                       "rdp_rollout_rollbacks_total",
                       "rdp_fleet_replicas_draining"):
            if f"# TYPE {family} " not in text:
                return _fail(f"/metrics is missing {family}")
        if 'rdp_rollout_state{state="idle"} 1' not in text:
            return _fail("rdp_rollout_state gauge not back at idle")
        print("observability ok: /debug/rollout shows both cycles, "
              "rdp_rollout_* families exported")

        # -- zero lost frames across the WHOLE run ----------------------
        stream.stop()
        stopped = stream
        stream = None
        if stopped.received != stopped.sent:
            return _fail(
                f"LOST FRAMES: sent {stopped.sent}, answered "
                f"{stopped.received} across drain/shadow/rollback/promote")
        if stopped.errors:
            return _fail(f"{stopped.errors} frames error-completed; "
                         "expected zero across the rollout")
        print(f"zero-lost ok: {stopped.sent} frames sent, "
              f"{stopped.received} answered, 0 errors")
    finally:
        if stream is not None:
            stream.stop()
        if mgr is not None:
            mgr.stop()
        if f_server is not None:
            f_server.stop(grace=None)
        if fe is not None:
            fe.close()
        for server in servers:
            server.stop(grace=None)
        for servicer in servicers:
            servicer.close()

    print("ROLLOUT SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
