#!/bin/bash
# Opportunistic TPU workload chain for a flapping tunnel: probe until a
# healthy window opens, then run the round-5 TPU measurements in priority
# order, each under its own timeout so a mid-run wedge kills the step (not
# the chain) and the loop falls back to probing. Stages record completion
# markers so nothing reruns after a flap.
cd /root/repo
MARK=/tmp/tpu_r5_stages
mkdir -p "$MARK"
log() { echo "[$(date -u +%H:%M:%S)] $*" >> /tmp/tpu_runner.log; }

probe() {
    # must be the REAL TPU backend: a fast-failing tunnel can drop JAX to
    # the CPU fallback, which would otherwise pass the probe and record
    # CPU timings as TPU results
    timeout 90 python -c "
import jax
assert jax.default_backend() == 'tpu', jax.default_backend()
import jax.numpy as jnp
float(jnp.ones(()) + 1)" > /dev/null 2>&1
}

run_stage() {  # name timeout cmd...
    local name=$1 tmo=$2; shift 2
    [ -f "$MARK/$name" ] && return 0
    log "stage $name: starting"
    if timeout "$tmo" "$@" >> "/tmp/tpu_stage_$name.log" 2>&1; then
        touch "$MARK/$name"
        log "stage $name: DONE"
        return 0
    else
        local rc=$?
        log "stage $name: failed/timeout (rc=$rc)"
        return 1
    fi
}

while true; do
    if [ -f "$MARK/all_done" ]; then log "all done"; exit 0; fi
    if ! probe; then sleep 45; continue; fi
    log "tunnel healthy; running chain"
    run_stage bench1 2700 python bench.py || continue
    run_stage autotune32 2700 python bench_pallas.py autotune 32 || continue
    run_stage autotune16 1500 python bench_pallas.py autotune 16 || continue
    run_stage pallasbench 3600 python bench_pallas.py || continue
    run_stage bench2 2700 python bench.py || continue
    run_stage parity_f32_s0 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 0 || continue
    run_stage parity_f32_s1 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 1 || continue
    run_stage parity_f32_s2 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 2 || continue
    run_stage parity_bf16_s0 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 0 || continue
    run_stage parity_bf16_s1 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 1 || continue
    run_stage parity_bf16_s2 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 2 || continue
    touch "$MARK/all_done"
    log "chain complete"
    exit 0
done
