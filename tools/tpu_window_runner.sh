#!/bin/bash
# Opportunistic TPU workload chain for a flapping tunnel: probe until a
# healthy window opens, then run the round-5 TPU measurements in priority
# order, each under its own timeout so a mid-run wedge kills the step (not
# the chain) and the loop falls back to probing. Stages record completion
# markers so nothing reruns after a flap.
cd /root/repo
MARK=/tmp/tpu_r5_stages
mkdir -p "$MARK"
log() { echo "[$(date -u +%H:%M:%S)] $*" >> /tmp/tpu_runner.log; }

probe() {
    # must be the REAL TPU backend: a fast-failing tunnel can drop JAX to
    # the CPU fallback, which would otherwise pass the probe and record
    # CPU timings as TPU results
    timeout 75 python -c "
import jax
assert jax.default_backend() == 'tpu', jax.default_backend()
import jax.numpy as jnp
float(jnp.ones(()) + 1)" > /dev/null 2>&1
}

run_stage() {  # name timeout cmd...
    local name=$1 tmo=$2; shift 2
    [ -f "$MARK/$name" ] && return 0
    log "stage $name: starting"
    if timeout "$tmo" "$@" >> "/tmp/tpu_stage_$name.log" 2>&1; then
        touch "$MARK/$name"
        log "stage $name: DONE"
        return 0
    else
        local rc=$?
        log "stage $name: failed/timeout (rc=$rc)"
        return 1
    fi
}

run_bench() {  # name -- bench.py exits 0 even for its structured error
    # artifact (by design, for the driver), and can fall back to CPU if
    # the tunnel flaps mid-init, so stage success here means: a result
    # line with backend "tpu" and no error. `timeout` targets python
    # DIRECTLY (a bash -c wrapper would absorb the SIGTERM and orphan a
    # wedged python holding the tunnel).
    local name=$1 out="/tmp/${1}_result.json"
    [ -f "$MARK/$name" ] && return 0
    log "stage $name: starting"
    if timeout 2700 python bench.py > "$out" 2>> "/tmp/tpu_stage_$name.log" \
        && tail -1 "$out" | grep -q '"backend": "tpu"' \
        && ! tail -1 "$out" | grep -q '"error"'; then
        touch "$MARK/$name"
        log "stage $name: DONE"
        return 0
    else
        local rc=$?
        log "stage $name: failed/timeout/cpu-fallback (rc=$rc)"
        return 1
    fi
}

export BENCH_PROBE_ATTEMPTS=1 BENCH_PROBE_TIMEOUT_S=75
while true; do
    if [ -f "$MARK/all_done" ]; then log "all done"; exit 0; fi
    if ! probe; then sleep 20; continue; fi
    log "tunnel healthy; running chain"
    run_bench bench1 || continue
    run_stage autotune32 2700 python bench_pallas.py autotune 32 || continue
    run_stage autotune16 1500 python bench_pallas.py autotune 16 || continue
    run_stage pallasbench 3600 python bench_pallas.py || continue
    run_bench bench2 || continue
    run_stage parity_f32_s0 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 0 || continue
    run_stage parity_f32_s1 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 1 || continue
    run_stage parity_f32_s2 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_f32 2 || continue
    run_stage parity_bf16_s0 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 0 || continue
    run_stage parity_bf16_s1 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 1 || continue
    run_stage parity_bf16_s2 3600 env PARITY_PROFILE=r5 \
        python bench_train_parity.py tpu_bf16 2 || continue
    touch "$MARK/all_done"
    log "chain complete"
    exit 0
done
