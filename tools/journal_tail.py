#!/usr/bin/env python
"""Load, merge, and pretty-print persisted event journals.

``RDP_JOURNAL_PATH`` makes every process append its journal ring to a
JSONL file (observability/journal.py:JournalFile) with one bounded
rotation generation (``<path>.1``). This tool is the post-mortem half:
point it at one file per fleet member and it reconstructs the fleet
timeline -- rotation generation first, then the live file, all sources
merged by ``(unix_ts, seq)`` exactly like the front-end's live
``/debug/events`` aggregation -- so a SIGKILLed member's final moments
are readable after the process (and its debug port) are gone.

Usage::

    python tools/journal_tail.py /tmp/replica-a.jsonl /tmp/fe.jsonl
    python tools/journal_tail.py --json --kind autoscaler.action *.jsonl

Exit 0 even when some files are missing (a crashed member may never
have written one); exit 2 when NO events could be loaded.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def load_journal_file(path: str) -> list[dict]:
    """All events persisted under ``path``: the ``.1`` rotation
    generation (older) first, then the live file. Missing files and
    corrupt lines (a SIGKILL can truncate the final write) are skipped,
    not fatal."""
    events: list[dict] = []
    for candidate in (path + ".1", path):
        try:
            text = Path(candidate).read_text(encoding="utf-8")
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail write
            if isinstance(event, dict) and "kind" in event:
                event.setdefault("source", path)
                events.append(event)
    return events


def merge_journals(paths: list[str]) -> list[dict]:
    """One fleet-wide timeline: every source's events sorted by wall
    clock, with each source's own cursor breaking ties -- the same
    ordering the front-end's fleet-wide /debug/events uses."""
    merged: list[dict] = []
    for path in paths:
        merged.extend(load_journal_file(path))
    merged.sort(key=lambda e: ((e.get("unix_ts") or 0.0),
                               (e.get("seq") or 0)))
    return merged


def _format(event: dict) -> str:
    attrs = event.get("attrs") or {}
    attr_text = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    who = ":".join(p for p in (event.get("host"), event.get("role")) if p)
    parts = [
        f"{event.get('unix_ts', 0.0):.3f}",
        f"#{event.get('seq', 0)}",
        who or "-",
        event.get("kind", "?"),
    ]
    if event.get("message"):
        parts.append(event["message"])
    if attr_text:
        parts.append(attr_text)
    return "  ".join(parts)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge persisted RDP_JOURNAL_PATH JSONL journals "
                    "into one fleet timeline.")
    parser.add_argument("paths", nargs="+",
                        help="journal files (each implies its .1 "
                             "rotation generation)")
    parser.add_argument("--kind", default="",
                        help="only events whose kind contains this "
                             "substring")
    parser.add_argument("--json", action="store_true",
                        help="emit merged events as one JSON array "
                             "instead of text lines")
    parser.add_argument("--limit", type=int, default=0,
                        help="keep only the LAST N merged events")
    args = parser.parse_args(argv)

    merged = merge_journals(args.paths)
    if args.kind:
        merged = [e for e in merged if args.kind in (e.get("kind") or "")]
    if args.limit > 0:
        merged = merged[-args.limit:]
    if not merged:
        print("no events loaded", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(merged, indent=2, sort_keys=True))
    else:
        for event in merged:
            print(_format(event))
    return 0


if __name__ == "__main__":
    sys.exit(main())
