"""Autotune populate pass: measured PALLASBENCH.json geometry rows ->
per-(op, shape) impl overrides in PALLAS_TUNE.json.

PR 8 fused the non-conv analyzer stages into Pallas and made their
dispatch consult ``ops/pallas/tuning.lookup_impl(op, **dims)`` -- but the
geometry rows in PALLASBENCH.json carried ANALYTIC rooflines only (the
TPU tunnel was down), so the table never got populated. This tool closes
that loop: when ``bench_pallas.py`` has written measured ``pallas_ms`` /
``xla_ms`` for the geometry ops, it decides per (op, shape) which backend
actually wins (same >3% margin criterion as the conv autotuner -- inside
the noise band no override is written and the caller's default policy
runs) and writes the overrides ``resolve_impl`` reads.

Row hygiene mirrors ``tuning.lookup_impl``: a malformed row (missing
dims, non-numeric or non-positive timing -- the wedged-tunnel 0.0
artifact, unknown op) is REJECTED with a reason, never trusted; a bad
bench file must not turn into a serving-time dispatch veto.

Usage:
    python tools/pallas_autotune.py                 # write PALLAS_TUNE.json
    python tools/pallas_autotune.py --dry-run       # diff only, no write
    python tools/pallas_autotune.py --bench other.json --margin 0.05
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from robotic_discovery_platform_tpu.ops.pallas import tuning  # noqa: E402

#: bench row "op" -> (tune-table op as resolve_impl queries it, its dims)
GEOMETRY_OPS = {
    "deproject_edge_stats": ("deproject", ("h", "w", "stride")),
    "bspline_design": ("bspline_design", ("n", "c")),
    "bspline_curvature": ("bspline_curvature", ("n", "c")),
}

#: table-key prefixes this pass owns (stale geometry entries under these
#: prefixes are dropped on rewrite; conv3x3 tile entries are untouched)
_OWNED_PREFIXES = tuple(f"{op}:" for op, _ in GEOMETRY_OPS.values())

DEFAULT_MARGIN = 0.03  # same ">3% faster" criterion as `autotune` for conv


def _positive_ms(row: dict, key: str) -> float:
    v = row.get(key)
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        raise ValueError(f"{key} is {v!r}, not a number")
    v = float(v)
    if not math.isfinite(v) or v <= 0.0:
        # 0.0 is the wedged-tunnel artifact (BENCH_r05): reject, never
        # treat as "infinitely fast"
        raise ValueError(f"{key}={v} is not a positive finite time")
    return v


def extract_overrides(
    bench: dict, margin: float = DEFAULT_MARGIN
) -> tuple[dict, list[str]]:
    """(entries, rejected_reasons) from one PALLASBENCH.json payload.

    Entries carry both measured times so the table stays self-documenting
    evidence, exactly like the conv autotuner's entries."""
    entries: dict[str, dict] = {}
    rejected: list[str] = []
    rows = bench.get("geometry")
    if rows is None:
        rejected.append("no 'geometry' section in bench payload")
        return entries, rejected
    if not isinstance(rows, list):
        # a skipped section ({"skipped": "tunnel"}) is not an error, just
        # nothing to tune from
        rejected.append(f"'geometry' section is {type(rows).__name__}, "
                        "not a row list (skipped bench?)")
        return entries, rejected
    for i, row in enumerate(rows):
        where = f"geometry[{i}]"
        if not isinstance(row, dict):
            rejected.append(f"{where}: not an object")
            continue
        op = row.get("op")
        if op not in GEOMETRY_OPS:
            rejected.append(f"{where}: unknown op {op!r}")
            continue
        table_op, dim_names = GEOMETRY_OPS[op]
        try:
            dims = {}
            for d in dim_names:
                v = row.get(d)
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ValueError(f"dim {d!r} is {v!r}, not an int")
                dims[d] = v
            pallas_ms = _positive_ms(row, "pallas_ms")
            xla_ms = _positive_ms(row, "xla_ms")
        except ValueError as exc:
            rejected.append(f"{where} ({op}): {exc}")
            continue
        if pallas_ms < (1.0 - margin) * xla_ms:
            impl = "pallas"
        elif xla_ms < (1.0 - margin) * pallas_ms:
            impl = "xla"
        else:
            continue  # inside the noise band: no override, default policy
        entries[tuning.op_key(table_op, **dims)] = {
            "impl": impl,
            "pallas_ms": round(pallas_ms, 4),
            "xla_ms": round(xla_ms, 4),
            "speedup": round(xla_ms / pallas_ms, 3),
        }
    return entries, rejected


def merge_table(existing: dict, new_entries: dict) -> dict:
    """New table contents: every geometry-owned key is replaced by this
    pass's verdict (including DROPPING a stale override whose shape now
    measures inside the noise band); everything else -- the conv3x3 tile
    entries -- rides along untouched."""
    merged = {
        k: v for k, v in existing.items()
        if not k.startswith(_OWNED_PREFIXES)
    }
    merged.update(new_entries)
    return merged


def diff_tables(old: dict, new: dict) -> dict:
    added = sorted(set(new) - set(old))
    removed = sorted(set(old) - set(new))
    changed = sorted(
        k for k in set(new) & set(old) if new[k] != old[k]
    )
    return {"added": added, "removed": removed, "changed": changed}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Populate PALLAS_TUNE.json geometry impl overrides "
                    "from measured bench_pallas.py rows."
    )
    parser.add_argument("--bench", default=str(REPO / "PALLASBENCH.json"),
                        help="bench result file (default PALLASBENCH.json)")
    parser.add_argument("--margin", type=float, default=DEFAULT_MARGIN,
                        help="required win margin before an override is "
                             "written (default 0.03 = >3%%, the conv "
                             "autotuner's criterion)")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the table diff and write nothing")
    cli = parser.parse_args(argv)

    try:
        bench = json.loads(Path(cli.bench).read_text())
    except (FileNotFoundError, json.JSONDecodeError) as exc:
        print(json.dumps({
            "error": "bench_unreadable",
            "detail": f"{type(exc).__name__}: {exc}",
            "bench": cli.bench,
        }))
        return 1

    entries, rejected = extract_overrides(bench, cli.margin)
    for reason in rejected:
        print(f"# rejected row: {reason}", file=sys.stderr)

    existing = dict(tuning._table())
    merged = merge_table(existing, entries)
    diff = diff_tables(existing, merged)

    summary = {
        "geometry_overrides": len(entries),
        "rejected_rows": len(rejected),
        "table_entries": len(merged),
        "dry_run": bool(cli.dry_run),
        **{k: len(v) for k, v in diff.items()},
    }
    if cli.dry_run:
        for k in diff["added"]:
            print(f"# + {k} -> {merged[k]}", file=sys.stderr)
        for k in diff["changed"]:
            print(f"# ~ {k}: {existing[k]} -> {merged[k]}",
                  file=sys.stderr)
        for k in diff["removed"]:
            print(f"# - {k} (was {existing[k]})", file=sys.stderr)
        print(json.dumps({**summary, "diff": diff}))
        return 0

    meta = {}
    try:
        meta = json.loads(tuning._TUNE_PATH.read_text()).get("meta", {})
    except (FileNotFoundError, json.JSONDecodeError):
        pass
    meta["geometry_autotune"] = {
        "source": cli.bench,
        "criterion": f">{cli.margin * 100:g}% faster than the other impl",
        "rejected_rows": len(rejected),
        "written_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    path = tuning.save_entries(merged, meta)
    print(f"# wrote {path}", file=sys.stderr)
    print(json.dumps({**summary, "path": str(path), "diff": diff}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
