"""50-epoch mIoU parity on collector->replay data (TRAINBENCH_r03.json).

VERDICT round-2 item 6: the round-2 parity run (TRAINBENCH.json) used the
synthetic generator's in-memory arrays at 10 epochs; this harness runs the
reference's FULL 50-epoch config (Adam 1e-4, batch 4, BCE, 256x256, 80/20
split -- reference: scripts/train_segmenter.py:45-50,143-145) on data that
traveled the real capture path:

1. a HELD-OUT generator config (seed 42, never used in training code or
   earlier benches) renders 64 scenes at the camera's native 480x640;
2. frames are written through the collector's capture layout
   (tools/collect_data.save_pair: color/*.png + depth/*.npy) and read BACK
   through io.frames.ReplaySource -- the same bytes a real camera capture
   would replay;
3. the replayed frames pair with the generator's exact masks into the
   trainer's dataset_dir layout (the reference's
   ml/datasets/processed/{images,masks} convention);
4. the TPU `train_model` trains 50 epochs FROM DISK (the streaming
   per-batch loader, matching the reference's per-__getitem__ cv2 reads),
   and the torch reference-equivalent trains the same 50 epochs on the
   same files with the same split, scored with the same numpy mIoU.

Caveat recorded in the output: the torch anchor runs on this host's single
CPU core (torch_threads=1); the north star's "vs single-GPU" comparison is
not measurable in this image.

Usage: python bench_train_replay.py [all|data|tpu|torch]
(torch takes ~2h on this host; run it under nice, see README)
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from bench_train import dice_np, miou_np  # shared scoring

N_IMAGES = 64
IMG = 256
BATCH = 4
EPOCHS = 50
HELD_OUT_SEED = 42
SPLIT_SEED = 0
DATA_DIR = REPO / "ml" / "datasets" / "replay_parity"
OUT = REPO / "TRAINBENCH_r03.json"


def build_replay_dataset(out_dir: Path = DATA_DIR) -> Path:
    """Held-out scenes -> collector capture -> replay -> labeled dataset."""
    import tempfile

    import cv2

    from robotic_discovery_platform_tpu.io.frames import ReplaySource
    from robotic_discovery_platform_tpu.tools import collect_data
    from robotic_discovery_platform_tpu.training.synthetic import render_scene

    rng = np.random.default_rng(HELD_OUT_SEED)
    masks = []
    with tempfile.TemporaryDirectory() as tmp:
        run_dir = collect_data.new_capture_dir(tmp)
        for i in range(N_IMAGES):
            img_rgb, mask, depth = render_scene(rng, 480, 640)
            collect_data.save_pair(run_dir, i, img_rgb[..., ::-1], depth)
            masks.append(mask)

        # Read the capture BACK through the replay source -- the dataset
        # images are the post-roundtrip bytes, exactly what a real capture
        # session would yield.
        (out_dir / "images").mkdir(parents=True, exist_ok=True)
        (out_dir / "masks").mkdir(parents=True, exist_ok=True)
        source = ReplaySource(run_dir, loop=False)
        source.start()
        i = 0
        while True:
            color_bgr, _depth = source.get_frames()
            if color_bgr is None:
                break
            stem = f"replay_{i:06d}.png"
            cv2.imwrite(str(out_dir / "images" / stem), color_bgr)
            cv2.imwrite(str(out_dir / "masks" / stem), masks[i])
            i += 1
    assert i == N_IMAGES, (i, N_IMAGES)
    return out_dir


def _steady_state(epoch_times) -> dict:
    """Contention-robust epoch rate for both legs on this shared 1-core
    host: the 25th percentile of per-epoch times (the median is still
    contended if another process ran during >half the epochs, which is
    exactly the scenario this guards against). Residual asymmetry, noted
    wherever the fair ratio is quoted: TPU epochs include a per-epoch
    validation pass the torch loop lacks (it validates once at the end),
    so the fair ratio is biased AGAINST the TPU."""
    if not epoch_times:
        return {}
    p25 = float(np.percentile(np.asarray(epoch_times), 25))
    return {
        "steady_state_epoch_s": round(p25, 2),
        "steady_state_wall_clock_s": round(p25 * EPOCHS, 2),
    }


def bench_tpu(data_dir: Path) -> dict:
    import tempfile

    import jax

    from robotic_discovery_platform_tpu.training import trainer
    from robotic_discovery_platform_tpu.utils.config import (
        ModelConfig,
        TrainConfig,
    )

    with tempfile.TemporaryDirectory() as tmp:
        cfg = TrainConfig(
            epochs=EPOCHS, batch_size=BATCH, img_size=IMG,
            learning_rate=1e-4, seed=SPLIT_SEED, validation_split=0.2,
            dataset_dir=str(data_dir),
            tracking_uri=f"file:{tmp}/mlruns", checkpoint_dir=f"{tmp}/ckpt",
            # the torch anchor checkpoints nothing; every 10 epochs keeps
            # the comparison fair while preserving real durability
            checkpoint_every=10,
        )
        res = trainer.train_model(cfg, ModelConfig(), register=False)
    return {
        "backend": jax.default_backend(),
        "epochs": EPOCHS,
        "wall_clock_s": round(res.wall_clock_s, 2),
        "epoch_s": round(res.wall_clock_s / EPOCHS, 2),
        **_steady_state(res.epoch_seconds),
        "val_miou": round(res.final_metrics.get("miou", float("nan")), 4),
        "val_dice": round(res.final_metrics.get("dice", float("nan")), 4),
        "best_val_loss": round(res.best_val_loss, 5),
    }


def bench_torch(data_dir: Path) -> dict:
    """Reference-equivalent 50-epoch torch run on the same files and split,
    reading per batch from disk each epoch like the reference's
    num_workers=0 DataLoader (train_segmenter.py:138-139)."""
    import torch

    from bench_reference import build_torch_unet
    from robotic_discovery_platform_tpu.training import data as data_lib

    torch.set_num_threads(1)  # this host has one core; recorded as caveat
    ds = data_lib.PairedSegmentationData(data_dir, IMG)
    n = len(ds)
    tr, va = data_lib.train_val_split(n, 0.2, SPLIT_SEED)

    def load_batch(idx):
        xs = np.zeros((len(idx), 3, IMG, IMG), np.float32)
        ys = np.zeros((len(idx), 1, IMG, IMG), np.float32)
        for j, i in enumerate(idx):
            x, y = ds.load(ds.names[i])  # same decode semantics both runs
            xs[j] = x.transpose(2, 0, 1)
            ys[j] = y.transpose(2, 0, 1)
        return torch.from_numpy(xs), torch.from_numpy(ys)

    model = build_torch_unet().train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-4)
    loss_fn = torch.nn.BCEWithLogitsLoss()
    shuffle_rng = np.random.default_rng(SPLIT_SEED)
    epoch_times = []
    t0 = time.perf_counter()
    for epoch in range(EPOCHS):
        t_e = time.perf_counter()
        order = shuffle_rng.permutation(tr)
        for i in range(0, len(order), BATCH):
            x, y = load_batch(order[i:i + BATCH])
            opt.zero_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()
        epoch_times.append(time.perf_counter() - t_e)
        print(f"torch epoch {epoch + 1}/{EPOCHS} "
              f"({time.perf_counter() - t0:.0f}s)", flush=True)
    wall = time.perf_counter() - t0
    model.eval()
    probs, targs = [], []
    with torch.no_grad():
        for i in range(0, len(va), BATCH):
            x, y = load_batch(va[i:i + BATCH])
            probs.append(torch.sigmoid(model(x)).numpy())
            targs.append(y.numpy())
    prob = np.concatenate(probs)
    targ = np.concatenate(targs)
    return {
        "backend": "torch-cpu",
        "torch_threads": 1,
        "epochs": EPOCHS,
        "wall_clock_s": round(wall, 2),
        "epoch_s": round(wall / EPOCHS, 2),
        **_steady_state(epoch_times),
        "val_miou": round(miou_np(prob, targ), 4),
        "val_dice": round(dice_np(prob, targ), 4),
    }


def main() -> None:
    only = sys.argv[1] if len(sys.argv) > 1 else "all"
    result = json.loads(OUT.read_text()) if OUT.exists() else {}
    result.setdefault("config", {
        "n_images": N_IMAGES, "img_size": IMG, "batch_size": BATCH,
        "epochs": EPOCHS, "optimizer": "adam(1e-4)", "loss": "bce",
        "validation_split": 0.2,
        "data": "held-out generator (seed 42) -> collector capture layout "
                "-> ReplaySource roundtrip -> dataset_dir files; both runs "
                "read the same files with the same decode and split",
        "caveat": "torch anchor is single-thread CPU (this host has one "
                  "core); the north star's single-GPU anchor is not "
                  "measurable in this image",
    })
    if only in ("all", "data") or not DATA_DIR.exists():
        build_replay_dataset()
        print(f"replay dataset at {DATA_DIR}", flush=True)
    if only in ("all", "tpu"):
        result["tpu_50epoch"] = bench_tpu(DATA_DIR)
        print(json.dumps(result["tpu_50epoch"]), flush=True)
    if only in ("all", "torch"):
        result["torch_50epoch"] = bench_torch(DATA_DIR)
        print(json.dumps(result["torch_50epoch"]), flush=True)
    if "tpu_50epoch" in result and "torch_50epoch" in result:
        tpu, tor = result["tpu_50epoch"], result["torch_50epoch"]
        # raw ratio of as-measured wall-clocks (both possibly contended)
        result["speedup_wall_clock"] = round(
            tor["wall_clock_s"] / tpu["wall_clock_s"], 2,
        )
        # contention-robust ratio when both legs carry steady-state rates;
        # drop any previous value first so a partial rerun cannot leave a
        # fair ratio that no longer matches the recorded legs
        result.pop("speedup_wall_clock_fair", None)
        if ("steady_state_wall_clock_s" in tor
                and "steady_state_wall_clock_s" in tpu):
            result["speedup_wall_clock_fair"] = round(
                tor["steady_state_wall_clock_s"]
                / tpu["steady_state_wall_clock_s"], 2,
            )
        result["miou_delta"] = round(
            result["tpu_50epoch"]["val_miou"]
            - result["torch_50epoch"]["val_miou"], 4,
        )
    result["measured_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    OUT.write_text(json.dumps(result, indent=2))
    print(json.dumps({k: v for k, v in result.items() if k != "config"},
                     indent=1))


if __name__ == "__main__":
    main()
