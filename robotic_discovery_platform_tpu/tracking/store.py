"""File-backed experiment tracking + model registry store.

The reference delegates all experiment tracking and model lifecycle to MLflow
with a local file store (reference: scripts/train_segmenter.py:61-63,112-115,
183-207; workflows/retraining_pipeline.py:50-74; services/vision_analysis/
server.py:62-82). MLflow is not part of this framework's substrate, so this
module provides the same *contract* -- experiments, runs, params, per-step
metrics, registered model versions, and aliases -- as plain JSON/JSONL under
the tracking root. The public API layer (tracking/api.py) exposes it with
MLflow-shaped functions, and every name the reference uses ("Actuator
Segmentation", "Actuator-Segmenter", train_loss/val_loss, the "staging"
alias) round-trips byte-identically.

Layout::

    <root>/
      experiments.json                  {name: experiment_id}
      runs/<run_id>/meta.json           run status/times/experiment
      runs/<run_id>/params.json
      runs/<run_id>/metrics/<key>.jsonl lines: {"step": s, "value": v, "ts": t}
      runs/<run_id>/artifacts/...
      registry/<model>/versions.json    [{"version": n, "run_id": ..., ...}]
      registry/<model>/aliases.json     {alias: version}
      registry/<model>/<version>/       model artifact directory
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
from pathlib import Path


def _resolve_uri(uri: str) -> Path:
    if uri.startswith("file://"):
        return Path(uri[len("file://"):])
    if uri.startswith("file:"):
        return Path(uri[len("file:"):])
    return Path(uri)


class FileStore:
    """All mutating operations are guarded by a process-local lock and use
    atomic JSON rewrites (tmp + rename); metric appends are O(1) JSONL."""

    def __init__(self, uri: str):
        self.root = _resolve_uri(uri)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    # -- json helpers -------------------------------------------------------

    def _read(self, path: Path, default):
        try:
            return json.loads(path.read_text())
        except FileNotFoundError:
            return default

    def _write(self, path: Path, obj) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(obj, indent=2, sort_keys=True))
        tmp.replace(path)

    # -- experiments --------------------------------------------------------

    def get_or_create_experiment(self, name: str) -> str:
        with self._lock:
            path = self.root / "experiments.json"
            exps = self._read(path, {})
            if name not in exps:
                exps[name] = str(len(exps))
                self._write(path, exps)
            return exps[name]

    def list_experiments(self) -> dict:
        return dict(self._read(self.root / "experiments.json", {}))

    # -- runs ---------------------------------------------------------------

    def _run_dir(self, run_id: str) -> Path:
        return self.root / "runs" / run_id

    def create_run(self, experiment_id: str, run_name: str | None = None) -> str:
        run_id = uuid.uuid4().hex
        meta = {
            "run_id": run_id,
            "run_name": run_name or run_id[:8],
            "experiment_id": experiment_id,
            "status": "RUNNING",
            "start_time": time.time(),
            "end_time": None,
        }
        with self._lock:
            self._write(self._run_dir(run_id) / "meta.json", meta)
        return run_id

    def end_run(self, run_id: str, status: str = "FINISHED") -> None:
        with self._lock:
            path = self._run_dir(run_id) / "meta.json"
            meta = self._read(path, {})
            meta.update(status=status, end_time=time.time())
            self._write(path, meta)

    def get_run(self, run_id: str) -> dict:
        meta = self._read(self._run_dir(run_id) / "meta.json", None)
        if meta is None:
            raise KeyError(f"no such run: {run_id}")
        return meta

    def log_params(self, run_id: str, params: dict) -> None:
        with self._lock:
            path = self._run_dir(run_id) / "params.json"
            cur = self._read(path, {})
            cur.update({k: str(v) for k, v in params.items()})
            self._write(path, cur)

    def get_params(self, run_id: str) -> dict:
        return self._read(self._run_dir(run_id) / "params.json", {})

    def log_metric(self, run_id: str, key: str, value: float,
                   step: int | None = None) -> None:
        path = self._run_dir(run_id) / "metrics" / f"{key}.jsonl"
        path.parent.mkdir(parents=True, exist_ok=True)
        line = json.dumps(
            {"step": step, "value": float(value), "ts": time.time()}
        )
        with self._lock, open(path, "a") as f:
            f.write(line + "\n")

    def get_metric_history(self, run_id: str, key: str) -> list[dict]:
        path = self._run_dir(run_id) / "metrics" / f"{key}.jsonl"
        try:
            return [json.loads(l) for l in path.read_text().splitlines() if l]
        except FileNotFoundError:
            return []

    def artifact_dir(self, run_id: str) -> Path:
        d = self._run_dir(run_id) / "artifacts"
        d.mkdir(parents=True, exist_ok=True)
        return d

    # -- model registry -----------------------------------------------------

    def _model_dir(self, name: str) -> Path:
        return self.root / "registry" / name

    def create_model_version(self, name: str, run_id: str | None,
                             source_dir: Path | None = None) -> int:
        """Register a new integer version (MLflow semantics: versions count up
        per model name, reference: workflows/retraining_pipeline.py:60-66).
        Copies ``source_dir`` into the registry as the durable artifact."""
        with self._lock:
            vpath = self._model_dir(name) / "versions.json"
            versions = self._read(vpath, [])
            version = 1 + max((v["version"] for v in versions), default=0)
            dest = self._model_dir(name) / str(version)
            if source_dir is not None:
                if dest.exists():
                    shutil.rmtree(dest)
                shutil.copytree(source_dir, dest)
            versions.append(
                {
                    "version": version,
                    "run_id": run_id,
                    "created": time.time(),
                    "path": str(dest),
                }
            )
            self._write(vpath, versions)
            return version

    def list_model_versions(self, name: str) -> list[dict]:
        return self._read(self._model_dir(name) / "versions.json", [])

    def latest_version(self, name: str) -> dict:
        versions = self.list_model_versions(name)
        if not versions:
            raise KeyError(f"registered model {name!r} has no versions")
        return max(versions, key=lambda v: v["version"])

    def set_alias(self, name: str, alias: str, version: int) -> None:
        """reference: workflows/retraining_pipeline.py:69-75
        (set_registered_model_alias(name, "staging", version))."""
        with self._lock:
            known = {v["version"] for v in self.list_model_versions(name)}
            if int(version) not in known:
                raise KeyError(f"model {name!r} has no version {version}")
            apath = self._model_dir(name) / "aliases.json"
            aliases = self._read(apath, {})
            aliases[alias] = int(version)
            self._write(apath, aliases)

    def get_alias(self, name: str, alias: str) -> int | None:
        aliases = self._read(self._model_dir(name) / "aliases.json", {})
        return aliases.get(alias)

    def version_path(self, name: str, version: int) -> Path:
        path = self._model_dir(name) / str(version)
        if not path.exists():
            raise KeyError(f"model {name!r} version {version} has no artifacts")
        return path
