"""Dependency-free MLflow REST tracking/registry backend.

The reference's deployments run a real MLflow *server* (reference:
scripts/train_segmenter.py:33,112-129 -- ``mlflow ui`` browses the same
store). tracking/mlflow_backend.py adapts to that via the ``mlflow`` client
package, but that package is an optional extra; this module speaks MLflow's
documented REST surface directly over HTTP (``/api/2.0/mlflow/...`` plus the
``mlflow-artifacts`` proxy a ``mlflow server --serve-artifacts`` deployment
exposes), so a framework process can log to / load from a genuine MLflow
tracking server with no mlflow dependency at all.

Backend selection (tracking/api._make_store): ``http(s)://`` URIs prefer the
mlflow-client adapter when the package is importable and fall back to this
store otherwise; ``mlflow-rest+http(s)://`` forces this store.

Protocol parity: every method mirrors FileStore/MlflowStore (store.py /
mlflow_backend.py) -- the contract tests drive all three through the same
surface, and tests/fake_mlflow_server.py exercises this one over a real
socket.
"""

from __future__ import annotations

import os
import posixpath
import tempfile
import time
from pathlib import Path

import requests

from robotic_discovery_platform_tpu.observability import instruments as obs
from robotic_discovery_platform_tpu.resilience import (
    Deadline,
    RetryPolicy,
    inject,
)
from robotic_discovery_platform_tpu.resilience import (
    sites as fault_sites,
)
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

_API = "/api/2.0/mlflow"
_ARTIFACTS = "/api/2.0/mlflow-artifacts/artifacts"

# Fault-injection site covering every HTTP round-trip this store makes
# (tracking API calls and artifact proxy transfers alike); see
# resilience/faults.py for the RDP_FAULTS spec grammar.
FAULT_SITE = fault_sites.TRACKING_REST_REQUEST


def _resolve_retry() -> RetryPolicy:
    """RDP_HTTP_RETRIES / RDP_HTTP_BACKOFF_S resolver. Transient HTTP
    failures (ConnectionError/timeout, 429, 5xx) retry with jittered
    exponential backoff; env-tunable so chaos tests (and latency-
    sensitive deployments) reshape the schedule without code."""
    return RetryPolicy(
        max_attempts=int(os.environ.get("RDP_HTTP_RETRIES", "3")),
        base_delay_s=float(os.environ.get("RDP_HTTP_BACKOFF_S", "0.2")),
        max_delay_s=5.0,
    )


def _resolve_deadline_s(timeout_s: float) -> float:
    """RDP_HTTP_DEADLINE_S resolver: overall per-call budget including
    retries; defaults to twice the single-request timeout."""
    return float(os.environ.get("RDP_HTTP_DEADLINE_S",
                                str(2.0 * timeout_s)))


class MlflowRestError(RuntimeError):
    """An MLflow REST call failed; carries the server's error_code."""

    def __init__(self, status: int, error_code: str, message: str):
        super().__init__(f"{error_code} (HTTP {status}): {message}")
        self.status = status
        self.error_code = error_code


class RestMlflowStore:
    """FileStore-protocol adapter speaking MLflow's REST API directly."""

    def __init__(self, uri: str, timeout_s: float = 30.0,
                 retry: RetryPolicy | None = None,
                 deadline_s: float | None = None):
        self.uri = uri.rstrip("/")
        self.timeout_s = timeout_s
        # ``timeout_s`` bounds ONE socket-level request; ``deadline_s`` is
        # the overall budget for one logical call *including* its retries,
        # so a flaky server cannot stretch a single resolve to
        # retries * timeout.
        self.deadline_s = (
            deadline_s if deadline_s is not None
            else _resolve_deadline_s(timeout_s)
        )
        self._retry = retry if retry is not None else _resolve_retry()
        self._http = requests.Session()
        self._make_scratch()

    def _make_scratch(self) -> None:
        import shutil
        import weakref

        self._scratch = Path(tempfile.mkdtemp(prefix="rdp-mlflow-rest-"))
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, str(self._scratch), True
        )

    def _ensure_scratch(self) -> Path:
        # same lazy-recreate semantics as MlflowStore._ensure_scratch
        if not self._scratch.exists():
            self._make_scratch()
        return self._scratch

    def close(self) -> None:
        """Remove the artifact staging scratch directory; the store remains
        usable (scratch is lazily recreated)."""
        self._cleanup()
        self._http.close()

    # -- transport ----------------------------------------------------------

    def _retrying(self, what: str, fn):
        """One logical REST operation: every attempt shares a Deadline
        budget, transient failures (connection errors, timeouts, 429, 5xx
        -- resilience.default_retryable) back off and retry, and the
        underlying error surfaces unchanged once the policy gives up.
        Every attempt (retries included) lands one sample in the
        rdp_http_request_seconds histogram, by outcome."""
        deadline = Deadline.after(self.deadline_s, self._retry.clock)

        def on_retry(attempt: int, exc: BaseException, delay: float):
            log.warning(
                "transient failure on %s (%s: %s); retry %d in %.2fs",
                what, type(exc).__name__, exc, attempt, delay,
            )

        def timed_attempt():
            t0 = time.perf_counter()
            try:
                out = fn()
            except BaseException:
                obs.HTTP_REQUESTS.labels(outcome="error").observe(
                    time.perf_counter() - t0
                )
                raise
            obs.HTTP_REQUESTS.labels(outcome="ok").observe(
                time.perf_counter() - t0
            )
            return out

        return self._retry.call(timed_attempt, deadline=deadline,
                                on_retry=on_retry, name=FAULT_SITE)

    def _call(self, method: str, endpoint: str, *, params=None, body=None):
        def attempt():
            inject(FAULT_SITE)
            resp = self._http.request(
                method, f"{self.uri}{_API}/{endpoint}", params=params,
                json=body, timeout=self.timeout_s,
            )
            if resp.status_code >= 400:
                try:
                    err = resp.json()
                except ValueError:
                    err = {}
                raise MlflowRestError(
                    resp.status_code,
                    err.get("error_code", "INTERNAL_ERROR"),
                    err.get("message", resp.text[:200]),
                )
            return resp.json() if resp.content else {}

        return self._retrying(f"{method} {endpoint}", attempt)

    # -- experiments / runs -------------------------------------------------

    def get_or_create_experiment(self, name: str) -> str:
        try:
            out = self._call("GET", "experiments/get-by-name",
                             params={"experiment_name": name})
            return out["experiment"]["experiment_id"]
        except MlflowRestError as e:
            if e.error_code != "RESOURCE_DOES_NOT_EXIST":
                raise
        return self._call("POST", "experiments/create",
                          body={"name": name})["experiment_id"]

    def create_run(self, experiment_id: str,
                   run_name: str | None = None) -> str:
        tags = ([{"key": "mlflow.runName", "value": run_name}]
                if run_name else [])
        out = self._call("POST", "runs/create", body={
            "experiment_id": experiment_id,
            "start_time": int(time.time() * 1e3),
            "tags": tags,
        })
        return out["run"]["info"]["run_id"]

    def end_run(self, run_id: str, status: str = "FINISHED") -> None:
        self._call("POST", "runs/update", body={
            "run_id": run_id, "status": status,
            "end_time": int(time.time() * 1e3),
        })

    def _get_run_raw(self, run_id: str) -> dict:
        return self._call("GET", "runs/get",
                          params={"run_id": run_id})["run"]

    def get_run(self, run_id: str) -> dict:
        # same key shape as FileStore.create_run meta (store.py:90-97)
        info = self._get_run_raw(run_id)["info"]
        return {
            "run_id": run_id,
            "run_name": info.get("run_name"),
            "experiment_id": info["experiment_id"],
            "status": info.get("status"),
            "start_time": int(info.get("start_time") or 0) / 1e3,
            "end_time": (int(info["end_time"]) / 1e3
                         if info.get("end_time") else None),
        }

    # -- params / metrics ---------------------------------------------------

    def log_params(self, run_id: str, params: dict) -> None:
        self._call("POST", "runs/log-batch", body={
            "run_id": run_id,
            "params": [{"key": str(k), "value": str(v)}
                       for k, v in params.items()],
        })

    def get_params(self, run_id: str) -> dict:
        data = self._get_run_raw(run_id).get("data", {})
        return {p["key"]: p["value"] for p in data.get("params", [])}

    def log_metric(self, run_id: str, key: str, value: float,
                   step: int | None = None) -> None:
        self._call("POST", "runs/log-metric", body={
            "run_id": run_id, "key": key, "value": float(value),
            "timestamp": int(time.time() * 1e3),
            "step": 0 if step is None else int(step),
        })

    def get_metric_history(self, run_id: str, key: str) -> list[dict]:
        out = self._call("GET", "metrics/get-history",
                         params={"run_id": run_id, "metric_key": key})
        # "ts" in seconds, matching FileStore.log_metric (store.py:130)
        return [
            {"step": int(m.get("step", 0)), "value": m["value"],
             "ts": int(m.get("timestamp", 0)) / 1e3}
            for m in out.get("metrics", [])
        ]

    # -- artifacts ----------------------------------------------------------

    def artifact_dir(self, run_id: str) -> Path:
        """Local staging dir; finalized by ``publish_artifacts``."""
        d = self._ensure_scratch() / run_id
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _artifact_http_path(self, artifact_uri: str, *parts: str) -> str:
        """Map an ``mlflow-artifacts:/...`` run artifact root (what the
        tracking server hands out under --serve-artifacts) onto the REST
        proxy path."""
        if not artifact_uri.startswith("mlflow-artifacts:/"):
            raise MlflowRestError(
                400, "INVALID_PARAMETER_VALUE",
                f"artifact uri {artifact_uri!r} is not served over the "
                "mlflow-artifacts REST proxy; run the tracking server "
                "with --serve-artifacts or install the mlflow client extra",
            )
        rel = artifact_uri[len("mlflow-artifacts:/"):].strip("/")
        return posixpath.join(rel, *parts)

    def publish_artifacts(self, run_id: str, local_dir: Path) -> None:
        local_dir = Path(local_dir)
        root = self._get_run_raw(run_id)["info"]["artifact_uri"]
        for f in sorted(local_dir.rglob("*")):
            if not f.is_file():
                continue
            rel = posixpath.join(local_dir.name,
                                 f.relative_to(local_dir).as_posix())
            path = self._artifact_http_path(root, rel)
            data = f.read_bytes()

            def put_attempt(path=path, data=data):
                inject(FAULT_SITE)
                resp = self._http.put(
                    f"{self.uri}{_ARTIFACTS}/{path}", data=data,
                    timeout=self.timeout_s,
                )
                if resp.status_code >= 400:
                    raise MlflowRestError(resp.status_code, "INTERNAL_ERROR",
                                          resp.text[:200])

            # artifact PUTs are idempotent (same bytes, same path), so a
            # lost-response retry is safe
            self._retrying(f"PUT artifact {path}", put_attempt)

    def _artifact_get(self, what: str, url: str, params=None):
        def attempt():
            inject(FAULT_SITE)
            resp = self._http.get(url, params=params,
                                  timeout=self.timeout_s)
            if resp.status_code >= 400:
                raise MlflowRestError(resp.status_code, "INTERNAL_ERROR",
                                      resp.text[:200])
            return resp

        return self._retrying(what, attempt)

    def _download_tree(self, http_root: str, dest: Path) -> None:
        listing = self._artifact_get(
            f"LIST artifacts {http_root}", f"{self.uri}{_ARTIFACTS}",
            params={"path": http_root},
        )
        for entry in listing.json().get("files", []):
            # per the proxy contract, entry["path"] is relative to the
            # queried directory
            sub = posixpath.join(http_root, entry["path"])
            if entry.get("is_dir"):
                self._download_tree(sub, dest / entry["path"])
                continue
            resp = self._artifact_get(f"GET artifact {sub}",
                                      f"{self.uri}{_ARTIFACTS}/{sub}")
            out = dest / entry["path"]
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_bytes(resp.content)

    # -- registry -----------------------------------------------------------

    def create_model_version(self, name: str, run_id: str | None,
                             artifact_dir: Path) -> int:
        source = posixpath.join(
            self._get_run_raw(run_id)["info"]["artifact_uri"],
            Path(artifact_dir).name,
        )
        try:
            self._call("POST", "registered-models/create",
                       body={"name": name})
        except MlflowRestError as e:
            if e.error_code != "RESOURCE_ALREADY_EXISTS":
                raise
        out = self._call("POST", "model-versions/create", body={
            "name": name, "source": source, "run_id": run_id,
        })
        return int(out["model_version"]["version"])

    def list_model_versions(self, name: str) -> list[dict]:
        out = self._call("GET", "model-versions/search",
                         params={"filter": f"name='{name}'"})
        return sorted(
            (
                {
                    "version": int(v["version"]),
                    "run_id": v.get("run_id"),
                    "stage": v.get("current_stage") or "None",
                }
                for v in out.get("model_versions", [])
            ),
            key=lambda v: v["version"],
        )

    def latest_version(self, name: str) -> dict:
        versions = self.list_model_versions(name)
        if not versions:
            raise KeyError(f"registered model {name!r} has no versions")
        return versions[-1]

    def set_alias(self, name: str, alias: str, version: int) -> None:
        self._call("POST", "registered-models/alias", body={
            "name": name, "alias": alias, "version": str(version),
        })

    def get_alias(self, name: str, alias: str) -> int | None:
        try:
            out = self._call("GET", "registered-models/alias",
                             params={"name": name, "alias": alias})
        except MlflowRestError as e:
            # only "no such alias/model" means None; connectivity/auth
            # failures must surface, not masquerade as a missing alias
            if e.error_code in ("RESOURCE_DOES_NOT_EXIST",
                                "INVALID_PARAMETER_VALUE"):
                return None
            raise
        return int(out["model_version"]["version"])

    def version_path(self, name: str, version: int) -> Path:
        """Download the registry version's model artifacts to a local dir."""
        out = self._call("GET", "model-versions/get",
                         params={"name": name, "version": str(version)})
        source = out["model_version"]["source"]
        dest = self._ensure_scratch() / "downloads" / name / str(version)
        dest.mkdir(parents=True, exist_ok=True)
        self._download_tree(self._artifact_http_path(source), dest)
        return dest
