"""MLflow-shaped tracking API over the file store.

Drop-in for the subset of the MLflow surface the reference exercises:
``set_tracking_uri`` / ``set_experiment`` / ``start_run`` / ``log_params`` /
``log_metric`` (reference: scripts/train_segmenter.py:112-129,183-191),
model logging + registration (:195-207), ``MlflowClient.get_latest_versions``
and ``set_registered_model_alias`` (reference: workflows/
retraining_pipeline.py:50-74), and ``load_model("models:/Name/latest" |
"models:/Name@alias" | "models:/Name/3")`` (reference: services/
vision_analysis/server.py:81-82 plus README.md:147's documented staging-alias
intent).

Model artifacts are Flax variable trees serialized with
``flax.serialization`` plus a JSON model config, so a registry entry is
self-describing: ``load_model`` rebuilds the Flax module and returns
``(model, variables)``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import re
import threading
from pathlib import Path
from types import SimpleNamespace

from robotic_discovery_platform_tpu.tracking.store import FileStore
from robotic_discovery_platform_tpu.utils.config import ModelConfig, from_dict

_DEFAULT_URI = "file:ml/mlruns"

# Process-global like real MLflow (the gRPC server's worker threads must see
# the URI the main thread configured); guarded for concurrent mutation.
_state = SimpleNamespace(
    uri=_DEFAULT_URI, store=None, experiment_id="0", active_run=None
)
_state_lock = threading.Lock()


def _globals():
    return _state


def set_tracking_uri(uri: str) -> None:
    with _state_lock:
        _state.uri = uri
        _state.store = None


def get_tracking_uri() -> str:
    return _globals().uri


def _make_store(uri: str):
    """URI-scheme backend selection: the dependency-free FileStore by
    default; for tracking-server URIs, the mlflow-client adapter
    (tracking/mlflow_backend.py) when the ``mlflow`` extra is installed,
    else the dependency-free REST client (tracking/rest_backend.py).
    ``mlflow+<uri>`` forces the client adapter, ``mlflow-rest+http(s)://``
    forces the REST client."""
    scheme = uri.split(":", 1)[0]
    if uri.startswith("mlflow-rest+"):
        from robotic_discovery_platform_tpu.tracking.rest_backend import (
            RestMlflowStore)

        return RestMlflowStore(uri[len("mlflow-rest+"):])
    if scheme in ("http", "https") or uri.startswith(("databricks", "mlflow+")):
        bare = uri[len("mlflow+"):] if uri.startswith("mlflow+") else uri
        try:
            from robotic_discovery_platform_tpu.tracking.mlflow_backend import (
                MlflowStore)

            return MlflowStore(bare)
        except ImportError:
            if scheme not in ("http", "https"):
                raise  # databricks/mlflow+file etc. need the real client
            from robotic_discovery_platform_tpu.tracking.rest_backend import (
                RestMlflowStore)

            return RestMlflowStore(bare)
    return FileStore(uri)


def _store() -> FileStore:
    with _state_lock:
        if _state.store is None:
            _state.store = _make_store(_state.uri)
        return _state.store


def set_experiment(name: str) -> str:
    g = _globals()
    g.experiment_id = _store().get_or_create_experiment(name)
    return g.experiment_id


class ActiveRun:
    """Mimics ``mlflow.ActiveRun``: has ``.info.run_id``."""

    class _Info:
        def __init__(self, run_id: str):
            self.run_id = run_id

    def __init__(self, run_id: str):
        self.info = self._Info(run_id)


@contextlib.contextmanager
def start_run(run_name: str | None = None):
    g = _globals()
    run_id = _store().create_run(g.experiment_id, run_name)
    g.active_run = ActiveRun(run_id)
    try:
        yield g.active_run
        _store().end_run(run_id, "FINISHED")
    except Exception:
        _store().end_run(run_id, "FAILED")
        raise
    finally:
        g.active_run = None


def active_run() -> ActiveRun | None:
    return _globals().active_run


def _require_run() -> str:
    run = active_run()
    if run is None:
        raise RuntimeError("no active run; wrap calls in tracking.start_run()")
    return run.info.run_id


def log_params(params: dict) -> None:
    _store().log_params(_require_run(), params)


def log_param(key: str, value) -> None:
    log_params({key: value})


def log_metric(key: str, value: float, step: int | None = None) -> None:
    _store().log_metric(_require_run(), key, value, step)


def log_metrics(metrics: dict, step: int | None = None) -> None:
    for k, v in metrics.items():
        log_metric(k, v, step)


def get_metric_history(run_id: str, key: str) -> list[dict]:
    return _store().get_metric_history(run_id, key)


# ---------------------------------------------------------------------------
# Model logging / registry
# ---------------------------------------------------------------------------

_MODEL_CONFIG_FILE = "model_config.json"
_MODEL_WEIGHTS_FILE = "variables.msgpack"


def save_model(variables, model_cfg: ModelConfig, path: Path) -> None:
    """Write a self-describing model artifact directory."""
    from flax import serialization

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    (path / _MODEL_CONFIG_FILE).write_text(
        json.dumps(dataclasses.asdict(model_cfg), indent=2)
    )
    (path / _MODEL_WEIGHTS_FILE).write_bytes(serialization.to_bytes(variables))


def load_model_dir(path: Path):
    """Load (model, variables) from an artifact directory."""
    from flax import serialization

    from robotic_discovery_platform_tpu.models.unet import build_unet, init_unet

    path = Path(path)
    cfg = from_dict(ModelConfig, json.loads((path / _MODEL_CONFIG_FILE).read_text()))
    model = build_unet(cfg)
    import jax

    template = init_unet(model, jax.random.key(0))
    variables = serialization.from_bytes(
        template, (path / _MODEL_WEIGHTS_FILE).read_bytes()
    )
    return model, variables


def log_model(variables, model_cfg: ModelConfig, artifact_path: str = "model",
              registered_model_name: str | None = None) -> int | None:
    """Save the model under the active run's artifacts and optionally register
    a new version (the reference's ``mlflow.pytorch.log_model(...,
    registered_model_name=...)`` flow, train_segmenter.py:200-206).

    Returns the new registry version when registered.
    """
    run_id = _require_run()
    store = _store()
    dest = store.artifact_dir(run_id) / artifact_path
    save_model(variables, model_cfg, dest)
    # remote backends (MlflowStore) stage locally, then upload to the run
    if hasattr(store, "publish_artifacts"):
        store.publish_artifacts(run_id, dest)
    if registered_model_name is None:
        return None
    return store.create_model_version(registered_model_name, run_id, dest)


_MODEL_URI = re.compile(
    r"^models:/(?P<name>[^/@]+)(?:/(?P<version>latest|\d+)|@(?P<alias>[\w-]+))?$"
)


def store_for(tracking_uri: str):
    """A store instance SCOPED to ``tracking_uri``, without touching the
    process-global tracking state. Background threads (the serving
    hot-reload poller) must use this: ``set_tracking_uri`` from a thread
    would silently re-point every other component's tracking mid-run."""
    return _make_store(tracking_uri)


def resolve_model_uri(uri: str, store=None) -> Path:
    """models:/Name/latest | models:/Name/3 | models:/Name@staging -> path.

    ``store`` defaults to the process-global one; pass ``store_for(uri)``
    for a scoped lookup.
    """
    m = _MODEL_URI.match(uri)
    if not m:
        raise ValueError(f"unsupported model uri: {uri!r}")
    name = m.group("name")
    store = _store() if store is None else store
    if m.group("alias"):
        version = store.get_alias(name, m.group("alias"))
        if version is None:
            raise KeyError(f"model {name!r} has no alias {m.group('alias')!r}")
    elif m.group("version") and m.group("version") != "latest":
        version = int(m.group("version"))
    else:
        version = store.latest_version(name)["version"]
    return store.version_path(name, version)


def load_model(uri: str, store=None):
    """Load (model, variables) from a ``models:/`` uri or a plain path."""
    if uri.startswith("models:/"):
        return load_model_dir(resolve_model_uri(uri, store=store))
    return load_model_dir(Path(uri))


class ModelVersionInfo:
    """Mimics mlflow's ModelVersion for the fields the reference touches
    (retraining_pipeline.py:60-66: ``.version``)."""

    def __init__(self, name: str, version: int, run_id: str | None):
        self.name = name
        self.version = version
        self.run_id = run_id


class Client:
    """Registry client with the reference's MlflowClient call shapes."""

    def get_latest_versions(self, name: str, stages=None) -> list[ModelVersionInfo]:
        """MLflow semantics: latest version per requested stage. A version's
        stage is "None" until transitioned (the reference promotes via the
        *alias* flow, retraining_pipeline.py:69-75, so stages stay "None"
        unless a version record carries an explicit ``stage`` field)."""
        if stages is None:
            v = _store().latest_version(name)
            return [ModelVersionInfo(name, v["version"], v.get("run_id"))]
        versions = _store().list_model_versions(name)
        if not versions:
            raise KeyError(f"registered model {name!r} has no versions")
        out = []
        for stage in stages:
            staged = [v for v in versions if v.get("stage", "None") == stage]
            if staged:
                v = max(staged, key=lambda v: v["version"])
                out.append(ModelVersionInfo(name, v["version"], v.get("run_id")))
        return out

    def set_registered_model_alias(self, name: str, alias: str, version) -> None:
        _store().set_alias(name, alias, int(version))

    def get_model_version_by_alias(self, name: str, alias: str) -> ModelVersionInfo:
        version = _store().get_alias(name, alias)
        if version is None:
            raise KeyError(f"model {name!r} has no alias {alias!r}")
        return ModelVersionInfo(name, version, None)

    def list_versions(self, name: str) -> list[dict]:
        return _store().list_model_versions(name)
