"""Real-MLflow backend for the tracking API (optional extra).

The framework's default tracking store is the dependency-free ``FileStore``
(store.py) exposing the MLflow call surface the reference exercises. This
module provides the same store protocol backed by an *actual* MLflow
tracking server / registry, so deployments already running MLflow (the
reference's setup: scripts/train_segmenter.py:112-129,195-207, browsed via
``mlflow ui`` per its README) can point the framework at it unchanged.

Backend selection is by tracking URI (see ``api._make_store``):

- ``file:...``            -> FileStore (default, no extra deps)
- ``http(s)://...``       -> MlflowStore against a tracking server
- ``databricks...``       -> MlflowStore
- ``mlflow+<uri>``        -> MlflowStore against any MLflow-supported URI
                             (e.g. ``mlflow+file:ml/mlruns`` uses MLflow's
                             own local file store -- handy for ``mlflow ui``)

Requires the ``mlflow`` extra (pyproject.toml); importing this module
without mlflow installed raises a clear ImportError.

Artifact flow: the api writes model files into a local scratch dir
(``artifact_dir``), then ``publish_artifacts`` uploads them to the run, and
``create_model_version`` registers ``runs:/<run_id>/<artifact_path>`` --
exactly the reference's ``mlflow.pytorch.log_model(...,
registered_model_name=...)`` shape. ``version_path`` downloads a registry
version's artifacts so ``load_model("models:/Name@staging")`` works
identically over both backends.
"""

from __future__ import annotations

import tempfile
from pathlib import Path

try:
    import mlflow
    from mlflow.exceptions import MlflowException
    from mlflow.tracking import MlflowClient
except ImportError as e:  # pragma: no cover - exercised only without mlflow
    raise ImportError(
        "the real-MLflow tracking backend needs the 'mlflow' extra "
        "(pip install robotic-discovery-platform-tpu[mlflow]); the default "
        "file: backend has no such dependency"
    ) from e


class MlflowStore:
    """FileStore-protocol adapter over a real MLflow client."""

    def __init__(self, uri: str):
        self.uri = uri
        self.client = MlflowClient(tracking_uri=uri, registry_uri=uri)
        self._make_scratch()

    def _make_scratch(self) -> None:
        import shutil
        import weakref

        self._scratch = Path(tempfile.mkdtemp(prefix="rdp-mlflow-artifacts-"))
        # long-lived processes (serving, repeated runs) must not accumulate
        # model-sized staging directories in /tmp: reclaim on GC/interpreter
        # exit, or explicitly via close()
        self._cleanup = weakref.finalize(
            self, shutil.rmtree, str(self._scratch), True
        )

    def _ensure_scratch(self) -> Path:
        # The store stays usable after close(): artifact-staging methods
        # lazily recreate the scratch dir (with a fresh finalizer -- the
        # old one is one-shot, so a bare mkdir would leak the new dir and,
        # worse, a post-close log_model would die mid-way on the missing
        # staging path).
        if not self._scratch.exists():
            self._make_scratch()
        return self._scratch

    def close(self) -> None:
        """Remove the artifact staging scratch directory. The store remains
        usable; a later staging operation recreates scratch lazily."""
        self._cleanup()

    # -- experiments / runs -------------------------------------------------

    def get_or_create_experiment(self, name: str) -> str:
        exp = self.client.get_experiment_by_name(name)
        if exp is not None:
            return exp.experiment_id
        return self.client.create_experiment(name)

    def create_run(self, experiment_id: str, run_name: str | None = None) -> str:
        tags = {"mlflow.runName": run_name} if run_name else {}
        return self.client.create_run(experiment_id, tags=tags).info.run_id

    def end_run(self, run_id: str, status: str = "FINISHED") -> None:
        self.client.set_terminated(run_id, status=status)

    def get_run(self, run_id: str) -> dict:
        # same key shape as FileStore.create_run meta (store.py:90-97)
        run = self.client.get_run(run_id)
        return {
            "run_id": run_id,
            "run_name": run.info.run_name,
            "experiment_id": run.info.experiment_id,
            "status": run.info.status,
            "start_time": (run.info.start_time or 0) / 1e3,
            "end_time": (run.info.end_time / 1e3
                         if run.info.end_time else None),
        }

    # -- params / metrics ---------------------------------------------------

    def log_params(self, run_id: str, params: dict) -> None:
        for k, v in params.items():
            self.client.log_param(run_id, k, v)

    def get_params(self, run_id: str) -> dict:
        return dict(self.client.get_run(run_id).data.params)

    def log_metric(self, run_id: str, key: str, value: float,
                   step: int | None = None) -> None:
        self.client.log_metric(run_id, key, float(value),
                               step=0 if step is None else int(step))

    def get_metric_history(self, run_id: str, key: str) -> list[dict]:
        # "ts" in seconds, matching FileStore.log_metric (store.py:130)
        return [
            {"step": m.step, "value": m.value, "ts": m.timestamp / 1e3}
            for m in self.client.get_metric_history(run_id, key)
        ]

    # -- artifacts / registry ----------------------------------------------

    def artifact_dir(self, run_id: str) -> Path:
        """Local staging dir; finalized by ``publish_artifacts``."""
        d = self._ensure_scratch() / run_id
        d.mkdir(parents=True, exist_ok=True)
        return d

    def publish_artifacts(self, run_id: str, local_dir: Path) -> None:
        local_dir = Path(local_dir)
        self.client.log_artifacts(run_id, str(local_dir),
                                  artifact_path=local_dir.name)

    def create_model_version(self, name: str, run_id: str | None,
                             artifact_dir: Path) -> int:
        # Client-side registration against self.uri. The fluent
        # ``mlflow.register_model`` would resolve the *process-global*
        # tracking URI (never set by this adapter) and miss the configured
        # backend entirely.
        source = (f"{self.client.get_run(run_id).info.artifact_uri}/"
                  f"{Path(artifact_dir).name}")
        try:
            self.client.create_registered_model(name)
        except MlflowException:
            pass  # already registered
        version = self.client.create_model_version(name, source=source,
                                                   run_id=run_id)
        return int(version.version)

    def list_model_versions(self, name: str) -> list[dict]:
        versions = self.client.search_model_versions(f"name='{name}'")
        return sorted(
            (
                {
                    "version": int(v.version),
                    "run_id": v.run_id,
                    "stage": getattr(v, "current_stage", None) or "None",
                }
                for v in versions
            ),
            key=lambda v: v["version"],
        )

    def latest_version(self, name: str) -> dict:
        versions = self.list_model_versions(name)
        if not versions:
            raise KeyError(f"registered model {name!r} has no versions")
        return versions[-1]

    def set_alias(self, name: str, alias: str, version: int) -> None:
        self.client.set_registered_model_alias(name, alias, str(version))

    def get_alias(self, name: str, alias: str) -> int | None:
        try:
            v = self.client.get_model_version_by_alias(name, alias)
        except MlflowException as e:
            # only "no such alias/model" means None; connectivity/auth
            # failures must surface, not masquerade as a missing alias
            if e.error_code in ("RESOURCE_DOES_NOT_EXIST",
                                "INVALID_PARAMETER_VALUE"):
                return None
            raise
        return int(v.version)

    def version_path(self, name: str, version: int) -> Path:
        """Download the registry version's model artifacts to a local dir."""
        dest = self._ensure_scratch() / "downloads" / name / str(version)
        dest.mkdir(parents=True, exist_ok=True)
        source = self.client.get_model_version(name, str(version)).source
        local = mlflow.artifacts.download_artifacts(
            artifact_uri=source, dst_path=str(dest), tracking_uri=self.uri
        )
        return Path(local)
