"""Flax U-Net for binary actuator segmentation, TPU-first.

Same architecture family as the reference PyTorch model
(reference: pkg/segmentation_model.py:24-120): DoubleConv blocks of
(3x3 conv, no bias -> norm -> ReLU) x 2, a 4-level encoder with 2x2
max-pooling, a decoder with bilinear upsampling (default) or transposed
convolution, pad-free skip fusion, and a 1x1 output head. Channel ladder
64 -> 128 -> 256 -> 512 -> 1024//factor with factor = 2 when bilinear
(the deployed configuration -- the reference instantiates ``UNet(3, 1)``
everywhere, e.g. scripts/train_segmenter.py:143).

TPU-first design departures (deliberate, not omissions):
- **NHWC layout** -- the native layout for XLA TPU convolutions (the
  reference is NCHW because cuDNN prefers it).
- **bfloat16 compute, float32 params** via ``dtype``/``param_dtype`` so
  convs hit the MXU at full rate; the output head is cast back to f32.
- **Resize-to-skip upsampling**: instead of the reference's pad-then-concat
  (segmentation_model.py:67-76) the decoder resizes the upsampled feature
  map directly to the skip's spatial shape -- identical result for even
  sizes, and shape-safe for odd sizes without dynamic padding.
- Optional **GroupNorm** (``norm="group"``) as a batch-size-independent
  alternative to BatchNorm for small per-device batches under data
  parallelism; ``norm="batch"`` matches the reference semantics and is the
  default.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.utils.config import ModelConfig

DType = Any


@shape_contract(x="b ih iw c")
def upsample_align_corners(x, h: int, w: int):
    """Bilinear 2D resize with ``align_corners=True`` sampling -- the exact
    semantics of the reference decoder's ``nn.Upsample(scale_factor=2,
    mode="bilinear", align_corners=True)`` (pkg/segmentation_model.py:58-60).

    ``jax.image.resize`` samples half-pixel centers (align_corners=False),
    a subtly different grid; matching torch's grid exactly is what lets
    trained reference checkpoints import with bit-comparable outputs
    (tools/import_torch_weights.py, tests/test_torch_parity.py).

    Implemented as two small dense interpolation matmuls over the static
    spatial dims -- MXU-friendly, fuses cleanly under jit.
    """
    b, ih, iw, c = x.shape

    def interp_matrix(out: int, inp: int):
        if out == 1 or inp == 1:
            pos = np.zeros((out,))
        else:
            pos = np.arange(out) * (inp - 1) / (out - 1)
        i0 = np.clip(np.floor(pos).astype(int), 0, inp - 1)
        i1 = np.minimum(i0 + 1, inp - 1)
        frac = (pos - i0).astype(np.float32)
        m = np.zeros((out, inp), np.float32)
        np.add.at(m, (np.arange(out), i0), 1.0 - frac)
        np.add.at(m, (np.arange(out), i1), frac)
        return jnp.asarray(m, x.dtype)

    y = jnp.einsum("Hh,bhwc->bHwc", interp_matrix(h, ih), x,
                   preferred_element_type=jnp.float32)
    y = jnp.einsum("Ww,bhwc->bhWc", interp_matrix(w, iw), y,
                   preferred_element_type=jnp.float32)
    return y.astype(x.dtype)


def _kernel_init(init: str):
    """Conv kernel initializer family.

    ``"torch"`` reproduces torch ``Conv2d``'s default
    ``kaiming_uniform_(a=sqrt(5))`` (reference models are built with it:
    pkg/segmentation_model.py:30-33 uses plain ``nn.Conv2d``): gain
    ``sqrt(2/(1+5)) = sqrt(1/3)`` over fan_in with a uniform distribution,
    i.e. ``U(+-sqrt(1/fan_in))`` -- exactly
    ``variance_scaling(1/3, "fan_in", "uniform")``. Matching the init
    family makes seed-for-seed training comparisons against the torch
    anchor fair (round-3 verdict item 1). ``"lecun"`` is the Flax default.
    """
    if init == "torch":
        return nn.initializers.variance_scaling(
            1.0 / 3.0, "fan_in", "uniform"
        )
    if init == "lecun":
        return nn.initializers.lecun_normal()
    raise ValueError(f"unknown init {init!r}")


def _bias_init(init: str, fan_in: int):
    """torch ``Conv2d`` bias default is ``U(+-1/sqrt(fan_in))``; Flax's is
    zeros. fan_in is known statically at call time (in_features * kh * kw)."""
    if init != "torch":
        return nn.initializers.zeros_init()
    bound = 1.0 / float(np.sqrt(fan_in))

    def initializer(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return initializer


def _norm(norm: str, dtype: DType, train: bool, features: int):
    if norm == "batch":
        # momentum 0.9 matches the reference's torch BatchNorm2d default
        # (momentum=0.1 on the *new* batch, i.e. 0.9 decay on the running
        # value; pkg/segmentation_model.py:35). Flax's own default of 0.99
        # leaves running stats ~30% initialization after a 120-step run,
        # which wrecks eval-mode predictions on short trainings.
        return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                            dtype=dtype)
    if norm == "group":
        import math

        return nn.GroupNorm(num_groups=math.gcd(32, features), dtype=dtype)
    raise ValueError(f"unknown norm {norm!r}")


class TrainConv3x3(nn.Module):
    """3x3 SAME no-bias conv backed by the custom-VJP Pallas kernels
    (ops/pallas/conv.conv3x3: Pallas forward, Pallas dx and dw) so the
    TRAINING step's hot op runs hand-written kernels too, not only the
    folded inference path. Same parameter name/shape as ``nn.Conv``
    ("kernel", [3, 3, Cin, Cout]), so checkpoints, torch-weight import,
    and the PallasUNet variable walk are layout-identical.

    The custom-VJP path engages only under ``train=True``: inference
    consumers of ``model.apply`` keep the plain XLA conv (per-layer
    Pallas/XLA mixing measures ~24% slower end-to-end, and the Pallas
    serving path is the uniformly-fused ``PallasUNet``, not this module).
    """

    features: int
    dtype: DType = jnp.bfloat16
    kernel_init: Any = nn.initializers.lecun_normal()
    impl: str = "auto"  # custom-VJP dispatch: auto | pallas | xla | interpret

    @nn.compact
    def __call__(self, x, train: bool = False):
        from robotic_discovery_platform_tpu.ops.pallas import conv as pconv

        kernel = self.param(
            "kernel", self.kernel_init,
            (3, 3, x.shape[-1], self.features), jnp.float32,
        )
        x = x.astype(self.dtype)
        kernel = kernel.astype(self.dtype)
        if train:
            return pconv.conv3x3(x, kernel, self.impl)
        y = jax.lax.conv_general_dilated(
            x, kernel, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )
        return y.astype(x.dtype)


class DoubleConv(nn.Module):
    """(3x3 conv no-bias -> norm -> ReLU) x 2
    (reference: pkg/segmentation_model.py:24-40).

    ``conv_impl="flax"`` uses ``nn.Conv`` (XLA convs end to end);
    anything else routes the convs through :class:`TrainConv3x3`'s
    custom-VJP Pallas kernels with that dispatch mode.
    """

    features: int
    mid_features: int | None = None
    norm: str = "batch"
    dtype: DType = jnp.bfloat16
    weight_init: str = "torch"
    conv_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train: bool = False):
        mid = self.mid_features or self.features
        kinit = _kernel_init(self.weight_init)

        def conv(features, name, y):
            if self.conv_impl == "flax":
                return nn.Conv(features, (3, 3), padding="SAME",
                               use_bias=False, dtype=self.dtype,
                               kernel_init=kinit, name=name)(y)
            return TrainConv3x3(features, dtype=self.dtype,
                                kernel_init=kinit, impl=self.conv_impl,
                                name=name)(y, train)

        x = conv(mid, "Conv_0", x)
        x = _norm(self.norm, self.dtype, train, mid)(x)
        x = nn.relu(x)
        x = conv(self.features, "Conv_1", x)
        x = _norm(self.norm, self.dtype, train, self.features)(x)
        return nn.relu(x)


class Down(nn.Module):
    """2x2 max-pool then DoubleConv (reference: pkg/segmentation_model.py:42-52)."""

    features: int
    norm: str = "batch"
    dtype: DType = jnp.bfloat16
    weight_init: str = "torch"
    conv_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return DoubleConv(self.features, norm=self.norm, dtype=self.dtype,
                          weight_init=self.weight_init,
                          conv_impl=self.conv_impl)(x, train)


class Up(nn.Module):
    """Upsample, fuse with the skip, DoubleConv
    (reference: pkg/segmentation_model.py:54-76).

    ``bilinear=True`` (deployed default) resizes by interpolation and gives
    the DoubleConv a halved mid-channel width; otherwise a 2x2 stride-2
    transposed conv halves the channel count before fusion.
    """

    features: int
    bilinear: bool = True
    norm: str = "batch"
    dtype: DType = jnp.bfloat16
    weight_init: str = "torch"
    conv_impl: str = "flax"

    @nn.compact
    def __call__(self, x, skip, train: bool = False):
        b, h, w, c = skip.shape
        if self.bilinear:
            # align_corners=True grid, matching the reference decoder exactly
            x = upsample_align_corners(x, h, w)
            mid = (x.shape[3] + c) // 2
            x = jnp.concatenate([skip, x.astype(skip.dtype)], axis=-1)
            return DoubleConv(self.features, mid_features=mid,
                              norm=self.norm, dtype=self.dtype,
                              weight_init=self.weight_init,
                              conv_impl=self.conv_impl)(x, train)
        in_ch = x.shape[3]
        # torch ConvTranspose2d computes init fan_in over weight dim 1
        # (out_channels) * kh * kw = (in_ch // 2) * 4 -- for BOTH kernel
        # and bias. variance_scaling's "fan_in" would use in_ch * kh * kw
        # (Flax ConvTranspose kernels are (kh, kw, in, out)), a bound
        # sqrt(2) too small here, so the kernel uses the same explicit
        # U(+-1/sqrt(fan)) closure as the bias.
        tfan = (in_ch // 2) * 4
        x = nn.ConvTranspose(
            in_ch // 2, (2, 2), strides=(2, 2), dtype=self.dtype,
            kernel_init=(_bias_init("torch", tfan)
                         if self.weight_init == "torch"
                         else _kernel_init(self.weight_init)),
            bias_init=_bias_init(self.weight_init, tfan),
        )(x)
        x = jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), method="nearest")
        x = jnp.concatenate([skip, x.astype(skip.dtype)], axis=-1)
        return DoubleConv(self.features, norm=self.norm, dtype=self.dtype,
                          weight_init=self.weight_init,
                          conv_impl=self.conv_impl)(x, train)


class UNet(nn.Module):
    """Encoder/decoder U-Net (reference: pkg/segmentation_model.py:86-120).

    Call with NHWC input; returns NHWC logits in float32.
    """

    num_classes: int = 1
    base_features: int = 64
    bilinear: bool = True
    norm: str = "batch"
    dtype: DType = jnp.bfloat16
    in_features: int = 3  # used by init helpers; convs infer from input
    weight_init: str = "torch"
    conv_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self.base_features
        factor = 2 if self.bilinear else 1
        x = x.astype(self.dtype)
        kw = dict(norm=self.norm, dtype=self.dtype,
                  weight_init=self.weight_init, conv_impl=self.conv_impl)
        x1 = DoubleConv(f, **kw)(x, train)
        x2 = Down(f * 2, **kw)(x1, train)
        x3 = Down(f * 4, **kw)(x2, train)
        x4 = Down(f * 8, **kw)(x3, train)
        x5 = Down(f * 16 // factor, **kw)(x4, train)
        y = Up(f * 8 // factor, self.bilinear, **kw)(x5, x4, train)
        y = Up(f * 4 // factor, self.bilinear, **kw)(y, x3, train)
        y = Up(f * 2 // factor, self.bilinear, **kw)(y, x2, train)
        y = Up(f, self.bilinear, **kw)(y, x1, train)
        # 1x1 head: the only conv with a bias (reference OutConv,
        # pkg/segmentation_model.py:78-84); fan_in = in_features * 1 * 1
        logits = nn.Conv(
            self.num_classes, (1, 1), dtype=self.dtype,
            kernel_init=_kernel_init(self.weight_init),
            bias_init=_bias_init(self.weight_init, y.shape[-1]),
        )(y)
        return logits.astype(jnp.float32)


def with_compute_dtype(model: UNet, dtype: DType) -> UNet:
    """A clone of ``model`` whose activations compute in ``dtype`` (params
    stay float32 -- ``param_dtype`` is untouched). The serving precision
    tiers (ops/pallas/quant.apply_precision) use this to force bf16
    activations regardless of how the checkpoint was trained; the variable
    tree is layout-identical so trained variables bind unchanged."""
    return model.clone(dtype=jnp.dtype(dtype))


def build_unet(cfg: ModelConfig = ModelConfig()) -> UNet:
    return UNet(
        num_classes=cfg.num_classes,
        base_features=cfg.base_features,
        bilinear=cfg.bilinear,
        norm=cfg.norm,
        dtype=jnp.dtype(cfg.compute_dtype),
        in_features=cfg.in_channels,
        weight_init=cfg.init,
        conv_impl=cfg.conv_impl,
    )


def init_unet(model: UNet, rng, img_size: int = 256):
    """Initialize variables with a dummy batch; returns the variable dict
    (``params`` + ``batch_stats`` when BatchNorm is used)."""
    dummy = jnp.zeros((1, img_size, img_size, model.in_features), jnp.float32)
    return model.init(rng, dummy, train=False)


def param_count(variables) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(variables.get("params", variables)))
