"""The model-zoo variant catalog: the named engine generations a serving
process can hold side by side (serving/zoo.py).

The platform's seed workload is ONE binary actuator segmenter per fleet.
The zoo breaks that pairing: a server advertises M named variants, each
with its own registry entry, precision tier, golden-frame parity gate,
and drift reference, statistically multiplexed over the shared chip mesh
(AlpaServe, PAPERS.md). This module is the catalog half -- pure
declarations plus builders, importable without jax so config resolution
and the bench can reason about variants before any device exists.

Variants shipped:

- ``seg``   -- the seed binary segmenter (the default model; empty
  ``AnalysisRequest.model`` on the wire resolves here, so pre-zoo
  clients interoperate unchanged). Registry entry: the server's
  configured ``model_name`` ("Actuator-Segmenter").
- ``multi`` -- the multi-actuator variant: the same U-Net family with a
  K-channel multi-label head (``ModelConfig.num_classes > 1``; each
  channel is one actuator class, a pixel joins the union mask when ANY
  class fires -- ops/pipeline handles C > 1 heads natively now).
- ``aux``   -- the cheap defect/anomaly auxiliary head: a quarter-width
  U-Net whose per-frame anomaly score is derived from the confidence
  margin the fused graph already computes (mean |sigmoid - 0.5|; a
  model far from its decision boundary across the frame is surprised by
  its input). Designed to ride along at near-zero marginal cost --
  exactly the model whose load peaks anti-correlate with the heavy
  segmenter's and make shared placement pay.

``ServerConfig.zoo_models`` / ``RDP_ZOO_MODELS`` pick the set ("" = the
default single-model server, bitwise-identical to the pre-zoo path).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from robotic_discovery_platform_tpu.utils.config import ModelConfig

_ZOO_ENV_VAR = "RDP_ZOO_MODELS"

#: the variant an empty wire ``model`` field resolves to
DEFAULT_MODEL = "seg"

#: head semantics: "segment" serves the mask/curvature contract as-is;
#: "anomaly" additionally derives a per-frame anomaly score from the
#: confidence margin and reports it in the response status
HEADS = ("segment", "anomaly")


@dataclass(frozen=True)
class ModelVariant:
    """One zoo catalog entry (declaration only; engines are built by the
    serving layer per generation)."""

    name: str
    #: registered-model name in the tracking registry; None = the
    #: server's configured ``ServerConfig.model_name`` (the seed entry)
    registered_name: str | None
    #: output channels of the 1x1 head (1 = binary; K > 1 = multi-label
    #: multi-actuator classes)
    num_classes: int
    #: channel-width multiplier on ``ModelConfig.base_features`` --
    #: sub-1 variants are the cheap ride-along heads
    width_scale: float
    head: str
    description: str

    def model_config(self, base: ModelConfig) -> ModelConfig:
        """The variant's ModelConfig derived from the serving base
        config (dtype/norm/init ride along unchanged)."""
        from robotic_discovery_platform_tpu.utils.config import replace

        features = max(4, int(round(base.base_features * self.width_scale)))
        return replace(base, num_classes=self.num_classes,
                       base_features=features)


VARIANTS: dict[str, ModelVariant] = {
    "seg": ModelVariant(
        name="seg", registered_name=None, num_classes=1, width_scale=1.0,
        head="segment",
        description="seed binary actuator segmenter (the default model)",
    ),
    "multi": ModelVariant(
        name="multi", registered_name="Actuator-Segmenter-Multi",
        num_classes=4, width_scale=1.0, head="segment",
        description="multi-actuator segmenter: 4-channel multi-label "
                    "head, union mask over classes",
    ),
    "aux": ModelVariant(
        name="aux", registered_name="Actuator-AuxHead", num_classes=1,
        width_scale=0.25, head="anomaly",
        description="cheap defect/anomaly head scoring off the "
                    "confidence margin",
    ),
}


def resolve_zoo_models(configured: str) -> tuple[str, ...]:
    """The effective zoo roster: ``RDP_ZOO_MODELS`` when set, else
    ``ServerConfig.zoo_models``; a comma-separated list of variant names.
    Empty = the single default model (the legacy server, bitwise path).
    The default model is always first (and always present): the empty
    wire ``model`` field must resolve somewhere."""
    raw = os.environ.get(_ZOO_ENV_VAR)
    spec = raw if raw is not None else configured
    names = [n.strip() for n in (spec or "").split(",") if n.strip()]
    if not names:
        return (DEFAULT_MODEL,)
    unknown = [n for n in names if n not in VARIANTS]
    if unknown:
        raise ValueError(
            f"unknown zoo model(s) {unknown}; catalog: "
            f"{sorted(VARIANTS)}"
        )
    ordered = [DEFAULT_MODEL] + [n for n in names if n != DEFAULT_MODEL]
    # preserve request order after the pinned default, dropping dups
    seen: set[str] = set()
    return tuple(n for n in ordered if not (n in seen or seen.add(n)))


def registered_name(variant: ModelVariant, default_model_name: str) -> str:
    """The registry entry this variant's generations resolve through."""
    return (variant.registered_name if variant.registered_name is not None
            else default_model_name)


def build_variant_model(variant: ModelVariant, base: ModelConfig):
    """Build the variant's (uninitialized) Flax module."""
    from robotic_discovery_platform_tpu.models.unet import build_unet

    return build_unet(variant.model_config(base))


def anomaly_score(confidence_margin: float) -> float:
    """Per-frame defect/anomaly score off the confidence margin: the
    margin is mean |sigmoid(logit) - 0.5| in [0, 0.5] (0 = every pixel
    sits on the decision boundary -- the model has no idea what it is
    looking at; 0.5 = saturated confidence). The score flips that to
    [0, 1] where 1 = maximally anomalous, so dashboards and the drift
    monitor read it the intuitive way up."""
    m = min(max(float(confidence_margin), 0.0), 0.5)
    return 1.0 - 2.0 * m
