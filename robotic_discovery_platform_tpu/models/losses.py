"""Segmentation losses and evaluation metrics.

The reference trains with ``BCEWithLogitsLoss`` only and never computes any
overlap metric (reference: scripts/train_segmenter.py:145; SURVEY.md section
2.1 "no accuracy/IoU/Dice anywhere"). Capability parity keeps BCE as the
default loss; the Dice term (BASELINE.json config 2) and the IoU/Dice/accuracy
metrics are new -- they exist precisely because the rebuild must demonstrate
"equal mIoU" against a baseline that never measured it.

All functions are pure jax.numpy on logits/labels of shape [..., H, W, C].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bce_with_logits(logits, labels):
    """Mean binary cross-entropy on logits (numerically stable form:
    max(x,0) - x*z + log1p(exp(-|x|)), the same formulation torch uses)."""
    x, z = logits, labels.astype(logits.dtype)
    per = jnp.maximum(x, 0.0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return jnp.mean(per)


def dice_loss(logits, labels, eps: float = 1.0):
    """Soft Dice loss (1 - Dice coefficient on sigmoid probabilities)."""
    p = jax.nn.sigmoid(logits)
    z = labels.astype(logits.dtype)
    axes = (-3, -2, -1)  # per-sample reduce over H, W, C; mean over leading dims
    inter = jnp.sum(p * z, axis=axes)
    denom = jnp.sum(p, axis=axes) + jnp.sum(z, axis=axes)
    dice = (2.0 * inter + eps) / (denom + eps)
    return jnp.mean(1.0 - dice)


def bce_dice(logits, labels, dice_weight: float = 0.5):
    return (1.0 - dice_weight) * bce_with_logits(logits, labels) + (
        dice_weight
    ) * dice_loss(logits, labels)


def make_loss_fn(name: str, dice_weight: float = 0.5):
    if name == "bce":
        return bce_with_logits
    if name == "dice":
        return dice_loss
    if name == "bce_dice":
        return lambda lg, lb: bce_dice(lg, lb, dice_weight)
    raise ValueError(f"unknown loss {name!r}")


# ---------------------------------------------------------------------------
# Metrics (hard masks at threshold 0.5, matching the serving threshold --
# reference: services/vision_analysis/server.py:124)
# ---------------------------------------------------------------------------


def binary_iou(logits, labels, threshold: float = 0.5, eps: float = 1e-7):
    """Foreground IoU per batch, scalar mean."""
    pred = jax.nn.sigmoid(logits) > threshold
    z = labels > 0.5
    axes = (-3, -2, -1)
    inter = jnp.sum(pred & z, axis=axes).astype(jnp.float32)
    union = jnp.sum(pred | z, axis=axes).astype(jnp.float32)
    return jnp.mean((inter + eps) / (union + eps))


def mean_iou(logits, labels, threshold: float = 0.5, eps: float = 1e-7):
    """mIoU over {background, foreground} -- the parity metric
    (BASELINE.md: 'equal mIoU')."""
    pred = jax.nn.sigmoid(logits) > threshold
    z = labels > 0.5
    axes = (-3, -2, -1)

    def iou(a, b):
        inter = jnp.sum(a & b, axis=axes).astype(jnp.float32)
        union = jnp.sum(a | b, axis=axes).astype(jnp.float32)
        return (inter + eps) / (union + eps)

    return jnp.mean(0.5 * (iou(pred, z) + iou(~pred, ~z)))


def dice_coefficient(logits, labels, threshold: float = 0.5, eps: float = 1e-7):
    pred = jax.nn.sigmoid(logits) > threshold
    z = labels > 0.5
    axes = (-3, -2, -1)
    inter = jnp.sum(pred & z, axis=axes).astype(jnp.float32)
    total = jnp.sum(pred, axis=axes) + jnp.sum(z, axis=axes)
    return jnp.mean((2.0 * inter + eps) / (total.astype(jnp.float32) + eps))


def pixel_accuracy(logits, labels, threshold: float = 0.5):
    pred = jax.nn.sigmoid(logits) > threshold
    z = labels > 0.5
    return jnp.mean((pred == z).astype(jnp.float32))
