"""Mask + depth -> point cloud -> edge -> B-spline -> curvature, as one
static-shape jax.numpy pipeline.

TPU-first redesign of the reference geometry engine
(reference: pkg/geometry_utils.py:42-162). Every data-dependent construct in
the reference -- ``np.where`` gathers, per-bin Python loops with variable
``k = max(1, int(0.05 * n))``, early-return empty arrays, FITPACK exceptions
-- becomes masked fixed-shape code so the whole profile runs (and fuses with
the U-Net forward pass) inside a single jitted XLA graph:

- dense deprojection over the full H x W grid instead of a gather
  (reference :101-117);
- edge extraction as ONE lexicographic sort of the dense maps by
  (x-bin, -y): each bin becomes a contiguous descending-y segment, so the
  reference's per-bin "top 5% by y" (:134-140) is the head of each segment
  -- masked by a dynamic cutoff ``k_b`` over a static ``max_per_bin``
  budget. No intermediate compaction, no data-dependent shapes;
- a fixed-knot penalized least-squares B-spline instead of ``splprep``
  (see ops/bspline.py; reference :78);
- graceful-zero semantics via flags instead of early returns: <100 cloud
  points, <50 points for binning, zero x-range, or <20 edge points all yield
  a zeroed, ``valid=False`` result (reference :64-70, :121-128, :95-97).

The public entry point is :func:`compute_curvature_profile`; it is shape-
polymorphic in (H, W) at trace time but fully static once traced.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.ops import bspline
from robotic_discovery_platform_tpu.utils.config import GeometryConfig


class CurvatureProfile(NamedTuple):
    """Fixed-shape analogue of the reference ``CurvatureResult`` dataclass
    (reference: pkg/geometry_utils.py:35-40). ``valid`` replaces the empty-
    result convention; when False every other field is zeroed."""

    mean_curvature: jnp.ndarray  # scalar
    max_curvature: jnp.ndarray  # scalar
    spline_points: jnp.ndarray  # [num_samples, 3]
    valid: jnp.ndarray  # scalar bool
    num_cloud_points: jnp.ndarray  # scalar int (diagnostics)
    num_edge_points: jnp.ndarray  # scalar int (diagnostics)
    truncated: jnp.ndarray  # scalar bool: per-bin max_per_bin budget bound


@shape_contract(mask="h w", depth="h w")
def deproject(mask, depth, fx, fy, cx, cy, depth_scale, stride: int = 1):
    """Pinhole deprojection over the dense grid (reference :101-117).

    Returns per-pixel (x, y, z) maps plus a validity map; no gathers.
    ``stride`` > 1 means mask/depth are an s x s pooled view of the native
    frame: iota coordinates scale by ``stride`` and point at each cell's
    CENTER ((s-1)/2 offset), which is unbiased for a pooled value that may
    come from anywhere in the cell (corner coordinates would skew every
    point up to s-1 native pixels toward the top-left).
    """
    h, w = depth.shape
    dtype = jnp.float32
    off = (stride - 1) / 2.0
    v = jax.lax.broadcasted_iota(dtype, (h, w), 0) * stride + off
    u = jax.lax.broadcasted_iota(dtype, (h, w), 1) * stride + off
    z = depth.astype(dtype) * jnp.asarray(depth_scale, dtype)
    valid = (mask > 0) & (z > 0)
    x = (u - cx) * z / fx
    y = (v - cy) * z / fy
    return x, y, z, valid


def _edge_points(x, y, z, valid, cfg: GeometryConfig, stats=None):
    """Static-shape re-expression of ``_find_point_cloud_edge``
    (reference :119-142), operating directly on the dense deprojection
    maps: bin x into ``num_bins`` equal bins over the valid x-range, keep
    the top ``max(1, floor(0.05 * n_b))`` points by y per bin.

    One lexicographic sort of the flattened maps by (bin, -y) replaces both
    the fixed-budget cloud compaction and per-bin top_k passes of earlier
    designs (the hot spot: 50 batched top_k(128) over a 65536-point cloud
    cost ~2.3 ms/frame on v5e, plus ~1 ms for the compaction's own top_k;
    the single sort does the whole job in under 2 ms with no cloud-size
    budget at all). After the sort each bin is a contiguous descending-y
    segment, so "top k_b by y" is the head of each segment.

    ``stats`` optionally carries pre-computed ``(x_min, x_max, y_min,
    y_max, n_valid)`` -- the fused Pallas deproject kernel produces them
    in its single pass over the maps; without it the reductions run here
    (the XLA reference path). Min/max/integer-count are order-independent,
    so both sources are bitwise-identical values.

    Returns ([num_bins * max_per_bin, 3] points, matching weights,
    edge_count, binnable flag, per-bin-cap flag).
    """
    xs = x.reshape(-1)
    ys = y.reshape(-1)
    v = valid.reshape(-1)
    big = jnp.float32(1e30)
    if stats is not None:
        x_min, x_max, y_min_s, y_max_s, n_valid = stats
    else:
        x_min = jnp.min(jnp.where(v, xs, big))
        x_max = jnp.max(jnp.where(v, xs, -big))
        y_min_s = y_max_s = None
        n_valid = jnp.sum(v)
    bin_width = (x_max - x_min) / cfg.num_bins
    binnable = (n_valid >= cfg.num_bins) & (bin_width > 0)

    safe_width = jnp.where(bin_width > 0, bin_width, 1.0)
    bin_idx = jnp.clip(
        jnp.floor((xs - x_min) / safe_width).astype(jnp.int32), 0, cfg.num_bins - 1
    )

    p = xs.shape[0]
    # ONE packed int32 sort key: (bin << 25) | quantize(descending y, 25b).
    # A single-key sort halves the comparator work of the previous
    # (bin, -y) two-key sort -- the sort is the whole pipeline's hot spot.
    # 25 bits across the frame's valid y-range (<= 51 * 2^25 < 2^31) keeps
    # ~15 nm selection resolution at 0.5 m spans: quantization can only
    # reorder exact physical ties, which the reference's argpartition also
    # breaks arbitrarily (reference :134-140).
    if (cfg.num_bins + 1) << 25 >= 2**31:
        raise ValueError(
            f"num_bins={cfg.num_bins} overflows the packed int32 sort key "
            "(needs (num_bins + 1) << 25 < 2^31, i.e. num_bins <= 62)"
        )
    shift = jnp.int32(1 << 25)
    y_min = (y_min_s if y_min_s is not None
             else jnp.min(jnp.where(v, ys, big)))
    y_max = (y_max_s if y_max_s is not None
             else jnp.max(jnp.where(v, ys, -big)))
    q_scale = ((1 << 25) - 1) / jnp.maximum(y_max - y_min, 1e-12)
    # Clip in FLOAT before the int cast: for a degenerate flat scene
    # (y_max ~ y_min) q_scale ~ 3.4e19 and the product overflows int32,
    # whose out-of-range convert is implementation-defined (saturates on
    # TPU, may wrap elsewhere) -- clipping first keeps tie ordering
    # backend-independent. The float bound must be exactly representable
    # AND <= 2^25-1: 2^25-1 itself rounds UP to 2^25 in float32 (ulp is 2
    # there), which would bleed the min-y point's key into the next bin's
    # range; 2^25-2 is representable, so the cast result stays < 2^25.
    qy = jnp.clip(
        (y_max - ys) * q_scale, 0.0, float((1 << 25) - 2)
    ).astype(jnp.int32)
    key = jnp.where(
        v, bin_idx * shift + qy, jnp.int32(cfg.num_bins) * shift
    )
    sorted_key, sorted_idx = jax.lax.sort(
        (key, jnp.arange(p, dtype=jnp.int32)), num_keys=1
    )
    bins = jnp.arange(cfg.num_bins + 1, dtype=jnp.int32)
    bounds = jnp.searchsorted(sorted_key, bins * shift)
    starts, ends = bounds[:-1], bounds[1:]
    n_b = (ends - starts).astype(jnp.int32)
    # k_b = max(1, floor(n_b * top_k_percent)), 0 when the bin is empty
    # (reference :138).
    k_b = jnp.where(
        n_b > 0,
        jnp.maximum(1, jnp.floor(n_b * cfg.top_k_percent).astype(jnp.int32)),
        0,
    )
    rank = jnp.arange(cfg.max_per_bin)
    gather = jnp.clip(starts[:, None] + rank[None, :], 0, p - 1)  # [B, K]
    sel = sorted_idx[gather].reshape(-1)
    e_pts = jnp.stack([xs[sel], ys[sel], z.reshape(-1)[sel]], axis=-1)
    # k_b is capped at the static max_per_bin budget; report when the cap
    # binds so frames using fewer edge points than the reference's 5%
    # rule are flagged rather than silent.
    keep = (rank[None, :] < jnp.minimum(k_b, cfg.max_per_bin)[:, None]) & (
        rank[None, :] < n_b[:, None]
    )
    e_w = keep.reshape(-1).astype(jnp.float32) * binnable.astype(jnp.float32)
    # Mask the cap flag by binnable: a frame with a degenerate x-range dumps
    # everything into bin 0 and is already invalid, not "truncated".
    return (
        e_pts, e_w, jnp.sum(e_w).astype(jnp.int32), binnable,
        jnp.any((k_b > cfg.max_per_bin) & (n_b > 0)) & binnable,
    )


def _sort_by_x(pts, w):
    """Sort edge points by x for a stable parametrization (reference :74),
    pushing padded points to the end."""
    key = jnp.where(w > 0, pts[:, 0], jnp.float32(1e30))
    order = jnp.argsort(key)
    return pts[order], w[order]


@shape_contract(mask="h w", depth="h w", intrinsics="3 3")
def compute_curvature_profile(
    mask,
    depth,
    intrinsics,
    depth_scale,
    cfg: GeometryConfig = GeometryConfig(),
) -> CurvatureProfile:
    """Full profile: the jittable equivalent of the reference's
    ``compute_curvature_profile`` (reference :42-97).

    Args:
        mask: [H, W] binary/uint8 segmentation mask.
        depth: [H, W] raw depth (e.g. z16) -- multiplied by ``depth_scale``.
        intrinsics: [3, 3] pinhole intrinsic matrix.
        depth_scale: scalar depth-to-meters factor.
        cfg: static geometry configuration.

    Returns:
        :class:`CurvatureProfile` with fixed shapes; check ``valid``.
    """
    intrinsics = jnp.asarray(intrinsics, jnp.float32)
    fx, fy = intrinsics[0, 0], intrinsics[1, 1]
    cx, cy = intrinsics[0, 2], intrinsics[1, 2]

    s = max(1, int(cfg.stride))
    native_cloud_count = None
    if s > 1:
        # Exact native-resolution cloud count for the validity gate: a
        # pooled cell survives whether 1 or s^2 of its pixels were valid,
        # so scaling the POOLED count by s^2 would let a sparse speckle
        # mask (e.g. 30 isolated pixels) pass the reference's
        # min_cloud_points=100 cutoff. One elementwise reduction, no sort.
        native_cloud_count = jnp.sum(
            (mask > 0) & (jnp.asarray(depth) > 0)
        ).astype(jnp.int32)
        # Decimate the cloud before the (dominant) packed-key sort: stride 2
        # quarters the sorted element count. Implemented as an s x s
        # max-pool of the MASKED depth -- NOT a strided slice, which costs
        # ~1.8 ms/frame in lane relayout on TPU while reduce_window is
        # effectively free. Pooling the masked depth keeps the mask & z>0
        # coupling exact (each pooled cell carries its deepest masked
        # pixel or is invalid). Accuracy vs the scipy oracle is quantified
        # per stride in GEOMETRY_PARITY.json.
        masked_depth = jnp.where(mask > 0, depth, 0)
        masked_depth = jax.lax.reduce_window(
            masked_depth,
            jnp.array(0, masked_depth.dtype),
            jax.lax.max,
            (s, s),
            (s, s),
            "VALID",
        )
        mask = (masked_depth > 0).astype(jnp.uint8)
        depth = masked_depth

    # Fused-kernel dispatch (ops/pallas/geometry.py): "auto" resolves per
    # backend with the PALLAS_TUNE.json table able to veto per (op, shape);
    # the XLA branch below is the reference path the kernels are
    # bitwise-compared against.
    from robotic_discovery_platform_tpu.ops.pallas import (
        geometry as pallas_geometry,
    )

    ph, pw = depth.shape
    dep_impl = pallas_geometry.resolve_impl(
        cfg.kernel_impl, "deproject", h=ph, w=pw, stride=s
    )
    if dep_impl in ("pallas", "interpret"):
        x, y, z, valid_map, stats = pallas_geometry.deproject_edge_stats(
            mask, depth, fx, fy, cx, cy, depth_scale, stride=s,
            interpret=dep_impl == "interpret",
        )
    else:
        x, y, z, valid_map = deproject(
            mask, depth, fx, fy, cx, cy, depth_scale, stride=s
        )
        stats = None
    cloud_count = (
        stats[4] if stats is not None
        else jnp.sum(valid_map).astype(jnp.int32)
    )

    e_pts, e_w, edge_count, binnable, bin_capped = _edge_points(
        x, y, z, valid_map, cfg, stats
    )
    s_pts, s_w = _sort_by_x(e_pts, e_w)

    n_edge = cfg.num_bins * cfg.max_per_bin
    fit_impl = pallas_geometry.resolve_impl(
        cfg.kernel_impl, "bspline_design", n=n_edge, c=cfg.num_ctrl
    )
    knots = bspline.clamped_uniform_knots(cfg.num_ctrl, cfg.spline_degree)
    ctrl, _ = bspline.fit_bspline(
        s_pts, s_w, knots, cfg.spline_degree, cfg.spline_smoothing,
        impl=fit_impl,
    )

    curv_impl = pallas_geometry.resolve_impl(
        cfg.kernel_impl, "bspline_curvature", n=cfg.num_samples,
        c=cfg.num_ctrl,
    )
    u_fine = jnp.linspace(0.0, 1.0, cfg.num_samples)
    kappa, k_valid, r = bspline.curvature_profile(
        ctrl, knots, u_fine, cfg.spline_degree, impl=curv_impl
    )
    n_kv = jnp.sum(k_valid)
    mean_k = jnp.where(n_kv > 0, jnp.sum(kappa) / jnp.maximum(n_kv, 1), 0.0)
    max_k = jnp.max(jnp.where(k_valid, kappa, 0.0))

    # Validity gates keep the reference's native-resolution cutoffs
    # (:64-70): the cloud gate uses the EXACT native count (computed above
    # when striding); the edge gate scales the pooled selection by s^2 --
    # an estimate that is exact for dense masks and conservative-ish for
    # sparse ones (the exact cloud gate already rejects speckle frames).
    gate_cloud = (
        native_cloud_count if native_cloud_count is not None else cloud_count
    )
    ok = (
        (gate_cloud >= cfg.min_cloud_points)
        & binnable
        & (edge_count * (s * s) >= cfg.min_edge_points)
        & (n_kv > 0)
    )
    zero = jnp.float32(0.0)
    return CurvatureProfile(
        mean_curvature=jnp.where(ok, mean_k, zero),
        max_curvature=jnp.where(ok, max_k, zero),
        spline_points=jnp.where(ok, r, jnp.zeros_like(r)),
        valid=ok,
        num_cloud_points=cloud_count,
        num_edge_points=edge_count,
        truncated=bin_capped,
    )


def make_jitted_profile(cfg: GeometryConfig = GeometryConfig()):
    """Return a jitted ``(mask, depth, intrinsics, depth_scale) -> profile``
    with the static config closed over."""

    @jax.jit
    def fn(mask, depth, intrinsics, depth_scale):
        return compute_curvature_profile(mask, depth, intrinsics, depth_scale, cfg)

    return fn


def profile_to_numpy(p: CurvatureProfile) -> dict:
    """Host-side unpacking helper for the serving layer."""
    valid = bool(p.valid)
    return {
        "mean_curvature": float(p.mean_curvature) if valid else 0.0,
        "max_curvature": float(p.max_curvature) if valid else 0.0,
        "spline_points": np.asarray(p.spline_points) if valid else np.zeros((0, 3)),
        "valid": valid,
        "num_cloud_points": int(p.num_cloud_points),
        "num_edge_points": int(p.num_edge_points),
        "truncated": bool(p.truncated),
    }
