"""The fused per-frame analysis graph: frame -> mask -> curvature in ONE
jitted XLA computation.

This is the BASELINE.json north star ("mask+curvature run in one XLA graph
per frame"). The reference executes the same logic as five separate host
steps with two host<->device transfers (reference: services/vision_analysis/
server.py:117-133 -- torchvision preprocess, torch forward, sigmoid/threshold,
cv2 nearest-resize back to native, numpy/scipy geometry). Here a single
compiled function takes the raw uint8 RGB frame + raw z16 depth and returns
the native-resolution mask, curvature profile, and coverage -- the only host
work left is image decode and protobuf.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from robotic_discovery_platform_tpu.analysis import recompile
from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.ops import geometry
from robotic_discovery_platform_tpu.utils import transferguard
from robotic_discovery_platform_tpu.utils.config import GeometryConfig


class FrameAnalysis(NamedTuple):
    mask: jnp.ndarray  # [(B,) H, W] uint8 native-resolution binary mask
    mask_coverage: jnp.ndarray  # [(B,)] percent of frame covered
    profile: geometry.CurvatureProfile  # leaves have a leading B in batch mode
    # [(B,)] mean |sigmoid(logit) - 0.5| over the model-resolution output:
    # how far the segmenter sits from its decision boundary (0 = maximally
    # uncertain, 0.5 = saturated). Free at serving time -- the logits are
    # already in the graph -- and the drift monitor's model-quality signal
    # (monitoring/profile.py).
    confidence_margin: jnp.ndarray


def pack_analysis(out: FrameAnalysis, *, n_pts: int, impl: str = "auto"):
    """Fuse a batched :class:`FrameAnalysis` into one ``[B, P]`` uint8
    packed payload -- the device half of the egress wire.

    Appended INSIDE the analyzer jit graph (the ``pack=True`` factories
    below), so the completer's host fetch shrinks from ~7 tree leaves
    (native-resolution mask dominating) to ONE contiguous array per
    dispatch: the bitpacked mask (ops/pallas/pack.py, 8x smaller) plus a
    f32 sidecar of every per-frame scalar the response needs (coverage,
    mean/max curvature, validity, confidence margin) and the spline
    block. Row layout + geometry ride a 16-byte self-describing header
    (``pack.payload_header``); ``serving/egress.PackedResult`` is the
    host-side parser.

    The invalid-profile curvatures are masked with ``jnp.where`` (NOT
    multiplied) so a NaN curvature on an invalid frame packs as the
    exact 0.0 the legacy host path reports (``float(mean) if valid
    else 0.0``) instead of propagating.
    """
    from robotic_discovery_platform_tpu.ops.pallas import pack as pack_lib

    b, h, w = out.mask.shape
    prof = out.profile
    if prof.spline_points.shape[-2] != n_pts:
        raise ValueError(
            f"spline block has {prof.spline_points.shape[-2]} samples; "
            f"the packed layout was declared with n_pts={n_pts}"
        )
    f32 = jnp.float32
    sidecar = jnp.concatenate(
        [
            jnp.stack(
                [
                    out.mask_coverage.astype(f32),
                    jnp.where(prof.valid, prof.mean_curvature, 0.0).astype(f32),
                    jnp.where(prof.valid, prof.max_curvature, 0.0).astype(f32),
                    prof.valid.astype(f32),
                    out.confidence_margin.astype(f32),
                ],
                axis=1,
            ),
            prof.spline_points.astype(f32).reshape(b, -1),
        ],
        axis=1,
    )
    # f32 -> little-endian bytes in-graph (bitcast adds a trailing
    # 4-byte axis); the host side reads them back with one .view(f32)
    side_u8 = jax.lax.bitcast_convert_type(sidecar, jnp.uint8).reshape(b, -1)
    bits = pack_lib.bitpack_mask(out.mask, impl=impl).reshape(b, -1)
    header = jnp.broadcast_to(
        jnp.asarray(pack_lib.payload_header(h, w, n_pts))[None],
        (b, pack_lib.HEADER_BYTES),
    )
    row = jnp.concatenate([header, side_u8, bits], axis=1)
    pad = pack_lib.frame_payload_bytes(h, w, n_pts) - row.shape[1]
    if pad:
        row = jnp.pad(row, ((0, 0), (0, pad)))
    return row


@functools.lru_cache(maxsize=None)
def _resize_matrix(n_in: int, n_out: int) -> np.ndarray:
    """[n_out, n_in] matrix R with ``R @ v == jax.image.resize(v, ...)``
    for 1-D antialiased bilinear resize: same half-pixel sample centers,
    triangle kernel widened by 1/scale when downscaling, per-output weight
    normalization, and out-of-bounds zeroing. Pure numpy (static) so it
    folds into the graph as a constant; equality with jax.image.resize is
    asserted in tests/test_pipeline.py."""
    inv_scale = n_in / n_out
    kernel_scale = max(inv_scale, 1.0)
    sample_f = (np.arange(n_out) + 0.5) * inv_scale - 0.5
    x = np.abs(sample_f[None, :] - np.arange(n_in)[:, None]) / kernel_scale
    weights = np.maximum(0.0, 1.0 - x)  # triangle kernel
    total = weights.sum(axis=0, keepdims=True)
    weights = np.where(
        np.abs(total) > 1e-7, weights / np.where(total != 0, total, 1), 0.0
    )
    in_bounds = ((sample_f >= -0.5) & (sample_f <= n_in - 0.5))[None, :]
    return np.where(in_bounds, weights, 0.0).T.astype(np.float32)


def stage_batch(frames_rgb, depths, intrinsics, depth_scales, device=None):
    """Host->device staging for one padded batch, explicit and OFF the
    analyzers' critical path.

    The pipelined dispatcher (serving/batching.py) calls this before
    launching, so the jitted analyzers receive device-resident arrays and
    their call is pure async launch -- no implicit H2D transfer hides
    inside the dispatch while the previous batch is still completing.
    All three batch analyzers accept either host numpy or pre-staged
    device arrays (jit treats both identically; the ``b == 1`` fast path
    in ``_analyze_batch`` is unaffected by where the arrays live).

    ``device`` selects the placement the mesh router threads through here:

    - ``None`` -- the process default device (single-chip serving);
    - a ``jax.Device`` -- commit the whole batch to ONE mesh chip (the
      round-robin dispatch mode: each launched bucket lands on the
      router's least-loaded chip);
    - a ``Sharding`` (``parallel.mesh.batch_sharding``) -- split the
      batch's leading dim over the mesh "data" axis (the data-sharded
      dispatch mode). ``jax.device_put`` performs the per-shard H2D
      transfers itself, reading each chip's rows straight out of the
      pooled host staging buffer -- no intermediate per-shard copies.

    Returns ``(frames, depths, intrinsics, depth_scales)`` as device
    arrays. ``jax.device_put`` is itself asynchronous, so staging batch
    N+1 overlaps batch N's compute.

    Fill-in-place contract with the ingest path (serving/ingest.py +
    ``_BucketBuffers.fill``): the host arrays arriving here are either
    the dispatcher's pooled staging buffers (filled row-in-place, one
    host copy per frame) or -- on the b == 1 fast path with raw-format
    wire payloads -- zero-copy (possibly read-only) ``np.frombuffer``
    views of the gRPC message buffer itself; ``device_put`` reads the
    H2D transfer straight out of either with no intermediate copy, and
    read-only inputs are first-class.
    """
    from jax.sharding import NamedSharding

    if isinstance(device, NamedSharding):
        b = int(np.shape(frames_rgb)[0])
        shards = device.mesh.shape.get("data", 1)
        if b % shards:
            raise ValueError(
                f"batch of {b} cannot shard evenly over {shards} 'data' "
                "chips; the dispatcher pads buckets to a multiple of the "
                "mesh size before staging"
            )
    return jax.device_put(
        (frames_rgb, depths, intrinsics, depth_scales), device
    )


@shape_contract(frames_rgb="b h w 3", out="b s s 3")
def preprocess(frames_rgb, img_size: int):
    """uint8 [B, H, W, 3] RGB -> float [B, S, S, 3] in [0, 1].

    Mirrors the reference's ToTensor + Resize(256, antialias) preprocess
    (reference: services/vision_analysis/server.py:107-121), but inside the
    graph. The antialiased bilinear resize is separable and linear, so it
    runs as two small static-weight matmuls on the MXU (H then W
    contraction) instead of ``jax.image.resize``'s gather lowering --
    measured ~10x cheaper per frame at 480x640 -> 256x256 and numerically
    identical (the weight matrices come from jax.image.resize itself, and
    the contractions run at highest precision).
    """
    h, w = frames_rgb.shape[1], frames_rgb.shape[2]
    x = frames_rgb.astype(jnp.float32) / 255.0
    r_h = jnp.asarray(_resize_matrix(h, img_size))  # [S, H]
    r_w = jnp.asarray(_resize_matrix(w, img_size))  # [S, W]
    x = jnp.einsum("Oh,bhwc->bOwc", r_h, x, precision="highest")
    return jnp.einsum("Pw,bOwc->bOPc", r_w, x, precision="highest")


@shape_contract(logits="b s s c", out="b h w")
def logits_to_native_masks(logits, h: int, w: int, threshold: float = 0.5):
    """sigmoid > threshold at model resolution, nearest-resize to native
    [B, H, W] (reference: server.py:122-125).

    C > 1 heads (the zoo's multi-actuator variant, models/variants.py)
    are multi-label: each channel is one actuator class and a pixel
    joins the union mask when ANY class clears the threshold. The C == 1
    branch keeps the seed binary expression verbatim -- the default
    model's graph (and its bitwise-parity guarantee) is untouched."""
    if logits.shape[-1] == 1:
        prob = jax.nn.sigmoid(logits[..., 0])
    else:
        prob = jnp.max(jax.nn.sigmoid(logits), axis=-1)
    masks = (prob > threshold).astype(jnp.uint8)
    return jax.image.resize(masks, (masks.shape[0], h, w), method="nearest")


def _analyze_batch(model, variables, frames_rgb, depths, intrinsics,
                   depth_scales, img_size, geom_cfg, threshold,
                   forward=None):
    """Shared core: [B, ...] frames -> FrameAnalysis with leading B.

    ``forward(variables, x) -> logits`` overrides the model forward; the
    serving layer passes the Pallas-fused net here (ops/pallas).
    """
    b, h, w = frames_rgb.shape[0], frames_rgb.shape[1], frames_rgb.shape[2]
    x = preprocess(frames_rgb, img_size)
    if forward is None:
        logits = model.apply(variables, x, train=False)
    else:
        logits = forward(variables, x)
    masks = logits_to_native_masks(logits, h, w, threshold)
    # distance from the decision boundary, at model resolution (XLA CSEs
    # the sigmoid with the one inside logits_to_native_masks; the extra
    # cost is one [B, S, S] mean riding the existing result fetch). The
    # C == 1 branch is the seed expression verbatim; multi-label heads
    # average the margin over every class channel.
    if logits.shape[-1] == 1:
        margin = jnp.mean(
            jnp.abs(jax.nn.sigmoid(logits[..., 0].astype(jnp.float32))
                    - 0.5),
            axis=(1, 2),
        )
    else:
        margin = jnp.mean(
            jnp.abs(jax.nn.sigmoid(logits.astype(jnp.float32)) - 0.5),
            axis=(1, 2, 3),
        )

    # The vmapped (dense-batch) leg pins the geometry kernels to the XLA
    # path: batching a pallas_call multiplies its VMEM working set by B
    # exactly like the dense U-Net forward (the measured VMEM-spill
    # anti-scaling), and the fused kernels' win is single-frame HBM-pass
    # elimination. The b == 1 fast path and the scan analyzer (B=1
    # residency by design) keep cfg.kernel_impl as configured.
    geom_cfg_vmap = (
        geom_cfg if geom_cfg.kernel_impl == "xla"
        else dataclasses.replace(geom_cfg, kernel_impl="xla")
    )

    def per_frame(mask, depth, k, scale, cfg=geom_cfg):
        return geometry.compute_curvature_profile(mask, depth, k, scale, cfg)

    # Geometry batches under vmap: the packed-key lax.sort at its heart
    # lowers to ONE row-batched XLA sort over [B, H*W] (an earlier design's
    # per-bin top_k ops lost 7x under vmap, which forced a sequential
    # lax.map here; the single-sort redesign removed that cliff).
    if b == 1:
        profs = jax.tree.map(
            lambda a: a[None],
            per_frame(masks[0], depths[0], intrinsics[0], depth_scales[0]),
        )
    else:
        profs = jax.vmap(
            lambda m, d, k, s: per_frame(m, d, k, s, geom_cfg_vmap)
        )(masks, depths, intrinsics, depth_scales)
    coverage = 100.0 * jnp.mean(masks.astype(jnp.float32), axis=(1, 2))
    return FrameAnalysis(mask=masks, mask_coverage=coverage, profile=profs,
                         confidence_margin=margin)


def make_frame_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
):
    """Build the jitted single-frame fused analyzer.

    Returns ``analyze(variables, frame_rgb_u8 [H,W,3], depth_u16 [H,W],
    intrinsics [3,3], depth_scale) -> FrameAnalysis`` (unbatched outputs).
    Shapes are static per (H, W); jit caches one executable per camera
    geometry.
    """

    # trace_guard rides UNDER jit so its body runs once per jit-cache miss:
    # one compile per camera geometry is the declared steady state (budget 2
    # tolerates one mid-run camera change before the guard flags).
    @jax.jit
    @recompile.trace_guard("pipeline.frame_analyzer", budget=2)
    @shape_contract(frame_rgb="h w 3", depth="h w", intrinsics="3 3")
    def analyze(variables, frame_rgb, depth, intrinsics, depth_scale):
        out = _analyze_batch(
            model,
            variables,
            frame_rgb[None],
            depth[None],
            jnp.asarray(intrinsics, jnp.float32)[None],
            jnp.asarray(depth_scale, jnp.float32)[None],
            img_size,
            geom_cfg,
            threshold,
            forward,
        )
        return jax.tree.map(lambda a: a[0], out)

    # RDP_TRANSFER_GUARD: with the guard armed, every warm call must move
    # zero implicit bytes (explicit stage_batch/device_put staging only);
    # off (default) this returns `analyze` unchanged
    return transferguard.apply(analyze)


def make_batch_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
    *,
    pack: bool = False,
):
    """Batched variant for cross-stream micro-batching on one chip: one
    forward pass over [B, H, W, 3], geometry vmapped per frame. The model
    forward is where the MXU time goes, so batching concurrent gRPC streams
    into one dispatch is the single biggest serving-throughput lever
    (SURVEY.md section 5.7b).

    ``intrinsics`` is [B, 3, 3] and ``depth_scales`` is [B] so streams from
    different cameras batch correctly. Inputs may be host numpy or arrays
    pre-staged with :func:`stage_batch` (the pipelined dispatcher's path);
    the call returns as soon as the computation is enqueued (async
    dispatch), so callers that want the result on the host perform the one
    blocking ``np.asarray`` themselves.

    ``pack=True`` appends :func:`pack_analysis` to the graph: the call
    returns the ``[B, P]`` uint8 packed payload instead of a
    :class:`FrameAnalysis` tree (the serving dispatcher's one-fetch
    egress). Default False keeps every existing caller bitwise.
    """

    # budget 8: the batching dispatcher pads to power-of-two buckets, so one
    # camera geometry legitimately compiles ~log2(max_batch)+1 batch shapes
    @jax.jit
    @recompile.trace_guard("pipeline.batch_analyzer", budget=8)
    @shape_contract(frames_rgb="b h w 3", depths="b h w",
                    intrinsics="b 3 3", depth_scales="b")
    def analyze(variables, frames_rgb, depths, intrinsics, depth_scales):
        out = _analyze_batch(
            model, variables, frames_rgb, depths,
            jnp.asarray(intrinsics, jnp.float32),
            jnp.asarray(depth_scales, jnp.float32),
            img_size, geom_cfg, threshold, forward,
        )
        if pack:
            return pack_analysis(out, n_pts=geom_cfg.num_samples,
                                 impl=geom_cfg.kernel_impl)
        return out

    return transferguard.apply(analyze)


# -- split JPEG decode: the device half ------------------------------------
#
# The host (serving/entropy.py) stops at quantized coefficient blocks;
# everything below runs inside the SAME jit graph as the analyzer, so the
# decoded RGB image never materializes on the host. Every stage mirrors
# libjpeg's fixed-point arithmetic exactly (islow IDCT as two integer
# basis matmuls in ops/pallas/decode.py, triangle "fancy" chroma
# upsampling, SCALEBITS=16 YCbCr->RGB), which is what makes the end-to-end
# split bitwise-comparable against cv2.imdecode in the golden tests.

_YCC_SCALE = 16
_YCC_HALF = 1 << (_YCC_SCALE - 1)


def _ycc_fix(x: float) -> int:
    return int(x * (1 << _YCC_SCALE) + 0.5)


def stage_coef_batch(y, cb, cr, qy, qc, depths, intrinsics, depth_scales,
                     device=None):
    """:func:`stage_batch` for coefficient-wire batches.

    Same explicit-H2D contract (pooled 64-byte-aligned staging buffers or
    zero-copy ``np.frombuffer`` views in, device arrays out, async
    ``device_put``); the payload is the entropy-decoded planes +
    per-frame quant tables instead of an RGB image.
    """
    from jax.sharding import NamedSharding

    if isinstance(device, NamedSharding):
        b = int(np.shape(y)[0])
        shards = device.mesh.shape.get("data", 1)
        if b % shards:
            raise ValueError(
                f"batch of {b} cannot shard evenly over {shards} 'data' "
                "chips; the dispatcher pads buckets to a multiple of the "
                "mesh size before staging"
            )
    return jax.device_put(
        (y, cb, cr, qy, qc, depths, intrinsics, depth_scales), device
    )


def _assemble_plane(samples, blocks_h: int, blocks_w: int):
    """[B, blocks_h*blocks_w, 64] block samples -> [B, 8*bh, 8*bw]."""
    b = samples.shape[0]
    x = samples.reshape(b, blocks_h, blocks_w, 8, 8)
    return x.transpose(0, 1, 3, 2, 4).reshape(
        b, blocks_h * 8, blocks_w * 8
    )


def _upsample_h2v2(plane):
    """libjpeg ``h2v2_fancy_upsample``, exact integer arithmetic.

    [B, ch, cw] int32 -> [B, 2ch, 2cw]: vertical 3:1 column sums with
    edge-clamped neighbors, then the 9/16-3/16-3/16-1/16 horizontal
    triangle with libjpeg's alternating +8/+7 rounding biases. Interleaves
    are stack+reshape (no scatters).
    """
    b, ih, iw = plane.shape
    above = np.clip(np.arange(ih) - 1, 0, ih - 1)
    below = np.clip(np.arange(ih) + 1, 0, ih - 1)
    even = 3 * plane + plane[:, above]
    odd = 3 * plane + plane[:, below]
    colsum = jnp.stack([even, odd], axis=2).reshape(b, 2 * ih, iw)
    left = np.clip(np.arange(iw) - 1, 0, iw - 1)
    right = np.clip(np.arange(iw) + 1, 0, iw - 1)
    h_even = (3 * colsum + colsum[:, :, left] + 8) >> 4
    h_odd = (3 * colsum + colsum[:, :, right] + 7) >> 4
    return jnp.stack([h_even, h_odd], axis=3).reshape(b, 2 * ih, 2 * iw)


def _upsample_h2v1(plane):
    """libjpeg ``h2v1_fancy_upsample``: horizontal-only triangle."""
    b, ih, iw = plane.shape
    left = np.clip(np.arange(iw) - 1, 0, iw - 1)
    right = np.clip(np.arange(iw) + 1, 0, iw - 1)
    h_even = (3 * plane + plane[:, :, left] + 1) >> 2
    h_odd = (3 * plane + plane[:, :, right] + 2) >> 2
    return jnp.stack([h_even, h_odd], axis=3).reshape(b, ih, 2 * iw)


def _ycc_to_rgb(y, cb, cr):
    """libjpeg ``ycc_rgb_convert``: SCALEBITS=16 fixed point, exact.

    int32 planes (0..255) -> uint8 [B, H, W, 3]. Arithmetic right shifts
    on int32 match the C tables bit for bit.
    """
    cb = cb - 128
    cr = cr - 128
    r = y + ((_ycc_fix(1.40200) * cr + _YCC_HALF) >> _YCC_SCALE)
    b = y + ((_ycc_fix(1.77200) * cb + _YCC_HALF) >> _YCC_SCALE)
    g = y + (
        (-_ycc_fix(0.34414) * cb - _ycc_fix(0.71414) * cr + _YCC_HALF)
        >> _YCC_SCALE
    )
    rgb = jnp.stack([r, g, b], axis=-1)
    return jnp.clip(rgb, 0, 255).astype(jnp.uint8)


def decode_coef_batch(y, cb, cr, qy, qc, *, height: int, width: int,
                      subsampling: str, impl: str = "auto"):
    """The on-chip half of the split JPEG decode, batched.

    Args:
        y/cb/cr: [B, N, 64] int16 quantized coefficient planes (natural
            order, block raster -- ``serving.entropy.CoefficientFrame``).
        qy/qc: [B, 64] uint16 quant tables (per frame).
        height/width/subsampling: static frame geometry.
        impl: kernel dispatch for the dequant+IDCT stage
            (``GeometryConfig.kernel_impl`` semantics).

    Returns uint8 RGB [B, height, width, 3], bitwise equal to what
    libjpeg/cv2.imdecode produces from the same coefficients.
    """
    from robotic_discovery_platform_tpu.ops.pallas import (
        decode as pallas_decode,
    )
    from robotic_discovery_platform_tpu.serving.entropy import block_grids

    (ybh, ybw), (cbh, cbw) = block_grids(height, width, subsampling)
    y_pix = _assemble_plane(
        pallas_decode.dequant_idct(y, qy, impl=impl), ybh, ybw
    )[:, :height, :width]
    cb_pix = _assemble_plane(
        pallas_decode.dequant_idct(cb, qc, impl=impl), cbh, cbw
    )
    cr_pix = _assemble_plane(
        pallas_decode.dequant_idct(cr, qc, impl=impl), cbh, cbw
    )
    # Crop the chroma planes to their TRUE downsampled dims before
    # upsampling: the block grid pads to whole MCUs, and the fancy
    # upsamplers' edge-clamped neighbor taps must replicate the real
    # last row/column (libjpeg's edge rule), not read MCU padding.
    if subsampling == "420":
        ch, cw = (height + 1) // 2, (width + 1) // 2
        cb_pix = _upsample_h2v2(cb_pix[:, :ch, :cw])
        cr_pix = _upsample_h2v2(cr_pix[:, :ch, :cw])
    elif subsampling == "422":
        ch, cw = height, (width + 1) // 2
        cb_pix = _upsample_h2v1(cb_pix[:, :ch, :cw])
        cr_pix = _upsample_h2v1(cr_pix[:, :ch, :cw])
    cb_pix = cb_pix[:, :height, :width]
    cr_pix = cr_pix[:, :height, :width]
    return _ycc_to_rgb(y_pix, cb_pix, cr_pix)


def make_coef_batch_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
    *,
    height: int,
    width: int,
    subsampling: str = "420",
    pack: bool = False,
):
    """Batched analyzer whose wire-side input is coefficient planes.

    The decode stage (:func:`decode_coef_batch`) is slotted AHEAD of the
    fused analyzer inside ONE jit graph: coefficients arrive via
    :func:`stage_coef_batch`, the decoded RGB lives only in device memory,
    and the analyzer consumes it directly -- the host never sees pixels.
    Frame geometry is static per analyzer (the dispatcher already groups
    by (model, frame shape), and coef groups add subsampling to the key).

    Call shape: ``analyze(variables, y, cb, cr, qy, qc, depths,
    intrinsics, depth_scales) -> FrameAnalysis``.
    """

    @jax.jit
    @recompile.trace_guard("pipeline.coef_batch_analyzer", budget=8)
    @shape_contract(y="b n 64", cb="b m 64", cr="b m 64", qy="b 64",
                    qc="b 64", depths="b h w", intrinsics="b 3 3",
                    depth_scales="b")
    def analyze(variables, y, cb, cr, qy, qc, depths, intrinsics,
                depth_scales):
        frames_rgb = decode_coef_batch(
            y, cb, cr, qy, qc, height=height, width=width,
            subsampling=subsampling, impl=geom_cfg.kernel_impl,
        )
        out = _analyze_batch(
            model, variables, frames_rgb, depths,
            jnp.asarray(intrinsics, jnp.float32),
            jnp.asarray(depth_scales, jnp.float32),
            img_size, geom_cfg, threshold, forward,
        )
        if pack:
            return pack_analysis(out, n_pts=geom_cfg.num_samples,
                                 impl=geom_cfg.kernel_impl)
        return out

    return transferguard.apply(analyze)


def make_scan_batch_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
    *,
    pack: bool = False,
):
    """Batched analyzer that keeps SINGLE-FRAME working-set residency:
    one compiled dispatch scans the B frames sequentially with
    ``lax.scan``, so peak activation memory is the B=1 footprint while the
    per-dispatch host/compile/launch overhead is amortized over the batch.

    Rationale (round-4 verdict item 5): dense batching (make_batch_analyzer)
    anti-scales on this chip -- the U-Net's wide 256-by-256 feature maps
    spill VMEM at B>=4 (measured 349.5 aggregate FPS at B=4 vs 501.5 at
    B=1) -- because batching multiplies the live activation set by B.
    Scanning trades the MXU's batched-matmul efficiency for staying inside
    VMEM; which wins is an empirical question bench.py measures
    (batched_scan_b*). Same call shape as make_batch_analyzer, so
    BatchDispatcher can use either via ServerConfig.batch_impl.
    """

    @jax.jit
    @recompile.trace_guard("pipeline.scan_batch_analyzer", budget=8)
    @shape_contract(frames_rgb="b h w 3", depths="b h w",
                    intrinsics="b 3 3", depth_scales="b")
    def analyze(variables, frames_rgb, depths, intrinsics, depth_scales):
        intr = jnp.asarray(intrinsics, jnp.float32)
        scales = jnp.asarray(depth_scales, jnp.float32)

        def step(carry, inp):
            f, d, k, s = inp
            out = _analyze_batch(
                model, variables, f[None], d[None], k[None], s[None],
                img_size, geom_cfg, threshold, forward,
            )
            return carry, jax.tree.map(lambda a: a[0], out)

        _, outs = jax.lax.scan(step, 0, (frames_rgb, depths, intr, scales))
        # every leaf stacked to leading B by scan; the pack stage (one
        # batched bitpack over the stacked masks) runs after the scan so
        # the per-step working set stays the B=1 footprint
        if pack:
            return pack_analysis(outs, n_pts=geom_cfg.num_samples,
                                 impl=geom_cfg.kernel_impl)
        return outs

    return transferguard.apply(analyze)
