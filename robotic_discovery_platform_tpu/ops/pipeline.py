"""The fused per-frame analysis graph: frame -> mask -> curvature in ONE
jitted XLA computation.

This is the BASELINE.json north star ("mask+curvature run in one XLA graph
per frame"). The reference executes the same logic as five separate host
steps with two host<->device transfers (reference: services/vision_analysis/
server.py:117-133 -- torchvision preprocess, torch forward, sigmoid/threshold,
cv2 nearest-resize back to native, numpy/scipy geometry). Here a single
compiled function takes the raw uint8 RGB frame + raw z16 depth and returns
the native-resolution mask, curvature profile, and coverage -- the only host
work left is image decode and protobuf.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from robotic_discovery_platform_tpu.ops import geometry
from robotic_discovery_platform_tpu.utils.config import GeometryConfig


class FrameAnalysis(NamedTuple):
    mask: jnp.ndarray  # [(B,) H, W] uint8 native-resolution binary mask
    mask_coverage: jnp.ndarray  # [(B,)] percent of frame covered
    profile: geometry.CurvatureProfile  # leaves have a leading B in batch mode


def preprocess(frames_rgb, img_size: int):
    """uint8 [B, H, W, 3] RGB -> float [B, S, S, 3] in [0, 1].

    Mirrors the reference's ToTensor + Resize(256, antialias) preprocess
    (reference: services/vision_analysis/server.py:107-121), but inside the
    graph: scale first, then antialiased bilinear resize.
    """
    b = frames_rgb.shape[0]
    x = frames_rgb.astype(jnp.float32) / 255.0
    return jax.image.resize(
        x, (b, img_size, img_size, 3), method="bilinear", antialias=True
    )


def logits_to_native_masks(logits, h: int, w: int, threshold: float = 0.5):
    """sigmoid > threshold at model resolution, nearest-resize to native
    [B, H, W] (reference: server.py:122-125)."""
    prob = jax.nn.sigmoid(logits[..., 0])
    masks = (prob > threshold).astype(jnp.uint8)
    return jax.image.resize(masks, (masks.shape[0], h, w), method="nearest")


def _analyze_batch(model, variables, frames_rgb, depths, intrinsics,
                   depth_scales, img_size, geom_cfg, threshold,
                   forward=None):
    """Shared core: [B, ...] frames -> FrameAnalysis with leading B.

    ``forward(variables, x) -> logits`` overrides the model forward; the
    serving layer passes the Pallas-fused net here (ops/pallas).
    """
    b, h, w = frames_rgb.shape[0], frames_rgb.shape[1], frames_rgb.shape[2]
    x = preprocess(frames_rgb, img_size)
    if forward is None:
        logits = model.apply(variables, x, train=False)
    else:
        logits = forward(variables, x)
    masks = logits_to_native_masks(logits, h, w, threshold)

    def per_frame(mask, depth, k, scale):
        return geometry.compute_curvature_profile(mask, depth, k, scale, geom_cfg)

    # Geometry stays *unbatched* per frame: its full-frame top_k selection
    # loses the efficient TPU lowering under vmap (measured 3.5 ms -> 25 ms
    # per frame at 640x480), so batching it would throw away far more than
    # the batched model forward gains. B == 1 calls it directly; B > 1 runs
    # the frames sequentially inside the graph via lax.map -- the model
    # forward above is still one batched MXU dispatch.
    if b == 1:
        profs = jax.tree.map(
            lambda a: a[None],
            per_frame(masks[0], depths[0], intrinsics[0], depth_scales[0]),
        )
    else:
        profs = jax.lax.map(
            lambda args: per_frame(*args),
            (masks, depths, intrinsics, depth_scales),
        )
    coverage = 100.0 * jnp.mean(masks.astype(jnp.float32), axis=(1, 2))
    return FrameAnalysis(mask=masks, mask_coverage=coverage, profile=profs)


def make_frame_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
):
    """Build the jitted single-frame fused analyzer.

    Returns ``analyze(variables, frame_rgb_u8 [H,W,3], depth_u16 [H,W],
    intrinsics [3,3], depth_scale) -> FrameAnalysis`` (unbatched outputs).
    Shapes are static per (H, W); jit caches one executable per camera
    geometry.
    """

    @jax.jit
    def analyze(variables, frame_rgb, depth, intrinsics, depth_scale):
        out = _analyze_batch(
            model,
            variables,
            frame_rgb[None],
            depth[None],
            jnp.asarray(intrinsics, jnp.float32)[None],
            jnp.asarray(depth_scale, jnp.float32)[None],
            img_size,
            geom_cfg,
            threshold,
            forward,
        )
        return jax.tree.map(lambda a: a[0], out)

    return analyze


def make_batch_analyzer(
    model,
    img_size: int = 256,
    geom_cfg: GeometryConfig = GeometryConfig(),
    threshold: float = 0.5,
    forward=None,
):
    """Batched variant for cross-stream micro-batching on one chip: one
    forward pass over [B, H, W, 3], geometry vmapped per frame. The model
    forward is where the MXU time goes, so batching concurrent gRPC streams
    into one dispatch is the single biggest serving-throughput lever
    (SURVEY.md section 5.7b).

    ``intrinsics`` is [B, 3, 3] and ``depth_scales`` is [B] so streams from
    different cameras batch correctly.
    """

    @jax.jit
    def analyze(variables, frames_rgb, depths, intrinsics, depth_scales):
        return _analyze_batch(
            model, variables, frames_rgb, depths,
            jnp.asarray(intrinsics, jnp.float32),
            jnp.asarray(depth_scales, jnp.float32),
            img_size, geom_cfg, threshold, forward,
        )

    return analyze
