"""Pallas-fused U-Net inference forward built from Flax variables.

Consumes the exact variable tree that ``models/unet.py`` trains (params +
batch_stats) and re-expresses the whole forward pass with the fused kernels
in :mod:`ops.pallas.conv`: every (conv -> BatchNorm -> ReLU) half-block of
the reference DoubleConv (reference: pkg/segmentation_model.py:24-40) is one
kernel launch with BatchNorm pre-folded, the decoder's 2x2 stride-2
transposed conv (reference: :62-63) is one kernel, and the 1x1 head
(reference: :78-84) is one kernel. Max-pooling and bilinear resizing stay in
XLA (bandwidth-bound data movement XLA already emits optimally).

Dispatch between the Pallas and XLA form of each conv is per-layer and
empirical: measured on v5e, the Pallas kernels win below ~2^23 activation
elements per launch (batch * H * W * max(Cin, Cout)) and lose to XLA's conv
above it, so :func:`auto` picks per shape. Inference-only: training uses the
Flax module (BatchNorm statistics must update).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from robotic_discovery_platform_tpu.analysis import recompile
from robotic_discovery_platform_tpu.models.unet import upsample_align_corners
from robotic_discovery_platform_tpu.ops.pallas import conv as pconv

# Measured v5e crossover for the UNIFORM whole-net choice: Pallas when the
# widest layer's activation volume (b * h * w * 128 concat channels at
# full resolution) stays within the budget, folded-XLA above it. At batch
# 1 the deployed 256^2 forward sits exactly at the budget and runs
# Pallas-uniform (r03: 544 vs 347 FPS over the unfolded Flax path; r04
# full-pipeline: 413 vs 379); batched forwards run XLA-uniform (r04 b4:
# 321 XLA vs 266 Pallas, and batched wide-map Pallas launches overflow
# VMEM outright at b=8).
#
# Why not per-LAYER dispatch: PALLASBENCH.json's isolated-launch timings
# show 3 of 16 conv shapes losing to XLA (0.48-0.64x), but rerouting just
# those to XLA was measured 24% SLOWER end-to-end in the fused serving
# graph (interleaved A/B: 472 vs 584 FPS), and the r04 remeasure agrees
# (mixed auto at b4: 457 FPS forward-only vs 814 XLA-uniform) -- every
# pallas<->XLA boundary pays a layout transition that outweighs the
# per-launch loss. The dispatcher therefore picks ONE backend for the
# whole forward, per input shape.
PALLAS_MAX_ELEMS = 2 ** 23


def _dispatch_3x3(x, w, scale, bias, *, relu, interpret, force):
    if force == "xla" or (
        force is None and not (interpret or pconv.use_pallas())
    ):
        return pconv.conv3x3_bn_relu_xla(x, w, scale, bias, relu=relu)
    from robotic_discovery_platform_tpu.ops.pallas import tuning

    b, h, width, cin = x.shape
    tiling = tuning.lookup(h, width, cin, w.shape[-1], batch=b,
                           dtype=jnp.dtype(x.dtype).name)
    return pconv.conv3x3_bn_relu(
        x, w, scale, bias, relu=relu, interpret=interpret, tiling=tiling
    )


class PallasUNet:
    """Callable inference forward over a fixed variable tree.

    Args:
        model: the Flax ``UNet`` the variables belong to (architecture
            hyperparameters are read off it).
        variables: ``{"params": ..., "batch_stats": ...}`` as produced by
            training.
        interpret: run kernels in the Pallas interpreter (CPU tests).
        force: None (auto per-shape dispatch), "pallas", or "xla".
    """

    def __init__(self, model, variables, *, interpret: bool = False,
                 force: str | None = None):
        if model.norm != "batch":
            raise ValueError(
                "PallasUNet folds BatchNorm; got norm="
                f"{model.norm!r} (use the Flax module instead)"
            )
        self.model = model
        self.interpret = interpret
        self.force = force
        params = variables["params"]
        stats = variables.get("batch_stats", {})
        self._layers = self._fold(params, stats)
        # Per-instance trace budget (analysis/recompile): the serving
        # engine traces this forward once per camera geometry / batch
        # bucket through the fused analyzer's jit. traced_only means
        # eager interpret-mode test calls never consume budget.
        self._guarded_forward = recompile.trace_guard(
            "pallas.unet_forward", budget=8
        )(self._forward)

    # -- variable-tree walking ------------------------------------------

    def _fold(self, params, stats):
        """Pre-fold every BatchNorm into (scale, bias) next to its conv."""

        def double_conv(p, s):
            out = []
            for conv, bn in (("Conv_0", "BatchNorm_0"), ("Conv_1", "BatchNorm_1")):
                scale, bias = pconv.fold_batchnorm(p[bn], s[bn])
                out.append((p[conv]["kernel"], scale, bias))
            return out

        layers = {"inc": double_conv(params["DoubleConv_0"],
                                     stats["DoubleConv_0"])}
        for i in range(4):
            layers[f"down{i}"] = double_conv(
                params[f"Down_{i}"]["DoubleConv_0"],
                stats[f"Down_{i}"]["DoubleConv_0"],
            )
        for i in range(4):
            up = {"dc": double_conv(
                params[f"Up_{i}"]["DoubleConv_0"],
                stats[f"Up_{i}"]["DoubleConv_0"],
            )}
            if not self.model.bilinear:
                ct = params[f"Up_{i}"]["ConvTranspose_0"]
                up["convt"] = (ct["kernel"], ct["bias"])
            layers[f"up{i}"] = up
        head = params["Conv_0"]
        layers["head"] = (
            head["kernel"][0, 0],  # 1x1 conv kernel -> [Cin, Cout]
            jnp.ones((head["kernel"].shape[-1],), jnp.float32),
            jnp.asarray(head["bias"], jnp.float32),
        )
        return layers

    # -- forward --------------------------------------------------------

    def _uniform_force(self, x) -> str:
        """ONE backend for the whole forward, per input shape (see the
        PALLAS_MAX_ELEMS comment): "pallas" or "xla"."""
        if self.force is not None:
            return self.force
        if self.interpret:
            # interpret-mode tests exist to validate the Pallas kernels;
            # the volume gate must never silently reroute them to XLA
            return "pallas"
        if not pconv.use_pallas():
            return "xla"
        b, h, w, _ = x.shape
        widest = b * h * w * 2 * self.model.base_features
        return "pallas" if widest <= PALLAS_MAX_ELEMS else "xla"

    def _double_conv(self, x, taps, force):
        for w, scale, bias in taps:
            x = _dispatch_3x3(
                x, w, scale, bias, relu=True,
                interpret=self.interpret, force=force,
            )
        return x

    def _up(self, x, skip, layer, force):
        b, h, w, c = skip.shape
        if self.model.bilinear:
            x = upsample_align_corners(x, h, w)
        else:
            wk, bias = layer["convt"]
            x = pconv.conv_transpose2x2(
                x, wk, bias, interpret=self.interpret
            ) if (force != "xla" and (
                self.interpret or pconv.use_pallas()
            )) else pconv.conv_transpose2x2_xla(x, wk, bias)
            x = jax.image.resize(
                x, (x.shape[0], h, w, x.shape[3]), method="nearest"
            )
        x = jnp.concatenate([skip, x.astype(skip.dtype)], axis=-1)
        return self._double_conv(x, layer["dc"], force)

    def __call__(self, x):
        """NHWC input -> NHWC f32 logits, same contract as
        ``model.apply(variables, x, train=False)``."""
        return self._guarded_forward(x)

    def _forward(self, x):
        L = self._layers
        force = self._uniform_force(x)
        x = x.astype(self.model.dtype)
        x1 = self._double_conv(x, L["inc"], force)
        xs = [x1]
        for i in range(4):
            x = nn.max_pool(xs[-1], (2, 2), strides=(2, 2))
            xs.append(self._double_conv(x, L[f"down{i}"], force))
        y = xs[4]
        for i in range(4):
            y = self._up(y, xs[3 - i], L[f"up{i}"], force)
        w, scale, bias = L["head"]
        logits = pconv.conv1x1(
            y, w, scale, bias, relu=False, out_dtype=jnp.float32,
            interpret=self.interpret,
        ) if (force != "xla" and (
            self.interpret or pconv.use_pallas()
        )) else pconv.conv1x1_xla(
            y, w, scale, bias, relu=False, out_dtype=jnp.float32
        )
        return logits


def make_pallas_unet(model, variables, *, interpret: bool = False,
                     force: str | None = None) -> PallasUNet:
    return PallasUNet(model, variables, interpret=interpret, force=force)
