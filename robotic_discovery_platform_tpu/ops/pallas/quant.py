"""Precision tiers for the serving path: bf16 activations and int8 weights.

The TPU paper's design rationale (Jouppi et al., ISCA 2017) is an MXU built
for reduced precision -- bf16 multiplies with f32 accumulation at full rate,
int8 at double rate. This module is the serving-side realization of that
rationale for the U-Net analyzer:

- ``"f32"`` -- no transformation at all. The engine is built exactly as the
  model was trained/configured, so serving stays BITWISE identical to the
  pre-precision-tier behavior (the parity anchor the other tiers are gated
  against).
- ``"bf16"`` -- activations in bfloat16 with f32 accumulation: the model's
  compute dtype is forced to bfloat16 (the existing Pallas conv kernels and
  the Flax forward both accumulate their matmuls in f32 and store bf16).
  Parameters stay f32.
- ``"int8"`` -- bf16 activations plus **per-output-channel symmetric int8
  weight quantization** of every conv kernel (3x3 DoubleConv convs, the 2x2
  transposed conv, and the 1x1 head): ``w ~ round(w / s_c) * s_c`` with
  ``s_c = max|w[..., c]| / 127``. The bound variables carry the DEQUANTIZED
  values (exact int8-grid points, so the arithmetic is the int8 weight
  error), which keeps every downstream consumer -- Flax apply, the
  Pallas-fused PallasUNet, mesh replication -- unchanged.

Quantization is applied **per engine generation** (serving/server.py calls
:func:`apply_precision` inside ``_make_engine``), so a hot-reload of new
registry weights re-quantizes automatically.

Accuracy is not assumed: every non-f32 tier is gated by a parity check
against f32 goldens (mask IoU + |delta curvature|) at server warm-up and in
CI (:func:`parity_report`, ``bench.py --serving-pipeline --precision``).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

PRECISIONS = ("f32", "bf16", "int8")

#: int8 symmetric range: [-127, 127] (the -128 code is unused so the grid
#: is symmetric and dequantization needs one scale, no zero point).
_QMAX = 127


def resolve_precision(cfg_value: str, env: str | None = None) -> str:
    """The serving precision tier: ``RDP_PRECISION`` overrides the config
    value (same env-knob convention as RDP_SERVING_CHIPS et al.)."""
    raw = env if env is not None else os.environ.get("RDP_PRECISION")
    value = (raw if raw not in (None, "") else cfg_value).strip().lower()
    if value not in PRECISIONS:
        raise ValueError(
            f"unknown precision {value!r} (choose from {PRECISIONS})"
        )
    return value


# -- int8 weight quantization ------------------------------------------------


def quantize_int8(w, axis: int = -1):
    """Per-channel symmetric int8 quantization along ``axis``.

    Returns ``(q int8, scale f32)`` with ``scale`` shaped like ``w`` reduced
    over every axis but ``axis`` (kept, so ``q * scale`` broadcasts back).
    All-zero channels get scale 1 (their codes are all 0 anyway).
    """
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(w / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale):
    """``q * scale`` back to f32 (exact int8-grid values)."""
    return q.astype(jnp.float32) * scale


def fake_quantize_int8(w, axis: int = -1):
    """quantize -> dequantize in one step: the int8-grid projection of
    ``w`` (what the bound serving variables carry)."""
    q, scale = quantize_int8(w, axis)
    return dequantize_int8(q, scale)


def _is_conv_kernel(path: tuple, leaf) -> bool:
    """Conv kernels in the UNet variable tree: named ``kernel`` with a
    trailing output-channel axis -- 4-D HWIO (3x3, 2x2 transpose) and the
    1x1 head. Norm scales/biases and conv biases stay f32: they are O(C)
    parameters whose quantization saves nothing and costs accuracy."""
    name = getattr(path[-1], "key", None) if path else None
    return name == "kernel" and hasattr(leaf, "ndim") and leaf.ndim >= 2


def quantize_unet_variables(variables) -> tuple[Any, dict]:
    """Per-output-channel int8 weight quantization of every conv kernel in
    a UNet variable tree. Returns ``(quantized_variables, report)``: the
    variables carry dequantized (int8-grid) f32 values, structurally
    identical to the input tree; the report records per-layer error and the
    int8 storage footprint.
    """
    report = {"layers": 0, "int8_bytes": 0, "f32_bytes": 0,
              "max_abs_err": 0.0, "max_rel_err": 0.0}

    def leaf_fn(path, leaf):
        if not _is_conv_kernel(path, leaf):
            return leaf
        q, scale = quantize_int8(leaf, axis=-1)
        dq = dequantize_int8(q, scale)
        err = float(jnp.max(jnp.abs(dq - jnp.asarray(leaf, jnp.float32))))
        amax = float(jnp.max(jnp.abs(leaf)))
        report["layers"] += 1
        report["int8_bytes"] += int(np.prod(q.shape)) + 4 * int(
            np.prod(scale.shape)
        )
        report["f32_bytes"] += 4 * int(np.prod(q.shape))
        report["max_abs_err"] = max(report["max_abs_err"], err)
        if amax > 0:
            report["max_rel_err"] = max(
                report["max_rel_err"], err / amax
            )
        return dq.astype(jnp.asarray(leaf).dtype)

    quantized = jax.tree_util.tree_map_with_path(leaf_fn, variables)
    return quantized, report


# -- precision application ---------------------------------------------------


def apply_precision(model, variables, precision: str):
    """Transform ``(model, variables)`` for one serving precision tier.

    Returns ``(model, variables, report)``; ``report`` is None for f32 (no
    transformation -- the returned objects ARE the inputs, so the f32 tier
    is bitwise identical to pre-tier serving by construction).
    """
    precision = resolve_precision(precision)
    if precision == "f32":
        return model, variables, None
    from robotic_discovery_platform_tpu.models.unet import with_compute_dtype

    model = with_compute_dtype(model, jnp.bfloat16)
    if precision == "bf16":
        return model, variables, {"tier": "bf16", "layers": 0}
    quantized, report = quantize_unet_variables(variables)
    report["tier"] = "int8"
    return model, quantized, report


# -- parity metrics ----------------------------------------------------------


def mask_iou(a, b) -> float:
    """Intersection-over-union of two binary masks; 1.0 when both empty
    (two all-background masks agree perfectly)."""
    a = np.asarray(a) > 0
    b = np.asarray(b) > 0
    union = np.logical_or(a, b).sum()
    if union == 0:
        return 1.0
    return float(np.logical_and(a, b).sum() / union)


def golden_frames(n: int, h: int, w: int, seed: int = 0):
    """Deterministic synthetic actuator scenes (training/synthetic.py) for
    parity calibration: structured frames with real geometry, not uniform
    noise -- thresholded-sigmoid masks on noise flip arbitrarily at the
    0.5 boundary and would make the gate meaningless."""
    from robotic_discovery_platform_tpu.training.synthetic import render_scene

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        img, _, depth = render_scene(rng, h, w)
        out.append((img, depth))
    return out


def parity_report(ref_outputs, got_outputs) -> dict:
    """Compare two lists of FrameAnalysis-like outputs (same frames through
    the f32 reference and a reduced-precision tier): mean mask IoU plus
    mean/max absolute curvature delta over frames valid in the reference.
    """
    ious, curv_errs = [], []
    valid_agree = 0
    for ref, got in zip(ref_outputs, got_outputs):
        ious.append(mask_iou(ref.mask, got.mask))
        rv = bool(np.asarray(ref.profile.valid))
        gv = bool(np.asarray(got.profile.valid))
        valid_agree += int(rv == gv)
        if rv and gv:
            for field in ("mean_curvature", "max_curvature"):
                curv_errs.append(abs(
                    float(np.asarray(getattr(ref.profile, field)))
                    - float(np.asarray(getattr(got.profile, field)))
                ))
        elif rv != gv:
            # a validity flip is the worst curvature outcome: score it as
            # the reference magnitude so the gate sees it
            curv_errs.append(abs(
                float(np.asarray(ref.profile.mean_curvature))
            ) + abs(float(np.asarray(got.profile.mean_curvature))))
    return {
        "frames": len(ious),
        "mask_iou_mean": float(np.mean(ious)) if ious else 1.0,
        "mask_iou_min": float(np.min(ious)) if ious else 1.0,
        "curvature_err_mean": float(np.mean(curv_errs)) if curv_errs else 0.0,
        "curvature_err_max": float(np.max(curv_errs)) if curv_errs else 0.0,
        "valid_agreement": valid_agree / max(len(ious), 1),
    }


def parity_gates_pass(report: dict, min_iou: float,
                      max_curv_err: float) -> bool:
    """The warm-up / CI gate: mean IoU at or above the floor AND the worst
    curvature delta at or below the ceiling."""
    return (report["mask_iou_mean"] >= min_iou
            and report["curvature_err_max"] <= max_curv_err)
