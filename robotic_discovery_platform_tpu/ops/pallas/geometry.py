"""Fused Pallas kernels for the non-conv analyzer stages.

The analyzer's remaining XLA-op chains (ROADMAP "Roofline-driven Pallas
expansion") are bandwidth-bound elementwise/reduction pipelines that XLA
emits as several HBM round trips:

- **deproject + masked edge-stats** (ops/geometry.py): the pinhole
  deprojection writes four dense [H, W] maps, then `_edge_points` re-reads
  them five times for the masked min/max/count reductions that seed the
  binning. :func:`deproject_edge_stats` computes the maps AND the five
  reductions in ONE pass over the input tiles -- each pixel is read once,
  the per-tile partials (one [1, 8] row per grid step) are folded outside
  the kernel with order-independent min/max/integer-sum, so the result is
  bitwise identical to the XLA reference path.
- **B-spline design matmuls** (ops/bspline.py): the Cox-de Boor basis
  matrix B [N, C] is materialized to HBM only to be immediately contracted
  into the [C, C] Gram matrix and [C, D] right-hand side.
  :func:`bspline_design` computes the basis in VMEM and performs both
  contractions in the same kernel -- B never touches HBM.
- **curvature evaluation** (ops/bspline.py): three derivative design
  matrices and the cross/norm curvature formula fuse into
  :func:`bspline_curvature`.

Every kernel mirrors the XLA reference path op for op (the basis recursion
and curvature formula are the SAME shared helpers from ops/bspline.py), so
tests/test_pallas_geometry.py compares them BITWISE on CPU in interpret
mode. Dispatch is per-shape via :func:`resolve_impl`:
``GeometryConfig.kernel_impl`` ("auto" = Pallas on TPU, XLA elsewhere) with
the PALLAS_TUNE.json autotable able to veto or force a backend per
(op, shape) -- the same measured-overlay convention as the conv tiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from robotic_discovery_platform_tpu.ops.pallas.conv import (
    _pick_tile,
    use_pallas,
)

KERNEL_IMPLS = ("auto", "pallas", "xla", "interpret")


def resolve_impl(configured: str, op: str, **dims) -> str:
    """The backend one fused-geometry launch runs: "pallas", "interpret",
    or "xla".

    ``configured`` is ``GeometryConfig.kernel_impl``: "xla" / "pallas" /
    "interpret" pin a path; "auto" runs Pallas on TPU and XLA elsewhere,
    with a per-(op, shape) entry in the PALLAS_TUNE.json table able to
    override the default either way (the escape hatch for shapes where the
    measured kernel loses to XLA, exactly like the conv tile overrides).
    """
    if configured not in KERNEL_IMPLS:
        raise ValueError(
            f"unknown kernel_impl {configured!r} (choose from "
            f"{KERNEL_IMPLS})"
        )
    if configured != "auto":
        return configured
    from robotic_discovery_platform_tpu.ops.pallas import tuning

    table = tuning.lookup_impl(op, **dims)
    if table in ("pallas", "xla"):
        return table
    return "pallas" if use_pallas() else "xla"


# -- deproject + masked edge-stats ------------------------------------------


def _deproject_kernel(m_ref, d_ref, p_ref, x_ref, y_ref, z_ref, v_ref,
                      s_ref, *, tile_h, width, stride):
    """One row-tile grid step: maps + per-tile masked stats.

    m_ref/d_ref: [tile_h, W] f32 mask/depth tiles (pre-cast by the
        wrapper: uint8/uint16 -> f32 is exact).
    p_ref: [1, 8] f32 parameter row (fx, fy, cx, cy, depth_scale, 0...).
    x/y/z/v_ref: [tile_h, W] f32 output map tiles (v is 0/1).
    s_ref: [1, 8] per-tile stats row: x_min, x_max, y_min, y_max, n_valid
        (masked with the same +-1e30 sentinels as the XLA path, so folding
        the rows with min/max/sum outside reproduces its values bitwise).
    """
    i = pl.program_id(0)
    fx, fy = p_ref[0, 0], p_ref[0, 1]
    cx, cy = p_ref[0, 2], p_ref[0, 3]
    ds = p_ref[0, 4]
    off = (stride - 1) / 2.0
    vv = (jax.lax.broadcasted_iota(jnp.float32, (tile_h, width), 0)
          + i * tile_h) * stride + off
    uu = jax.lax.broadcasted_iota(jnp.float32, (tile_h, width), 1) \
        * stride + off
    z = d_ref[:] * ds
    valid = (m_ref[:] > 0) & (z > 0)
    x = (uu - cx) * z / fx
    y = (vv - cy) * z / fy
    x_ref[:] = x
    y_ref[:] = y
    z_ref[:] = z
    v_ref[:] = valid.astype(jnp.float32)
    big = jnp.float32(1e30)
    s_ref[:] = jnp.zeros((1, 8), jnp.float32)
    s_ref[0, 0] = jnp.min(jnp.where(valid, x, big))
    s_ref[0, 1] = jnp.max(jnp.where(valid, x, -big))
    s_ref[0, 2] = jnp.min(jnp.where(valid, y, big))
    s_ref[0, 3] = jnp.max(jnp.where(valid, y, -big))
    s_ref[0, 4] = jnp.sum(valid.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("stride", "interpret"))
def deproject_edge_stats(mask, depth, fx, fy, cx, cy, depth_scale, *,
                         stride: int = 1, interpret: bool = False):
    """Fused pinhole deprojection + masked edge-stat reductions.

    Args:
        mask, depth: [H, W] (any dtype; cast to f32 -- exact for the
            uint8/uint16 camera formats).
        fx, fy, cx, cy, depth_scale: scalars (traced OK).
        stride: the pooled-view stride (iota coordinates scale, center
            offset), same semantics as ops/geometry.deproject.

    Returns ``(x, y, z, valid_bool, (x_min, x_max, y_min, y_max,
    n_valid_i32))`` -- bitwise identical to the XLA reference path
    (``deproject`` + the inline reductions of ``_edge_points``): the maps
    are the same elementwise f32 ops, and min/max/integer-count folds are
    order-independent.
    """
    h, width = depth.shape
    mf = jnp.asarray(mask).astype(jnp.float32)
    df = jnp.asarray(depth).astype(jnp.float32)
    params = jnp.concatenate([
        jnp.stack([
            jnp.asarray(fx, jnp.float32), jnp.asarray(fy, jnp.float32),
            jnp.asarray(cx, jnp.float32), jnp.asarray(cy, jnp.float32),
            jnp.asarray(depth_scale, jnp.float32),
        ]),
        jnp.zeros((3,), jnp.float32),
    ])[None, :]
    tile_h = _pick_tile(h, 64)
    tiles = h // tile_h
    map_shape = jax.ShapeDtypeStruct((h, width), jnp.float32)
    map_spec = pl.BlockSpec((tile_h, width), lambda i: (i, 0))
    x, y, z, v, part = pl.pallas_call(
        functools.partial(_deproject_kernel, tile_h=tile_h, width=width,
                          stride=stride),
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((tile_h, width), lambda i: (i, 0)),
            pl.BlockSpec((tile_h, width), lambda i: (i, 0)),
            pl.BlockSpec((1, 8), lambda i: (0, 0)),
        ],
        out_specs=[
            map_spec, map_spec, map_spec, map_spec,
            pl.BlockSpec((1, 8), lambda i: (i, 0)),
        ],
        out_shape=[map_shape, map_shape, map_shape, map_shape,
                   jax.ShapeDtypeStruct((tiles, 8), jnp.float32)],
        interpret=interpret,
    )(mf, df, params)
    stats = (
        jnp.min(part[:, 0]),
        jnp.max(part[:, 1]),
        jnp.min(part[:, 2]),
        jnp.max(part[:, 3]),
        jnp.sum(part[:, 4]).astype(jnp.int32),
    )
    return x, y, z, v > 0, stats


# -- fused B-spline design matrices -----------------------------------------


def _design_kernel(u_ref, w_ref, p_ref, k_ref, g_ref, r_ref, *, degree):
    """Single-step kernel: Cox-de Boor basis in VMEM, then the weighted
    Gram/RHS contractions -- the basis matrix never reaches HBM. The basis
    recursion and the matmul spelling are the SAME code the XLA path runs
    (ops/bspline._basis_columns / _mm), so interpret-mode results match it
    bitwise. The knot vector rides in as a [1, K] input (a kernel cannot
    close over array constants)."""
    from robotic_discovery_platform_tpu.ops import bspline

    uu = u_ref[:]  # [N, 1]
    b = bspline._basis_columns(uu, k_ref[0, :], degree)  # [N, C]
    bw = b * w_ref[:]  # weights ride in as [N, 1]
    g_ref[:] = bspline._mm(bw.T, b)
    r_ref[:] = bspline._mm(bw.T, p_ref[:])


@functools.partial(
    jax.jit, static_argnames=("knots", "degree", "interpret")
)
def bspline_design(points, weights, u, knots, degree: int = 3,
                   interpret: bool = False):
    """Fused ``(B^T W B, B^T W X)`` for the penalized least-squares fit.

    Args:
        points: [N, D]; weights: [N]; u: [N] parameters.
        knots: STATIC knot vector as a tuple of floats (hashable; the
            callers' knot vectors are compile-time numpy constants).

    Returns ``(gram [C, C], rhs [C, D])`` in f32.
    """
    n = u.shape[0]
    n_knots = len(knots)
    num_ctrl = n_knots - degree - 1
    d = points.shape[1]
    pts = jnp.asarray(points, jnp.float32)
    return pl.pallas_call(
        functools.partial(_design_kernel, degree=degree),
        in_specs=[
            pl.BlockSpec((n, 1), lambda: (0, 0)),
            pl.BlockSpec((n, 1), lambda: (0, 0)),
            pl.BlockSpec((n, d), lambda: (0, 0)),
            pl.BlockSpec((1, n_knots), lambda: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((num_ctrl, num_ctrl), lambda: (0, 0)),
            pl.BlockSpec((num_ctrl, d), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((num_ctrl, num_ctrl), jnp.float32),
            jax.ShapeDtypeStruct((num_ctrl, d), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(u, jnp.float32)[:, None],
        jnp.asarray(weights, jnp.float32)[:, None],
        pts,
        jnp.asarray(knots, jnp.float32)[None, :],
    )


# -- fused curvature evaluation ---------------------------------------------


def _curvature_kernel(c_ref, u_ref, k_ref, m1_ref, m2_ref, kap_ref, v_ref,
                      r_ref, *, degree):
    """r, r', r'' via the shared basis recursion and the (input-fed)
    static derivative-matrix products, then the curvature formula -- one
    launch instead of three design matmuls plus an elementwise chain."""
    from robotic_discovery_platform_tpu.ops import bspline

    uu = u_ref[:]  # [N, 1]
    ctrl = c_ref[:]
    knots_j = k_ref[0, :]
    r = bspline._mm(bspline._basis_columns(uu, knots_j, degree), ctrl)
    b1 = bspline._basis_columns(uu, knots_j, degree - 1)
    r1 = bspline._mm(bspline._mm(b1, m1_ref[:]), ctrl)
    b2 = bspline._basis_columns(uu, knots_j, degree - 2)
    r2 = bspline._mm(bspline._mm(b2, m2_ref[:]), ctrl)
    kappa, valid = bspline._curvature_formula(r1, r2)
    r_ref[:] = r
    kap_ref[:] = kappa[:, None]
    v_ref[:] = valid.astype(jnp.float32)[:, None]


@functools.partial(
    jax.jit, static_argnames=("knots", "degree", "interpret")
)
def bspline_curvature(ctrl, u, knots, degree: int = 3,
                      interpret: bool = False):
    """Fused curvature profile: ``(kappa [N], valid [N] bool, r [N, D])``,
    bitwise-matching ops/bspline.curvature_profile's XLA path."""
    from robotic_discovery_platform_tpu.ops import bspline

    n = u.shape[0]
    c, d = ctrl.shape
    # knots is a STATIC tuple (static_argnames), not a traced value: the
    # numpy conversion runs at trace time to build the static derivative
    # matrices, exactly like the XLA path does.
    knots_np = np.asarray(knots)  # jaxlint: disable=JL001
    n_knots = knots_np.shape[0]
    m1 = bspline._deriv_matrix_product(knots_np, degree, 1)  # [C+1, C]
    m2 = bspline._deriv_matrix_product(knots_np, degree, 2)  # [C+2, C]
    kappa, valid, r = pl.pallas_call(
        functools.partial(_curvature_kernel, degree=degree),
        in_specs=[
            pl.BlockSpec((c, d), lambda: (0, 0)),
            pl.BlockSpec((n, 1), lambda: (0, 0)),
            pl.BlockSpec((1, n_knots), lambda: (0, 0)),
            pl.BlockSpec(m1.shape, lambda: (0, 0)),
            pl.BlockSpec(m2.shape, lambda: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n, 1), lambda: (0, 0)),
            pl.BlockSpec((n, 1), lambda: (0, 0)),
            pl.BlockSpec((n, d), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, d), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(ctrl, jnp.float32),
        jnp.asarray(u, jnp.float32)[:, None],
        jnp.asarray(knots_np, jnp.float32)[None, :],
        jnp.asarray(m1, jnp.float32),
        jnp.asarray(m2, jnp.float32),
    )
    return kappa[:, 0], valid[:, 0] > 0, r


def static_knots(knots) -> tuple:
    """A hashable (static-arg) form of a numpy knot vector."""
    return tuple(float(k) for k in np.asarray(knots))
