"""Fused TPU Pallas convolution kernels for the U-Net inference path.

The reference's hot blocks are DoubleConv = (3x3 conv no-bias -> BatchNorm ->
ReLU) x 2 (reference: pkg/segmentation_model.py:24-40) and the 2x2 stride-2
transposed conv of the non-bilinear decoder (reference: :54-65). On GPU the
reference leans on cuDNN; here each conv + folded-BatchNorm + ReLU is ONE
Pallas kernel:

- the 3x3 SAME conv is expressed as nine shifted ``(tile_h * W, Cin) @
  (Cin, Cout)`` matmuls accumulated in float32 -- the MXU-native decomposition
  (no im2col materialization, no gather);
- the input rides in as an overlapping row slab (halo = 1 row) via
  ``pl.Element`` block indexing, so the Pallas pipeline DMAs each row of HBM
  exactly once per tile;
- inference BatchNorm is folded to a per-channel scale/bias applied in the
  matmul epilogue together with ReLU, so normalized activations never touch
  HBM.

Everything accumulates in f32 and stores in the requested compute dtype
(bf16 by default, matching models/unet.py). The plain-XLA equivalents of
every kernel live alongside (``*_xla``) as the fallback path and the
numerics oracle; ``use_pallas()`` picks per-backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract


def _element_block_spec(shape, index_map) -> pl.BlockSpec:
    """A BlockSpec whose index_map returns ELEMENT offsets, across the two
    Pallas APIs: newer jax spells it per-dimension (``pl.Element(d)``),
    jax <= 0.4.x spells it ``indexing_mode=pl.Unblocked()`` for the whole
    spec. The halo-slab input of the 3x3 kernel needs element indexing in
    either spelling (overlapping row tiles cannot be expressed as block
    indices)."""
    if hasattr(pl, "Element"):
        return pl.BlockSpec(
            tuple(pl.Element(d) for d in shape), index_map
        )
    return pl.BlockSpec(shape, index_map, indexing_mode=pl.Unblocked())


def use_pallas() -> bool:
    """Default policy: compiled Pallas on TPU, XLA fallback elsewhere.

    (Kernels also run under ``interpret=True`` on CPU -- that is the test
    path, not the serving default.)
    """
    return jax.default_backend() == "tpu"


def fold_batchnorm(bn_params, bn_stats, eps: float = 1e-5):
    """Fold inference BatchNorm into per-channel (scale, bias), f32.

    y = (x - mean) / sqrt(var + eps) * gamma + beta
      = x * scale + bias.
    """
    gamma = jnp.asarray(bn_params["scale"], jnp.float32)
    beta = jnp.asarray(bn_params["bias"], jnp.float32)
    mean = jnp.asarray(bn_stats["mean"], jnp.float32)
    var = jnp.asarray(bn_stats["var"], jnp.float32)
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale


def _pick_tile(size: int, target: int) -> int:
    """Largest divisor of ``size`` that is <= target."""
    t = min(size, target)
    while size % t:
        t -= 1
    return t


def _lane(n: int) -> int:
    """VMEM lane padding: a buffer's final dim is tiled to 128 lanes, so a
    narrow channel count occupies ceil(n/128)*128 lanes of space -- 16x
    the naive size at n=8. Every VMEM budget below must count this."""
    return -(-n // 128) * 128


_VMEM_BUDGET = 10 * 1024 * 1024  # against the 16 MB scoped-vmem limit


def vmem_bytes_3x3(tile_h: int, tile_co: int, w: int, cin: int,
                   in_itemsize: int, out_itemsize: int) -> int:
    """Estimated VMEM for one 3x3-conv grid step: halo slab, weight block,
    f32 accumulator, output block -- lane padding on every final dim and
    the Pallas pipeline's double buffering (x2 on every streamed block)
    counted. Shared by the analytic heuristic and the autotuner's
    candidate filter (ops/pallas/tuning.py)."""
    w_bytes = 2 * 9 * cin * _lane(tile_co) * in_itemsize
    slab = 2 * (tile_h + 2) * (w + 2) * _lane(cin) * in_itemsize
    acc = tile_h * w * _lane(tile_co) * 4
    out = 2 * tile_h * w * _lane(tile_co) * out_itemsize
    return w_bytes + slab + acc + out


def _tiles_3x3(h: int, w: int, cin: int, cout: int,
               in_itemsize: int, out_itemsize: int):
    """(tile_h, tile_co) under the VMEM budget (vmem_bytes_3x3). 10 MB
    against the 16 MB scoped-vmem limit: with the lane padding counted for
    real, this reproduces the serving tiles that have been stable since
    round 2 while keeping narrow-channel (test-sized) models under the
    hard limit."""
    budget = _VMEM_BUDGET
    tile_co = _pick_tile(cout, 256)
    while (tile_co > 128
           and 2 * 9 * cin * _lane(tile_co) * in_itemsize > budget // 3):
        tile_co = _pick_tile(cout, tile_co // 2)
    tile_h = _pick_tile(h, 64)
    while tile_h > 1:
        if vmem_bytes_3x3(tile_h, tile_co, w, cin, in_itemsize,
                          out_itemsize) <= budget:
            break
        tile_h = _pick_tile(h, tile_h // 2)
    return tile_h, tile_co


def _conv3x3_kernel(x_ref, w_ref, sb_ref, o_ref, *, tile_h, width, relu,
                    dx_major):
    """One (batch, row-tile, cout-tile) grid step.

    x_ref: [tile_h + 2, W + 2, Cin] halo slab (pl.Element rows) cut from the
        batch-flattened [B * (H + 2), W + 2, Cin] padded input.
    w_ref: [3, 3, Cin, tile_co].
    sb_ref: [2, tile_co] folded scale/bias rows.
    o_ref: [tile_h, W, tile_co] tile of the [B * H, W, Cout] output.

    Two loop orders, chosen statically (measured on v5e, see
    tests/test_pallas.py and BENCH notes):
    - ``dx_major``: one sublane shift per dx (3 total); after flattening rows
      into the sublane dim the dy offsets are W-aligned slices (an address
      offset, not a relayout). Wins for narrow feature maps (W <= ~128).
    - dy-major: nine small shifted patches. Wins for wide maps (W >= ~256)
      where whole-slab relayouts are the dominant cost.
    """
    cin = x_ref.shape[-1]
    tile_co = o_ref.shape[-1]
    slab = x_ref[:]
    acc = jnp.zeros((tile_h * width, tile_co), jnp.float32)
    if dx_major:
        for dx in range(3):
            flat = slab[:, dx:dx + width, :].reshape(
                (tile_h + 2) * width, cin
            )
            for dy in range(3):
                patch = flat[dy * width:dy * width + tile_h * width]
                acc = acc + jnp.dot(
                    patch, w_ref[dy, dx], preferred_element_type=jnp.float32
                )
    else:
        for dy in range(3):
            for dx in range(3):
                patch = slab[dy:dy + tile_h, dx:dx + width, :].reshape(
                    tile_h * width, cin
                )
                acc = acc + jnp.dot(
                    patch, w_ref[dy, dx], preferred_element_type=jnp.float32
                )
    y = acc * sb_ref[0:1, :] + sb_ref[1:2, :]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[:] = y.reshape(tile_h, width, tile_co).astype(o_ref.dtype)


@shape_contract(x="b h w ci", w="3 3 ci co", scale="co", bias="co",
                out="b h w co")
@functools.partial(
    jax.jit, static_argnames=("relu", "out_dtype", "interpret", "tiling")
)
def conv3x3_bn_relu(
    x, w, scale, bias, *, relu: bool = True, out_dtype=None,
    interpret: bool = False, tiling=None,
):
    """Fused NHWC 3x3 SAME conv + per-channel scale/bias (+ ReLU).

    The Pallas form of the reference DoubleConv half-block
    (pkg/segmentation_model.py:33-39: Conv2d(bias=False) -> BatchNorm ->
    ReLU), with BatchNorm pre-folded via :func:`fold_batchnorm`.

    Args:
        x: [B, H, W, Cin].
        w: [3, 3, Cin, Cout] (HWIO, the Flax kernel layout).
        scale, bias: [Cout] f32 epilogue coefficients.
        relu: apply max(y, 0) in the epilogue.
        out_dtype: output dtype (default: x.dtype).
        interpret: run the Pallas interpreter (CPU tests).
        tiling: optional (tile_h, tile_co, dx_major) override of the
            analytic VMEM-budget heuristic -- the autotuner
            (bench_pallas.py autotune / ops/pallas/tuning.py) sweeps these
            per shape; tile_h must divide H and tile_co divide Cout.
    """
    b, h, width, cin = x.shape
    cout = w.shape[-1]
    out_dtype = x.dtype if out_dtype is None else out_dtype
    if tiling is not None:
        tile_h, tile_co, dx_major = tiling
        if h % tile_h or cout % tile_co:
            raise ValueError(
                f"tiling {tiling} does not divide (H={h}, Cout={cout})"
            )
    else:
        tile_h, tile_co = _tiles_3x3(
            h, width, cin, cout, x.dtype.itemsize,
            jnp.dtype(out_dtype).itemsize
        )
        dx_major = width <= 192

    # Flatten batch into rows: each image is padded separately, so a halo
    # slab never crosses an image boundary (row tiles divide H exactly).
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0))).reshape(
        b * (h + 2), width + 2, cin
    )
    w = w.astype(x.dtype)  # MXU-native operand dtype, same as the XLA path
    sb = jnp.stack([scale, bias]).astype(jnp.float32)  # [2, Cout]

    kern = functools.partial(
        _conv3x3_kernel, tile_h=tile_h, width=width, relu=relu,
        dx_major=dx_major,
    )
    tiles = h // tile_h
    out = pl.pallas_call(
        kern,
        grid=(b * tiles, cout // tile_co),
        in_specs=[
            _element_block_spec(
                (tile_h + 2, width + 2, cin),
                lambda t, co: (
                    (t // tiles) * (h + 2) + (t % tiles) * tile_h, 0, 0
                ),
            ),
            pl.BlockSpec((3, 3, cin, tile_co), lambda t, co: (0, 0, 0, co)),
            pl.BlockSpec((2, tile_co), lambda t, co: (0, co)),
        ],
        out_specs=pl.BlockSpec(
            (tile_h, width, tile_co), lambda t, co: (t, 0, co)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, width, cout), out_dtype),
        interpret=interpret,
    )(xp, w, sb)
    return out.reshape(b, h, width, cout)


def conv3x3_bn_relu_xla(x, w, scale, bias, *, relu: bool = True,
                        out_dtype=None):
    """XLA fallback / numerics oracle for :func:`conv3x3_bn_relu`."""
    out_dtype = x.dtype if out_dtype is None else out_dtype
    y = jax.lax.conv_general_dilated(
        x.astype(x.dtype), w.astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    y = y * scale + bias
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(out_dtype)


def _conv1x1_kernel(x_ref, w_ref, sb_ref, o_ref, *, relu):
    """x_ref: [1, tile_h, W, Cin]; w_ref: [Cin, tile_co]."""
    th, width, cin = x_ref.shape[1:]
    tile_co = o_ref.shape[-1]
    y = jnp.dot(
        x_ref[0].reshape(th * width, cin), w_ref[:],
        preferred_element_type=jnp.float32,
    )
    y = y * sb_ref[0:1, :] + sb_ref[1:2, :]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[0] = y.reshape(th, width, tile_co).astype(o_ref.dtype)


def _conv1x1_squeeze_kernel(x_ref, w_ref, sb_ref, o_ref, *, relu):
    """cout == 1 head: the output block is [1, tile_h, W] so the *width*
    rides on the VMEM lane dimension. Writing a [..., 1] block instead would
    pad that final dim 1 -> 128 lanes and blow the scoped-VMEM budget 128x
    (observed as a 24 MB stack allocation at batch 8, 256x256)."""
    th, width, cin = x_ref.shape[1:]
    y = jnp.dot(
        x_ref[0].reshape(th * width, cin), w_ref[:],
        preferred_element_type=jnp.float32,
    )
    y = y * sb_ref[0, 0] + sb_ref[1, 0]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[0] = y.reshape(th, width).astype(o_ref.dtype)


@shape_contract(x="b h w ci", w="ci co", scale="co", bias="co",
                out="b h w co")
@functools.partial(
    jax.jit, static_argnames=("relu", "out_dtype", "interpret")
)
def conv1x1(x, w, scale, bias, *, relu: bool = False, out_dtype=None,
            interpret: bool = False):
    """Fused NHWC 1x1 conv + scale/bias (+ ReLU): the OutConv head
    (reference: pkg/segmentation_model.py:78-84) with an identity scale and
    the conv bias riding in ``bias``."""
    b, h, width, cin = x.shape
    cout = w.shape[-1]
    out_dtype = x.dtype if out_dtype is None else out_dtype
    tile_co = _pick_tile(cout, 256)
    squeeze = cout == 1
    # VMEM budget per block, counting the lane padding the (8,128) tiled
    # layout applies to each buffer's final dimension.
    budget = 5 * 1024 * 1024

    def _padded(n: int) -> int:
        return -(-n // 128) * 128

    out_lanes = width if squeeze else _padded(tile_co)
    out_lane_rows = 1 if squeeze else width
    tile_h = _pick_tile(h, 128)
    while tile_h > 1 and 2 * tile_h * (
        width * _padded(cin) * x.dtype.itemsize
        + out_lane_rows * out_lanes * jnp.dtype(out_dtype).itemsize
    ) + tile_h * width * tile_co * 4 > budget:
        tile_h = _pick_tile(h, tile_h // 2)
    w = w.astype(x.dtype)
    sb = jnp.stack([scale, bias]).astype(jnp.float32)

    x_spec = pl.BlockSpec(
        (1, tile_h, width, cin), lambda bi, t, co: (bi, t, 0, 0)
    )
    if squeeze:
        out = pl.pallas_call(
            functools.partial(_conv1x1_squeeze_kernel, relu=relu),
            grid=(b, h // tile_h),
            in_specs=[
                pl.BlockSpec(
                    (1, tile_h, width, cin), lambda bi, t: (bi, t, 0, 0)
                ),
                pl.BlockSpec((cin, 1), lambda bi, t: (0, 0)),
                pl.BlockSpec((2, 1), lambda bi, t: (0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, tile_h, width), lambda bi, t: (bi, t, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((b, h, width), out_dtype),
            interpret=interpret,
        )(x, w, sb)
        return out[..., None]

    kern = functools.partial(_conv1x1_kernel, relu=relu)
    return pl.pallas_call(
        kern,
        grid=(b, h // tile_h, cout // tile_co),
        in_specs=[
            x_spec,
            pl.BlockSpec((cin, tile_co), lambda bi, t, co: (0, co)),
            pl.BlockSpec((2, tile_co), lambda bi, t, co: (0, co)),
        ],
        out_specs=pl.BlockSpec(
            (1, tile_h, width, tile_co), lambda bi, t, co: (bi, t, 0, co)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, width, cout), out_dtype),
        interpret=interpret,
    )(x, w, sb)


def conv1x1_xla(x, w, scale, bias, *, relu: bool = False, out_dtype=None):
    out_dtype = x.dtype if out_dtype is None else out_dtype
    y = jnp.einsum(
        "bhwi,io->bhwo", x, w.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    y = y * scale + bias
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(out_dtype)


def _convt2x2_kernel(x_ref, w_ref, b_ref, o_ref, *, tile_h, width):
    """2x2 stride-2 transposed conv: each input pixel spawns a 2x2 output
    patch, so the kernel is four independent matmuls whose results
    interleave. x_ref: [1, tile_h, W, Cin]; w_ref: [2, 2, Cin, tile_co]."""
    cin = x_ref.shape[-1]
    tile_co = o_ref.shape[-1]
    x2d = x_ref[0].reshape(tile_h * width, cin)

    def tap(dy, dx):
        # out[2h+dy, 2w+dx] = x[h, w] @ w[1-dy, 1-dx] -- the spatially
        # flipped tap, matching lax.conv_transpose/Flax semantics
        # (verified exact against an f64 oracle).
        y = jnp.dot(
            x2d, w_ref[1 - dy, 1 - dx], preferred_element_type=jnp.float32
        )
        return y.reshape(tile_h, width, tile_co)

    # interleave columns then rows
    row0 = jnp.stack([tap(0, 0), tap(0, 1)], axis=2).reshape(
        tile_h, 2 * width, tile_co
    )
    row1 = jnp.stack([tap(1, 0), tap(1, 1)], axis=2).reshape(
        tile_h, 2 * width, tile_co
    )
    out = jnp.stack([row0, row1], axis=1).reshape(
        2 * tile_h, 2 * width, tile_co
    )
    o_ref[0] = (out + b_ref[0:1, :]).astype(o_ref.dtype)


@shape_contract(x="b h w ci", w="2 2 ci co", bias="co")
@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def conv_transpose2x2(x, w, bias, *, out_dtype=None, interpret: bool = False):
    """NHWC 2x2 stride-2 transposed conv + bias: the reference's
    non-bilinear ``Up`` upsampler (pkg/segmentation_model.py:62-63).

    Args:
        x: [B, H, W, Cin]; w: [2, 2, Cin, Cout]; bias: [Cout].
    Returns [B, 2H, 2W, Cout].
    """
    b, h, width, cin = x.shape
    cout = w.shape[-1]
    out_dtype = x.dtype if out_dtype is None else out_dtype
    tile_co = _pick_tile(cout, 256)
    budget = 5 * 1024 * 1024
    tile_h = _pick_tile(h, 32)
    while tile_h > 1 and 2 * tile_h * width * (
        _lane(cin) * x.dtype.itemsize
        + 4 * _lane(tile_co) * jnp.dtype(out_dtype).itemsize
    ) + 4 * tile_h * width * _lane(tile_co) * 4 > budget:
        tile_h = _pick_tile(h, tile_h // 2)
    w = w.astype(x.dtype)
    bias2d = jnp.asarray(bias, jnp.float32).reshape(1, cout)

    kern = functools.partial(_convt2x2_kernel, tile_h=tile_h, width=width)
    return pl.pallas_call(
        kern,
        grid=(b, h // tile_h, cout // tile_co),
        in_specs=[
            pl.BlockSpec(
                (1, tile_h, width, cin), lambda bi, t, co: (bi, t, 0, 0)
            ),
            pl.BlockSpec((2, 2, cin, tile_co), lambda bi, t, co: (0, 0, 0, co)),
            pl.BlockSpec((1, tile_co), lambda bi, t, co: (0, co)),
        ],
        out_specs=pl.BlockSpec(
            (1, 2 * tile_h, 2 * width, tile_co),
            lambda bi, t, co: (bi, t, 0, co),
        ),
        out_shape=jax.ShapeDtypeStruct((b, 2 * h, 2 * width, cout), out_dtype),
        interpret=interpret,
    )(x, w, bias2d)


def conv_transpose2x2_xla(x, w, bias, *, out_dtype=None):
    out_dtype = x.dtype if out_dtype is None else out_dtype
    y = jax.lax.conv_transpose(
        x, w.astype(x.dtype), (2, 2), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    return (y + jnp.asarray(bias, jnp.float32)).astype(out_dtype)


# ---------------------------------------------------------------------------
# Training-path custom-VJP conv (forward AND backward as Pallas kernels).
#
# The inference kernels above fold BatchNorm, which training cannot (batch
# statistics must be computed from the live conv output), so the training
# unit is the RAW 3x3 no-bias conv of the reference DoubleConv
# (pkg/segmentation_model.py:30-33); BatchNorm/ReLU stay in XLA where their
# train-mode statistics autodiff for free. All three derivatives of a
# stride-1 SAME 3x3 conv are themselves MXU-shaped programs:
#
#   y  = conv(x, w)                   -- the forward kernel (unit epilogue)
#   dx = conv(dy, flipT(w))           -- SAME conv with the spatially
#                                        flipped, in/out-transposed kernel:
#                                        the SAME forward kernel reused
#   dw[ky,kx] = sum_bhw xpad[...+ky, ...+kx]^T @ dy   -- nine reduction
#                                        matmuls: a dedicated accumulating
#                                        kernel below
# ---------------------------------------------------------------------------


def _conv3x3_dw_kernel(x_ref, g_ref, o_ref, *, tile_h, width):
    """One (cout-tile, slab) grid step of the weight-gradient reduction.

    x_ref: [1, tile_h + 2, W + 2, Cin] pre-materialized halo slab (standard
        block indexing -- see conv3x3_grad_weights for why not pl.Element).
    g_ref: [1, tile_h, W, tile_co] tile of the upstream gradient.
    o_ref: [9, Cin, tile_co] all nine taps' gradient block, revisited (and
        accumulated into) across every slab grid step -- the slab axis is
        the minor grid dimension, so TPU grid sequencing makes the
        accumulation well-defined. The nine tap windows are SLICED inside
        the kernel (static offsets), the same scheme as the forward kernel.
    """
    cin = x_ref.shape[-1]
    tile_co = o_ref.shape[-1]
    s = pl.program_id(1)
    slab = x_ref[0]
    g2d = g_ref[0].reshape(tile_h * width, tile_co)

    @pl.when(s == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    for ky in range(3):
        for kx in range(3):
            patch = slab[ky:ky + tile_h, kx:kx + width, :].reshape(
                tile_h * width, cin
            )
            part = jax.lax.dot_general(
                patch, g2d, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            o_ref[ky * 3 + kx] += part


@functools.partial(jax.jit, static_argnames=("interpret",))
def conv3x3_grad_weights(x, g, *, interpret: bool = False):
    """dL/dw for a stride-1 SAME 3x3 no-bias conv: [3, 3, Cin, Cout] f32.

    Unlike the forward kernel, the overlapping halo slabs are materialized
    at the XLA level (one extra HBM copy of x, ~2/tile_h overhead) and the
    kernel uses standard block indexing. The pl.Element halo scheme the
    forward kernel uses is NOT available here: this image's TPU compile
    service crashes (HTTP 500, tpu_compile_helper exit 1) whenever an
    Element-indexed dw kernel shares one XLA module with the forward
    kernel -- as every backward pass does -- so the dw kernel avoids
    Element indexing entirely.

    Args:
        x: [B, H, W, Cin] forward input.
        g: [B, H, W, Cout] upstream gradient.
    """
    b, h, width, cin = x.shape
    cout = g.shape[-1]
    if cin < 64:
        # narrow lane dims (the RGB input layer) crash this image's
        # compile helper at serving scale; zero-padded channels contribute
        # exactly zero to the gradient, so pad up to a full lane tile and
        # slice the result back (the layer is a negligible FLOP fraction)
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, 64 - cin)))
        return conv3x3_grad_weights(x, g, interpret=interpret)[:, :, :cin]
    # VMEM accounting against the 16 MB scoped limit (observed error
    # text): the f32 9-tap accumulator block, the double-buffered slab and
    # gradient tiles, AND the nine unrolled in-kernel patch reshapes --
    # the compiler stack-allocates all nine live (measured: 9 x patch
    # dominates the 16.69M OOM at tile_h=32, W=256, C=64).
    tile_co = cout
    while 9 * cin * _lane(tile_co) * 4 > 6 * 1024 * 1024 and tile_co % 256 == 0:
        tile_co //= 2
    acc = 9 * cin * _lane(tile_co) * 4
    budget = 10 * 1024 * 1024
    tile_h = _pick_tile(h, 32)
    while tile_h > 1 and (
        2 * ((tile_h + 2) * (width + 2) * _lane(cin) * x.dtype.itemsize
             + tile_h * width * _lane(tile_co) * g.dtype.itemsize)
        + 9 * tile_h * width * _lane(cin) * x.dtype.itemsize
        + acc
    ) > budget:
        tile_h = _pick_tile(h, tile_h // 2)
    tiles = h // tile_h

    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    # overlapping slabs: [B, tiles, tile_h + 2, W + 2, Cin] -> flat slabs
    slabs = jnp.stack(
        [xp[:, i * tile_h:i * tile_h + tile_h + 2] for i in range(tiles)],
        axis=1,
    ).reshape(b * tiles, tile_h + 2, width + 2, cin)
    gf = g.reshape(b * tiles, tile_h, width, cout)

    out = pl.pallas_call(
        functools.partial(_conv3x3_dw_kernel, tile_h=tile_h, width=width),
        grid=(cout // tile_co, b * tiles),
        in_specs=[
            pl.BlockSpec(
                (1, tile_h + 2, width + 2, cin),
                lambda co, s: (s, 0, 0, 0),
            ),
            pl.BlockSpec((1, tile_h, width, tile_co),
                         lambda co, s: (s, 0, 0, co)),
        ],
        out_specs=pl.BlockSpec((9, cin, tile_co), lambda co, s: (0, 0, co)),
        out_shape=jax.ShapeDtypeStruct((9, cin, cout), jnp.float32),
        interpret=interpret,
    )(slabs, gf)
    return out.reshape(3, 3, cin, cout)


def conv3x3_grad_weights_xla(x, g):
    """XLA oracle for :func:`conv3x3_grad_weights` (the standard
    activations*grads correlation, expressed as a conv over the batch dim)."""
    dw = jax.lax.conv_general_dilated(
        jnp.transpose(x, (3, 1, 2, 0)),  # [Cin, H, W, B]
        jnp.transpose(g, (1, 2, 0, 3)),  # [H, W, B, Cout] as an HxW kernel
        window_strides=(1, 1), padding=((1, 1), (1, 1)),  # -> 3x3 output
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )  # [Cin, 3, 3, Cout]
    return jnp.transpose(dw, (1, 2, 0, 3))


def _vjp_pallas(x, cin: int, cout: int, impl: str, interpret: bool) -> bool:
    """ONE dispatch predicate shared by the custom-VJP forward, dx, and dw
    (so the rules cannot drift apart between them). True -> Pallas kernels.

    - interpret always exercises the interpreted Pallas kernels (they are
      what the CPU tests exist to validate);
    - sub-sublane channel counts (the RGB input layer, its cout=3 dx conv,
      and any dw whose lane dim would be < 8) crash this image's compile
      helper at large batch; those layers are a negligible FLOP fraction
      and already sit at XLA boundaries, so they run the XLA forms under
      every COMPILED dispatch mode, forced "pallas" included;
    - measured v5e crossover for the TRAIN step (chained scan, 256^2):
      full-Pallas custom-VJP 21.8 ms vs XLA 22.6 at batch 4 (the reference
      config, train_segmenter.py:46; volume 4 * 256^2 == 2^18) but 210 vs
      115 ms at batch 32 -- the same "batched wide maps favor XLA" physics
      as inference. "auto" therefore gates at the measured 2^18 anchor;
      the b8/b16 region is unmeasured and conservatively routed to XLA.
    """
    if interpret or impl == "interpret":
        return True
    if min(cin, cout) < 8:
        return False
    if impl == "pallas":
        return True
    if impl == "xla":
        return False
    small = x.shape[0] * x.shape[1] * x.shape[2] <= 2 ** 18
    return use_pallas() and small


def _conv3x3_raw(x, w, impl: str, interpret: bool):
    cin, cout = w.shape[2], w.shape[3]
    unit = jnp.ones((cout,), jnp.float32)
    zero = jnp.zeros((cout,), jnp.float32)
    interpret = interpret or impl == "interpret"
    if _vjp_pallas(x, cin, cout, impl, interpret):
        return conv3x3_bn_relu(
            x, w, unit, zero, relu=False, interpret=interpret
        )
    return conv3x3_bn_relu_xla(x, w, unit, zero, relu=False)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv3x3(x, w, impl: str = "auto", interpret: bool = False):
    """Differentiable stride-1 SAME 3x3 no-bias conv with Pallas forward
    and backward kernels -- the training-path form of the DoubleConv
    half-block's conv (reference: pkg/segmentation_model.py:30-33).

    ``impl``: "auto" (Pallas on TPU, XLA elsewhere), "pallas", or "xla" --
    the same measured-dispatch convention as the inference path.
    """
    return _conv3x3_raw(x, w, impl, interpret)


def _conv3x3_fwd(x, w, impl, interpret):
    return _conv3x3_raw(x, w, impl, interpret), (x, w)


def _conv3x3_bwd(impl, interpret, res, g):
    x, w = res
    g = g.astype(x.dtype)
    # dx: SAME conv of the upstream gradient with the flipped, transposed
    # kernel -- the same forward kernel on transformed weights.
    wt = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2)).astype(x.dtype)
    dx = _conv3x3_raw(g, wt, impl, interpret)
    interpret = interpret or impl == "interpret"
    # the shared predicate, on the conv's own (cin, cout): the dw kernel's
    # lane dims are cout (accumulator) and cin (slab)
    if _vjp_pallas(x, w.shape[2], w.shape[3], impl, interpret):
        dw = conv3x3_grad_weights(x, g, interpret=interpret)
    else:
        dw = conv3x3_grad_weights_xla(x, g)
    return dx.astype(x.dtype), dw.astype(w.dtype)


conv3x3.defvjp(_conv3x3_fwd, _conv3x3_bwd)
