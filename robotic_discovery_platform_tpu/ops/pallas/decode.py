"""Fused dequant + 8x8 IDCT for the split JPEG decode, on the block axis.

The device half of the ROADMAP "device-side ingest" split (host half:
serving/entropy.py). The host ships QUANTIZED coefficient blocks
``[B, N, 64] int16`` plus per-frame quant tables ``[B, 64]``; this kernel
fuses the dequantize multiply with the 2-D 8x8 inverse DCT and the final
level shift/clamp, so the only HBM traffic is coefficients in, spatial
samples out -- bandwidth-bound by construction (utils/flops.py
``jpeg_idct_roofline_ms``; bench_pallas.py asserts it).

**Why integer matmuls.** libjpeg's ``jpeg_idct_islow`` -- what
``cv2.imdecode`` runs -- is a fixed-point Loeffler factorization that is
LINEAR between its two DESCALE roundings: pass 1 (columns) is an exact
integer linear map of the dequantized inputs, DESCALE(.., 11), and pass 2
(rows) is the SAME map followed by DESCALE(.., 18) + 128. Feeding unit
vectors through the butterflies with exact integer arithmetic yields the
8x8 integer basis matrix A (:func:`islow_basis`); on the flattened block
axis the two passes become two ``[N, 64] @ [64, 64]`` matmuls --
``kron(A, I8)`` then ``kron(I8, A)`` -- i.e. batched DCT-basis matmuls in
exactly the MXU shape the ISSUE/ROADMAP call for, while staying BITWISE
equal to libjpeg (int32 two's-complement wrap and arithmetic shifts match
C semantics in both numpy and XLA). That bit-exactness is what lets the
golden tests pin the whole split decode against ``cv2.imdecode`` and the
XLA path against the Pallas path (co-traced in one jit, the
tests/test_pallas_geometry.py idiom).

Dispatch rides the same machinery as the geometry kernels:
``GeometryConfig.kernel_impl`` through :func:`geometry.resolve_impl` with
the op key ``"jpeg_idct"``, so PALLAS_TUNE.json can pin either backend per
(batch, blocks) shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from robotic_discovery_platform_tpu.ops.pallas.conv import _pick_tile
from robotic_discovery_platform_tpu.ops.pallas.geometry import resolve_impl

# islow fixed-point constants: FIX(x) at CONST_BITS = 13.
_CONST_BITS = 13
_PASS1_SHIFT = _CONST_BITS - 2           # 11: pass 1 DESCALE
_PASS2_SHIFT = _CONST_BITS + 2 + 3       # 18: pass 2 DESCALE
_FIX = {
    "c0298": 2446, "c0390": 3196, "c0541": 4433, "c0765": 6270,
    "c0899": 7373, "c1175": 9633, "c1501": 12299, "c1847": 15137,
    "c1961": 16069, "c2053": 16819, "c2562": 20995, "c3072": 25172,
}


@functools.lru_cache(maxsize=None)
def islow_basis() -> np.ndarray:
    """The exact [8, 8] int32 basis matrix of one ``jpeg_idct_islow`` pass.

    Runs the islow butterfly on unit vectors with Python ints (the pass is
    linear up to its DESCALE, so columns of the result ARE the matrix).
    ``pass_out = DESCALE(A @ x, shift)`` reproduces libjpeg bit for bit.
    """
    f = _FIX
    a = np.zeros((8, 8), np.int64)
    for j in range(8):
        x = [0] * 8
        x[j] = 1
        z2, z3 = x[2], x[6]
        z1 = (z2 + z3) * f["c0541"]
        t2 = z1 - z3 * f["c1847"]
        t3 = z1 + z2 * f["c0765"]
        t0 = (x[0] + x[4]) << _CONST_BITS
        t1 = (x[0] - x[4]) << _CONST_BITS
        t10, t13 = t0 + t3, t0 - t3
        t11, t12 = t1 + t2, t1 - t2
        o0, o1, o2, o3 = x[7], x[5], x[3], x[1]
        z1, z2 = o0 + o3, o1 + o2
        z3, z4 = o0 + o2, o1 + o3
        z5 = (z3 + z4) * f["c1175"]
        o0 *= f["c0298"]
        o1 *= f["c2053"]
        o2 *= f["c3072"]
        o3 *= f["c1501"]
        z1 *= -f["c0899"]
        z2 *= -f["c2562"]
        z3 = z3 * -f["c1961"] + z5
        z4 = z4 * -f["c0390"] + z5
        o0 += z1 + z3
        o1 += z2 + z4
        o2 += z2 + z3
        o3 += z1 + z4
        col = (t10 + o3, t11 + o2, t12 + o1, t13 + o0,
               t13 - o0, t12 - o1, t11 - o2, t10 - o3)
        for i in range(8):
            a[i, j] = col[i]
    return a.astype(np.int32)


@functools.lru_cache(maxsize=None)
def _pass_matrices() -> tuple:
    """([64, 64], [64, 64]) int32 right-multiply forms of the two passes.

    With blocks flattened row-major (index = 8*row + col):
    pass 1 contracts block COLUMNS -> ``x @ kron(A, I8).T``;
    pass 2 contracts block ROWS    -> ``ws @ kron(I8, A).T``.
    """
    a = islow_basis().astype(np.int64)
    eye = np.eye(8, dtype=np.int64)
    m1 = np.kron(a, eye).T.astype(np.int32)
    m2 = np.kron(eye, a).T.astype(np.int32)
    return m1, m2


def _descale(x, shift: int):
    """libjpeg DESCALE: round-half-up then arithmetic shift right."""
    return (x + (1 << (shift - 1))) >> shift


def _idct_math(deq, m1, m2):
    """The shared two-pass islow arithmetic, [M, 64] int32 in/out.

    Used verbatim by BOTH the XLA reference path and the Pallas kernel
    body, so interpret-mode results match the XLA path bitwise (integer
    ops have no contraction-order freedom).
    """
    ws = _descale(
        jax.lax.dot(deq, m1, preferred_element_type=jnp.int32),
        _PASS1_SHIFT,
    )
    s = _descale(
        jax.lax.dot(ws, m2, preferred_element_type=jnp.int32),
        _PASS2_SHIFT,
    ) + 128
    return jnp.clip(s, 0, 255)


def _idct_kernel(c_ref, q_ref, m1_ref, m2_ref, o_ref):
    """One (frame, block-tile) grid step: [1, tile_n, 64] coefficients
    dequantized against that frame's [1, 64] quant row, then the two
    matmul passes. The basis matrices ride in as inputs (a kernel cannot
    close over array constants)."""
    o_ref[0] = _idct_math(
        c_ref[0] * q_ref[:], m1_ref[:], m2_ref[:]
    )


@functools.partial(jax.jit, static_argnames=("impl",))
def dequant_idct(coefs, q, *, impl: str = "auto"):
    """Fused dequantize + 8x8 islow IDCT over the block axis.

    Args:
        coefs: [B, N, 64] integer QUANTIZED coefficients, natural
            (row-major) order -- ``serving.entropy.CoefficientFrame``
            planes, batched.
        q: [B, 64] integer quant tables (per frame: tables may differ
            across cameras/qualities within one batch).
        impl: ``GeometryConfig.kernel_impl`` semantics via
            :func:`resolve_impl` ("auto" consults PALLAS_TUNE.json, then
            Pallas-on-TPU/XLA-elsewhere).

    Returns [B, N, 64] int32 spatial samples in 0..255 (level-shifted,
    range-limited), bitwise equal to libjpeg's islow output.
    """
    b, n, _ = coefs.shape
    cc = jnp.asarray(coefs).astype(jnp.int32)
    qq = jnp.asarray(q).astype(jnp.int32)
    m1, m2 = _pass_matrices()
    which = resolve_impl(impl, "jpeg_idct", b=b, n=n)
    if which == "xla":
        deq = (cc * qq[:, None, :]).reshape(b * n, 64)
        return _idct_math(
            deq, jnp.asarray(m1), jnp.asarray(m2)
        ).reshape(b, n, 64)
    tile_n = _pick_tile(n, 512)
    return pl.pallas_call(
        _idct_kernel,
        grid=(b, n // tile_n),
        in_specs=[
            pl.BlockSpec((1, tile_n, 64), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 64), lambda i, j: (i, 0)),
            pl.BlockSpec((64, 64), lambda i, j: (0, 0)),
            pl.BlockSpec((64, 64), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_n, 64), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n, 64), jnp.int32),
        interpret=which == "interpret",
    )(cc, qq, jnp.asarray(m1), jnp.asarray(m2))
