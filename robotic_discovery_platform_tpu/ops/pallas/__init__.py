"""Fused TPU Pallas kernels (conv-BN-ReLU, transpose-conv, 1x1 head) and the
Pallas-backed U-Net inference forward. See conv.py for the kernel design and
unet_infer.py for the per-layer pallas/XLA dispatch policy."""

from robotic_discovery_platform_tpu.ops.pallas.conv import (  # noqa: F401
    conv1x1,
    conv1x1_xla,
    conv3x3_bn_relu,
    conv3x3_bn_relu_xla,
    conv_transpose2x2,
    conv_transpose2x2_xla,
    fold_batchnorm,
    use_pallas,
)
from robotic_discovery_platform_tpu.ops.pallas.unet_infer import (  # noqa: F401
    PallasUNet,
    make_pallas_unet,
)
