"""Fused TPU Pallas kernels (conv-BN-ReLU, transpose-conv, 1x1 head, the
deproject+reduction and B-spline geometry kernels) and the Pallas-backed
U-Net inference forward. See conv.py / geometry.py for the kernel designs,
unet_infer.py for the per-layer pallas/XLA dispatch policy, and quant.py
for the bf16/int8 serving precision tiers."""

from robotic_discovery_platform_tpu.ops.pallas.conv import (  # noqa: F401
    conv1x1,
    conv1x1_xla,
    conv3x3_bn_relu,
    conv3x3_bn_relu_xla,
    conv_transpose2x2,
    conv_transpose2x2_xla,
    fold_batchnorm,
    use_pallas,
)
from robotic_discovery_platform_tpu.ops.pallas.unet_infer import (  # noqa: F401
    PallasUNet,
    make_pallas_unet,
)
