"""Per-shape Pallas tile tuning: measured overrides for the analytic
heuristic.

The conv3x3 kernel's default (tile_h, tile_co, dx_major) comes from a VMEM
budget formula (conv._tiles_3x3) that is deliberately conservative and
shape-agnostic. PALLASBENCH.json shows that for a few shapes (small spatial
extents with wide channels, e.g. 32x32 512->512) the analytic choice leaves
the kernel behind XLA's conv (round-4 verdict weak item 4). The autotuner
(``python bench_pallas.py autotune`` on the real chip) sweeps every
budget-feasible (tile_h, tile_co, dx_major) per deployed layer shape with
the chained-scan timing methodology and records the winners here; the
dispatch layer (unet_infer) then passes the measured tiling to each launch.

The tune table lives at ``PALLAS_TUNE.json`` in the repo root (next to
PALLASBENCH.json); a missing or stale table simply means the analytic
heuristic runs -- tuning is a pure overlay, never a correctness dependency.
Entries record the measured per-launch ms of both the tuned and heuristic
configs so the table is self-documenting evidence.
"""

from __future__ import annotations

import json
from pathlib import Path

from robotic_discovery_platform_tpu.ops.pallas.conv import (
    _VMEM_BUDGET,
    _tiles_3x3,
    vmem_bytes_3x3,
)

_TUNE_PATH = Path(__file__).resolve().parents[3] / "PALLAS_TUNE.json"
_cache: dict | None = None


def key(h: int, w: int, cin: int, cout: int, batch: int = 1,
        dtype: str = "bfloat16") -> str:
    return f"conv3x3:b{batch}:{h}x{w}:{cin}->{cout}:{dtype}"


def _table() -> dict:
    global _cache
    if _cache is None:
        try:
            _cache = json.loads(_TUNE_PATH.read_text()).get("entries", {})
        except (FileNotFoundError, json.JSONDecodeError):
            _cache = {}
    return _cache


def invalidate_cache() -> None:
    global _cache
    _cache = None


def lookup(h: int, w: int, cin: int, cout: int, batch: int = 1,
           dtype: str = "bfloat16"):
    """Measured (tile_h, tile_co, dx_major) for this shape, or None to use
    the analytic heuristic. Entries that no longer divide the shape or
    exceed the kernel's VMEM budget (e.g. a hand-edited or stale table)
    are ignored rather than trusted -- a bad table must never turn into a
    serving-time compile crash."""
    entry = _table().get(key(h, w, cin, cout, batch, dtype))
    if not entry:
        return None
    tile_h, tile_co = int(entry["tile_h"]), int(entry["tile_co"])
    if h % tile_h or cout % tile_co:
        return None
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    if vmem_bytes_3x3(tile_h, tile_co, w, cin, itemsize,
                      itemsize) > _VMEM_BUDGET:
        return None
    return tile_h, tile_co, bool(entry["dx_major"])


def candidates(h: int, w: int, cin: int, cout: int,
               in_itemsize: int = 2, out_itemsize: int = 2):
    """Every budget-feasible (tile_h, tile_co, dx_major) for the sweep:
    divisor tile sizes up to 128 rows / 512 channels, both loop orders,
    deduplicated, analytic choice first (so index 0 is the baseline)."""
    heur = _tiles_3x3(h, w, cin, cout, in_itemsize, out_itemsize)
    seen, out = set(), []
    tile_hs = [t for t in (1, 2, 4, 8, 16, 32, 64, 128)
               if t <= h and h % t == 0]
    tile_cos = [c for c in (64, 128, 256, 512)
                if c <= cout and cout % c == 0] or [cout]
    for dx_major in (w <= 192, not (w <= 192)):  # heuristic order first
        for th in tile_hs:
            for co in tile_cos:
                if vmem_bytes_3x3(th, co, w, cin, in_itemsize,
                                  out_itemsize) > _VMEM_BUDGET:
                    continue
                cand = (th, co, dx_major)
                if cand in seen:
                    continue
                seen.add(cand)
                out.append(cand)
    heuristic = (heur[0], heur[1], w <= 192)
    if heuristic in out:
        out.remove(heuristic)
    out.insert(0, heuristic)
    return out


def op_key(op: str, **dims) -> str:
    """Generic table key for the non-conv fused kernels: the op name plus
    its sorted shape dims, e.g. ``deproject:h480:s1:w640`` or
    ``bspline_design:c16:n6400``."""
    parts = [f"{k}{v}" for k, v in sorted(dims.items())]
    return ":".join([op] + parts)


def lookup_impl(op: str, **dims) -> str | None:
    """Measured backend override for one fused-geometry (op, shape):
    ``"pallas"`` / ``"xla"``, or None to use the caller's default policy.
    Written by the autotuner / by hand after a TPU bench window; entries
    with any other value are ignored (a hand-edited table must never turn
    into a dispatch crash)."""
    entry = _table().get(op_key(op, **dims))
    if not isinstance(entry, dict):
        return None
    impl = entry.get("impl")
    return impl if impl in ("pallas", "xla") else None


def save_entries(entries: dict, meta: dict) -> Path:
    """Write the tune table (autotuner only); invalidates the read cache."""
    _TUNE_PATH.write_text(json.dumps(
        {"meta": meta, "entries": entries}, indent=2, sort_keys=True
    ))
    invalidate_cache()
    return _TUNE_PATH


__all__ = [
    "key", "lookup", "candidates", "op_key", "lookup_impl",
    "save_entries", "invalidate_cache", "vmem_bytes_3x3", "_lane",
]
