"""Device-side mask bitpacking for the egress wire.

The egress twin of :mod:`decode`: where the split decode keeps decoded
PIXELS off the host on the way in, this kernel keeps the full-resolution
mask off the host on the way out. The analyzer's ``[B, H, W]`` uint8
binary mask packs to ``[B, H, ceil(W/8)]`` on the device (8 pixels per
byte, MSB first -- ``np.packbits`` order, so ``np.unpackbits`` is the
exact host-side inverse), an 8x reduction of the dominant D2H payload
before the completer's single blocking fetch. The op is one HBM pass --
mask in, bytes out -- so it is bandwidth-bound by construction
(utils/flops.py ``mask_bitpack_roofline_ms``; bench_pallas.py asserts
it).

Dispatch rides the same machinery as the geometry and decode kernels:
``GeometryConfig.kernel_impl`` through :func:`geometry.resolve_impl`
with the op key ``"mask_pack"``, so PALLAS_TUNE.json can pin either
backend per (batch, height, width) shape. The XLA fallback and the
Pallas kernel body share :func:`_pack_math` verbatim (integer ops, no
contraction-order freedom), so xla / pallas / interpret results are
bitwise identical -- the tests/test_egress.py co-traced gate.

This module also owns the PACKED PAYLOAD ROW layout the pipeline's
``pack_analysis`` emits and ``serving/egress.py`` parses: one
self-describing uint8 row per frame,

    [0:16)   header: ``<4sIII`` = (b"RDPP", height, width, n_pts)
    [16:..)  f32 sidecar, bitcast little-endian: coverage, mean
             curvature, max curvature, validity (1.0/0.0), confidence
             margin, then the [n_pts, 3] spline block row-major
    [..:..)  the bitpacked mask rows, H * ceil(W/8) bytes
    [..:P)   zero pad up to :func:`frame_payload_bytes` (a multiple of
             64, so every row of a 64-byte-aligned [B, P] staging
             buffer is itself 64-byte aligned)

The header makes each row self-describing: the completer hands rows out
without threading any (geometry, spline-count) metadata through the
dispatcher.
"""

from __future__ import annotations

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from robotic_discovery_platform_tpu.ops.pallas.conv import _pick_tile
from robotic_discovery_platform_tpu.ops.pallas.geometry import resolve_impl

#: f32 scalars ahead of the spline block in the sidecar: coverage,
#: mean curvature, max curvature, validity, confidence margin.
N_SCALARS = 5

#: bytes of the self-describing row header, ``<4sIII``.
HEADER_BYTES = 16

#: header magic of a packed payload ROW (staging layout). Wire payloads
#: carry their own magics (serving/egress.py: b"RDPB" / b"RDPR").
ROW_MAGIC = b"RDPP"

#: staging rows pad to a multiple of this, so rows of a 64-byte-aligned
#: pooled buffer (serving/batching._aligned_empty) stay 64-byte aligned.
ROW_ALIGN = 64


def sidecar_floats(n_pts: int) -> int:
    """f32 slots in the per-frame sidecar: the scalars + the spline."""
    return N_SCALARS + 3 * n_pts


def packed_row_bytes(w: int) -> int:
    """Bytes of one bitpacked mask row: ceil(w / 8)."""
    return (w + 7) // 8


def frame_payload_bytes(h: int, w: int, n_pts: int) -> int:
    """Total bytes of one frame's packed payload row, 64-byte padded."""
    raw = HEADER_BYTES + 4 * sidecar_floats(n_pts) + h * packed_row_bytes(w)
    return -(-raw // ROW_ALIGN) * ROW_ALIGN


@functools.lru_cache(maxsize=None)
def payload_header(h: int, w: int, n_pts: int) -> np.ndarray:
    """The [16] uint8 header constant for one frame geometry."""
    return np.frombuffer(
        struct.pack("<4sIII", ROW_MAGIC, h, w, n_pts), np.uint8
    )


def _pack_math(m):
    """The shared bitpack arithmetic, ``[..., wb, 8]`` -> ``[..., wb]``.

    Used verbatim by BOTH the XLA fallback and the Pallas kernel body,
    so interpret-mode results match the XLA path bitwise (pure integer
    ops). Nonzero input is a set bit, MSB first -- ``np.packbits``'
    default bit order, which makes ``np.unpackbits(packed, axis=-1)
    [..., :w]`` the exact inverse. Unrolled shift-accumulate with scalar
    literals (no captured array constant, which a Pallas kernel traced
    inside an outer jit would reject)."""
    bits = (m != 0).astype(jnp.int32)
    packed = bits[..., 0]
    for k in range(1, 8):
        packed = packed * 2 + bits[..., k]
    return packed.astype(jnp.uint8)


def _pack_kernel(m_ref, o_ref):
    """One (frame, row-tile) grid step: [1, tile_h, wb, 8] mask bits to
    [1, tile_h, wb] packed bytes."""
    o_ref[0] = _pack_math(m_ref[0])


@functools.partial(jax.jit, static_argnames=("impl",))
def bitpack_mask(mask, *, impl: str = "auto"):
    """Bitpack a ``[B, H, W]`` uint8 binary mask to ``[B, H, ceil(W/8)]``.

    Args:
        mask: [B, H, W] uint8 (any nonzero pixel packs as a set bit --
            the analyzer emits exact 0/1).
        impl: ``GeometryConfig.kernel_impl`` semantics via
            :func:`resolve_impl` ("auto" consults PALLAS_TUNE.json, then
            Pallas-on-TPU/XLA-elsewhere).

    Returns [B, H, ceil(W/8)] uint8, MSB-first per byte --
    ``np.unpackbits(out, axis=-1)[..., :W]`` recovers the exact mask.
    """
    b, h, w = mask.shape
    wb = packed_row_bytes(w)
    if w % 8:
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, wb * 8 - w)))
    m = mask.reshape(b, h, wb, 8)
    which = resolve_impl(impl, "mask_pack", b=b, h=h, w=w)
    if which == "xla":
        return _pack_math(m)
    tile_h = _pick_tile(h, 256)
    return pl.pallas_call(
        _pack_kernel,
        grid=(b, h // tile_h),
        in_specs=[
            pl.BlockSpec((1, tile_h, wb, 8), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_h, wb), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, wb), jnp.uint8),
        interpret=which == "interpret",
    )(m)
