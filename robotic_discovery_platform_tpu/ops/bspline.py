"""Fixed-knot cubic B-spline fitting in pure jax.numpy.

TPU-native replacement for the reference's FITPACK usage
(reference: pkg/geometry_utils.py:78 ``splprep(..., s=0.1, k=3)`` and
:148-149 ``splev(..., der=1|2)``). FITPACK is Fortran with data-dependent
knot placement -- unusable inside an XLA graph. Here the knot vector is
*static* (clamped, uniform interior knots), so fitting is a small dense
weighted least-squares solve with a difference penalty on control points
(P-spline smoothing, Eilers & Marx 1996) -- a few MXU-friendly matmuls and
one [C,C] solve, fully jittable and differentiable.

All functions take/return fixed-shape arrays and support a per-point
``weights`` vector so padded/invalid points (weight 0) are ignored without
dynamic shapes.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract

# All spline matmuls are tiny ([N, C] with C ~ 16); force full f32 precision
# so the TPU MXU's default-bf16 f32 matmul does not degrade curvature (second
# derivatives amplify rounding ~1e-3 relative under bf16 accumulation).
_mm = functools.partial(jnp.matmul, precision="highest")


def clamped_uniform_knots(num_ctrl: int, degree: int = 3) -> np.ndarray:
    """Clamped knot vector on [0, 1] with uniform interior knots.

    Length is ``num_ctrl + degree + 1``; the first/last ``degree + 1`` knots
    are pinned to 0/1 so the spline interpolates the parameter range ends.
    Static (numpy) because knots are compile-time constants.
    """
    if num_ctrl <= degree:
        raise ValueError(f"num_ctrl ({num_ctrl}) must exceed degree ({degree})")
    interior = np.linspace(0.0, 1.0, num_ctrl - degree + 1)[1:-1]
    return np.concatenate(
        [np.zeros(degree + 1), interior, np.ones(degree + 1)]
    ).astype(np.float64)


def _basis_columns(uu, knots, degree: int):
    """Cox-de Boor recursion on a COLUMN of parameters: ``uu`` is [N, 1],
    ``knots`` an already-cast jnp vector. Shared verbatim by the XLA path
    (:func:`bspline_basis`) and the fused Pallas kernels
    (ops/pallas/geometry.py), so the two paths are the same ops and their
    results compare bitwise."""
    n_knots = knots.shape[0]
    num_ctrl = n_knots - degree - 1

    # Degree-0: indicator of the half-open knot span, closed at the top so
    # u == 1 lands in the last nonempty span (FITPACK convention).
    t_lo = knots[:-1][None, :]  # [1, n_knots-1]
    t_hi = knots[1:][None, :]
    last_span = t_hi >= knots[-1]
    b = jnp.where(
        (uu >= t_lo) & ((uu < t_hi) | (last_span & (uu <= t_hi))),
        1.0,
        0.0,
    ).astype(uu.dtype)
    # Zero-width spans (clamped ends) must not fire.
    b = jnp.where((t_hi - t_lo) > 0, b, 0.0)

    for d in range(1, degree + 1):
        n_b = n_knots - 1 - d  # number of degree-d functions
        t_i = knots[:n_b][None, :]
        t_id = knots[d : d + n_b][None, :]
        t_i1 = knots[1 : 1 + n_b][None, :]
        t_id1 = knots[d + 1 : d + 1 + n_b][None, :]
        denom_l = t_id - t_i
        denom_r = t_id1 - t_i1
        left = jnp.where(denom_l > 0, (uu - t_i) / jnp.where(denom_l > 0, denom_l, 1.0), 0.0)
        right = jnp.where(denom_r > 0, (t_id1 - uu) / jnp.where(denom_r > 0, denom_r, 1.0), 0.0)
        b = left * b[:, :n_b] + right * b[:, 1 : 1 + n_b]
    assert b.shape[-1] == num_ctrl
    return b


def bspline_basis(u, knots, degree: int = 3):
    """Cox-de Boor basis matrix, vectorized over parameters.

    Args:
        u: [N] parameters in [0, 1].
        knots: [num_ctrl + degree + 1] knot vector (static).
        degree: spline degree (static).

    Returns:
        [N, num_ctrl] basis matrix B with ``spline(u) = B @ ctrl``.
    """
    u = jnp.asarray(u)
    knots = jnp.asarray(knots, dtype=u.dtype)
    return _basis_columns(u[:, None], knots, degree)


def _deriv_matrix_product(knots_np: np.ndarray, degree: int,
                          order: int) -> np.ndarray:
    """Static numpy product ``M_{p-order+1} @ ... @ M_p`` mapping the
    degree-(p-order) basis to the order-th derivative of the degree-p
    basis. Shared by :func:`bspline_basis_derivative` and the fused
    curvature kernel (ops/pallas/geometry.py)."""
    n_knots = knots_np.shape[0]

    # D maps degree-(d-1) basis coefficients to the derivative contribution of
    # degree-d basis: a static sparse-ish [n_{d-1}, n_d] matrix per level.
    def deriv_matrix(d: int) -> np.ndarray:
        n_hi = n_knots - 1 - d  # degree-d functions
        n_lo = n_hi + 1  # degree-(d-1) functions
        m = np.zeros((n_lo, n_hi))
        for i in range(n_hi):
            dl = knots_np[i + d] - knots_np[i]
            dr = knots_np[i + d + 1] - knots_np[i + 1]
            if dl > 0:
                m[i, i] += d / dl
            if dr > 0:
                m[i + 1, i] -= d / dr
        return m

    low = degree - order
    return functools.reduce(
        np.matmul, [deriv_matrix(d) for d in range(low + 1, degree + 1)]
    )


def bspline_basis_derivative(u, knots, degree: int = 3, order: int = 1):
    """Basis matrix of the ``order``-th derivative of the degree-``degree``
    basis: ``spline^(k)(u) = D @ ctrl``.

    Uses the standard recursion B'_{i,d} = d * (B_{i,d-1}/(t_{i+d}-t_i)
    - B_{i+1,d-1}/(t_{i+d+1}-t_{i+1})) applied ``order`` times.
    """
    if order == 0:
        return bspline_basis(u, knots, degree)
    knots_np = np.asarray(knots)
    num_ctrl = knots_np.shape[0] - degree - 1

    # order-th derivative of degree-p basis = B_{p-order} @ M_{p-order+1} ... @ M_p
    low = degree - order
    if low < 0:
        return jnp.zeros((jnp.asarray(u).shape[0], num_ctrl))
    b = bspline_basis(u, knots, low)
    m = _deriv_matrix_product(knots_np, degree, order)
    return _mm(b, jnp.asarray(m, dtype=b.dtype))


@shape_contract(points="n d", weights="n", out="n")
def chord_length_params(points, weights):
    """Normalized cumulative chord-length parametrization (the ``splprep``
    default, reference: pkg/geometry_utils.py:78) for a *weighted* fixed-shape
    point set. Points must be pre-sorted; zero-weight (padded) points inherit
    the running parameter and contribute nothing downstream.

    Args:
        points: [N, D].
        weights: [N] in {0, 1} (or soft).

    Returns:
        [N] parameters in [0, 1].
    """
    w = weights.astype(points.dtype)
    deltas = jnp.linalg.norm(jnp.diff(points, axis=0), axis=1)
    # A segment counts only when both endpoints are valid.
    seg_w = w[1:] * w[:-1]
    cum = jnp.concatenate([jnp.zeros((1,), points.dtype), jnp.cumsum(deltas * seg_w)])
    total = cum[-1]
    return jnp.where(total > 1e-12, cum / jnp.maximum(total, 1e-12), jnp.zeros_like(cum))


def second_difference_penalty(num_ctrl: int) -> np.ndarray:
    """P-spline penalty ``P = D2.T @ D2`` on control points (static)."""
    d2 = np.diff(np.eye(num_ctrl), n=2, axis=0)
    return d2.T @ d2


@shape_contract(points="n d", weights="n", knots="k")
def fit_bspline(points, weights, knots, degree: int = 3,
                smoothing: float = 1e-3, impl: str = "xla"):
    """Weighted penalized least-squares B-spline fit (all shapes static).

    Solves ``(B^T W B + lam * P + eps I) C = B^T W X`` per coordinate, where
    ``lam = smoothing * sum(w)`` scales the P-spline penalty with the active
    point count so smoothness is resolution-independent.

    Args:
        points: [N, D] pre-sorted points (padding allowed).
        weights: [N] validity weights.
        knots: static knot vector.
        degree: static degree.
        smoothing: penalty strength (plays the role of FITPACK ``s``).
        impl: "xla" (default -- the reference path), or
            "pallas"/"interpret" to run the basis + design contractions as
            ONE fused Pallas kernel (ops/pallas/geometry.bspline_design;
            the basis matrix stays in VMEM). Requires a static (numpy)
            knot vector; the two paths are bitwise-compared in
            tests/test_pallas_geometry.py. The [C, C] solve stays in XLA
            either way (LU has no MXU win at C ~ 16).

    Returns:
        (ctrl [num_ctrl, D], u [N]) control points and per-point parameters.
    """
    u = chord_length_params(points, weights)
    w = weights.astype(points.dtype)
    num_ctrl = np.asarray(knots).shape[0] - degree - 1
    if impl in ("pallas", "interpret") and not isinstance(
        knots, jnp.ndarray
    ):
        from robotic_discovery_platform_tpu.ops.pallas import (
            geometry as pallas_geometry,
        )

        gram, rhs = pallas_geometry.bspline_design(
            points, w, u, pallas_geometry.static_knots(knots), degree,
            interpret=impl == "interpret",
        )
    else:
        b = bspline_basis(u, knots, degree)  # [N, C]
        bw = b * w[:, None]
        gram = _mm(bw.T, b)  # [C, C]
        rhs = _mm(bw.T, points)  # [C, D]
    lam = smoothing * jnp.maximum(jnp.sum(w), 1.0)
    pen = jnp.asarray(second_difference_penalty(num_ctrl), dtype=points.dtype)
    reg = gram + lam * pen + 1e-8 * jnp.eye(num_ctrl, dtype=points.dtype)
    ctrl = jnp.linalg.solve(reg, rhs)
    return ctrl, u


@shape_contract(ctrl="c d", knots="k", u="n", out="n d")
def evaluate_bspline(ctrl, knots, u, degree: int = 3, order: int = 0):
    """Evaluate the spline (or its ``order``-th derivative) at parameters
    ``u``: returns [N, D]."""
    d = bspline_basis_derivative(u, knots, degree, order)
    return _mm(d, ctrl)


def _curvature_formula(r1, r2):
    """kappa = ||r' x r''|| / ||r'||^3 with the reference's degenerate-
    tangent guard (:155). Shared by the XLA path and the fused curvature
    kernel so the two stay op-identical."""
    cross = jnp.cross(r1, r2)
    num = jnp.linalg.norm(cross, axis=-1)
    den = jnp.linalg.norm(r1, axis=-1)
    valid = den > 1e-6
    kappa = jnp.where(valid, num / jnp.maximum(den, 1e-6) ** 3, 0.0)
    return kappa, valid


@shape_contract(ctrl="c d", knots="k", u="n")
def curvature_profile(ctrl, knots, u, degree: int = 3, impl: str = "xla"):
    """kappa(u) = ||r' x r''|| / ||r'||^3 along the fitted curve
    (reference: pkg/geometry_utils.py:144-162), plus the sample points.

    ``impl`` follows :func:`fit_bspline`: "pallas"/"interpret" fuses the
    three derivative design matmuls and the curvature formula into one
    Pallas launch (ops/pallas/geometry.bspline_curvature).

    Returns:
        (kappa [N], valid [N] bool, r [N, D]).
    """
    if impl in ("pallas", "interpret") and not isinstance(
        knots, jnp.ndarray
    ):
        from robotic_discovery_platform_tpu.ops.pallas import (
            geometry as pallas_geometry,
        )

        return pallas_geometry.bspline_curvature(
            ctrl, u, pallas_geometry.static_knots(knots), degree,
            interpret=impl == "interpret",
        )
    r = evaluate_bspline(ctrl, knots, u, degree, order=0)
    r1 = evaluate_bspline(ctrl, knots, u, degree, order=1)
    r2 = evaluate_bspline(ctrl, knots, u, degree, order=2)
    kappa, valid = _curvature_formula(r1, r2)
    return kappa, valid, r
