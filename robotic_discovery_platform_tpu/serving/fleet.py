"""Cross-host serving fleet: membership, placement, and fleet-level SLO
control over per-host replica servers.

The multi-chip router (serving/batching.DeviceRouter) saturates ONE
process's devices; this module is the next ring out -- the Pathways DCN
direction (PAPERS.md): a front-end (serving/frontend.py) fans
``AnalyzeActuatorPerformance`` streams over N per-host replicas, each a
full serving/server.py process with its own chip mesh, reached over
localhost/DCN gRPC. The design deliberately mirrors the chip ring one
level up:

- **Membership is health-gated** on the replicas' existing
  ``grpc.health.v1`` surface: replicas come from a static endpoint list
  (``ServerConfig.fleet_replicas`` / ``RDP_FLEET_REPLICAS``) and are
  polled every ``fleet_poll_s``; a replica whose status flips
  NOT_SERVING (drain, crash, all chips quarantined) drops out of the
  placement ring exactly like a chip drops out of the chip ring, and
  rejoins on recovery through a half-open probe (the per-replica
  :class:`~robotic_discovery_platform_tpu.resilience.CircuitBreaker`
  admits one health probe after ``fleet_breaker_reset_s``; success
  reinstates). A replica reporting ``draining=true`` over the stats RPC
  (a rollout cycle borrowing its chips, serving/rollout.py) leaves
  NEW-stream placement BEFORE health ever flips: a graceful drain, not
  a failover -- its in-flight streams finish normally and the breaker
  never trips.
- **Placement is least-loaded with ring tie-break**, fed by each
  replica's reported inflight/burn: a lightweight stats RPC
  (:func:`add_replica_stats_to_server`, a JSON-over-gRPC unary the
  replica server registers next to health) carries the replica's
  in-flight streams and its ``rdp_slo_error_budget_burn`` reading, so
  the front-end never needs to scrape HTTP /metrics to place a stream.
- **The PR 7 control loop is lifted one level**: a
  :class:`FleetController` consumes the per-replica burn gauges and
  rebalances new-stream placement (a weighted ring -- burning replicas
  are de-weighted toward ``fleet_weight_floor``) BEFORE any replica
  browns out; the replica's own reactive controller still handles its
  intra-host knobs.

Clockwork (Gujarati et al., OSDI 2020) is the other parent: replicas are
exclusively owned by this front-end's placement decisions, and
least-loaded pick with ring tie-break is the work-conserving
simplification of its central scheduler for homogeneous single-model
replicas.

This module is deliberately jax-free: a fleet front-end routes bytes, it
never touches an accelerator.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

import grpc

from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
)
from robotic_discovery_platform_tpu.resilience import CircuitBreaker
from robotic_discovery_platform_tpu.resilience.breaker import CLOSED
from robotic_discovery_platform_tpu.serving import health as health_lib
from robotic_discovery_platform_tpu.serving.proto import (
    health_pb2,
    vision_grpc,
)
from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)


def resolve_fleet_replicas(configured: str) -> list[str]:
    """The replica endpoint list serving should fan out to: the
    ``RDP_FLEET_REPLICAS`` env var when set, else the configured value
    (``ServerConfig.fleet_replicas``), split on commas with blanks
    dropped. Empty list = no fleet (plain single-host serving)."""
    env = os.environ.get("RDP_FLEET_REPLICAS", "").strip()
    spec = env if env else configured
    return [e.strip() for e in spec.split(",") if e.strip()]


# -- replica stats RPC -------------------------------------------------------
#
# A lightweight unary the replica server registers next to grpc.health.v1:
# request is empty bytes, response is a UTF-8 JSON object (inflight
# streams, frames served, error-budget burn, chips/quarantined, version,
# draining). Hand-built on grpcio's generic APIs like vision_grpc.py /
# health.py -- no protoc plugin in the image, and a JSON payload keeps the
# schema evolvable without wire churn.

STATS_SERVICE = "rdp.fleet.ReplicaStats"
_STATS_PATH = f"/{STATS_SERVICE}/Get"


def _identity_bytes(b):
    return bytes(b or b"")


class ReplicaStatsStub:
    """Client stub: ``stub.Get(b"")`` returns the stats JSON bytes."""

    def __init__(self, channel: grpc.Channel):
        self.Get = channel.unary_unary(
            _STATS_PATH,
            request_serializer=_identity_bytes,
            response_deserializer=_identity_bytes,
        )


def add_replica_stats_to_server(
        server, provider: Callable[[], dict]) -> None:
    """Register the stats RPC; ``provider`` returns the stats dict (the
    serving layer passes ``VisionAnalysisService.replica_stats``)."""

    def get(request, context):
        return json.dumps(provider()).encode("utf-8")

    handlers = {
        "Get": grpc.unary_unary_rpc_method_handler(
            get,
            request_deserializer=_identity_bytes,
            response_serializer=_identity_bytes,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(STATS_SERVICE, handlers),)
    )


def fetch_replica_stats(stub: ReplicaStatsStub,
                        timeout_s: float | None = None) -> dict:
    payload = stub.Get(b"", timeout=timeout_s)
    stats = json.loads(payload.decode("utf-8") or "{}")
    if not isinstance(stats, dict):
        raise ValueError(f"replica stats payload is {type(stats).__name__},"
                         " not an object")
    return stats


# -- placement ---------------------------------------------------------------


def _least_loaded(loads, start: int = 0) -> int:
    """Index of the minimum of ``loads``, ties broken in ring order from
    ``start`` -- parallel/mesh.least_loaded re-stated here so the
    front-end never imports jax just to walk a ring."""
    n = len(loads)
    best = start % n
    for off in range(1, n):
        i = (start + off) % n
        if loads[i] < loads[best]:
            best = i
    return best


class Replica:
    """One fleet member: endpoint, lazy gRPC plumbing, and the live state
    placement reads (health verdict, breaker, inflight, burn, weight).

    The channel/stubs are created on first use so placement units can
    drive a router over fake replicas without any sockets."""

    def __init__(self, endpoint: str, *, breaker_failures: int = 2,
                 breaker_reset_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 channel_factory=grpc.insecure_channel):
        self.endpoint = endpoint
        self.breaker = CircuitBreaker(
            failure_threshold=max(1, breaker_failures),
            reset_timeout_s=breaker_reset_s,
            name=f"replica:{endpoint}",
            clock=clock,
        )
        self._channel_factory = channel_factory
        self._channel: grpc.Channel | None = None
        self._stub = None
        self._health_stub = None
        self._stats_stub = None
        #: last health-poll verdict (SERVING and reachable)
        self.serving = False
        #: replica reports draining=true over the stats RPC: healthy but
        #: asking for no NEW streams (rollout drain / pre-stop). Distinct
        #: from a health drop-out on purpose -- in-flight streams finish
        #: normally instead of failing over, and the breaker never trips.
        self.draining = False
        #: front-end-placed streams currently open on this replica
        self.inflight = 0
        #: frames relayed through this replica (front-end count)
        self.frames = 0
        #: streams ever placed here
        self.placements = 0
        #: last scraped rdp_slo_error_budget_burn (0.0 when unknown)
        self.burn = 0.0
        #: FleetController placement weight (1.0 = full share)
        self.weight = 1.0
        #: last full stats payload (diagnostics)
        self.stats: dict = {}
        #: metrics-exposition port the replica advertised over the stats
        #: RPC (0 = none); the federation/trace-stitch scrapes need it
        self.metrics_port = 0

    @property
    def metrics_base_url(self) -> str | None:
        """Base URL of this replica's metrics server (federated scrape +
        /debug/spans stitching target), once the stats RPC has
        advertised a port."""
        if not self.metrics_port or self.metrics_port <= 0:
            return None
        host = self.endpoint.rsplit(":", 1)[0] or "localhost"
        return f"http://{host}:{self.metrics_port}"

    # -- wiring (lazy) ------------------------------------------------------

    @property
    def channel(self) -> grpc.Channel:
        if self._channel is None:
            self._channel = self._channel_factory(self.endpoint)
        return self._channel

    @property
    def stub(self) -> vision_grpc.VisionAnalysisServiceStub:
        if self._stub is None:
            self._stub = vision_grpc.VisionAnalysisServiceStub(self.channel)
        return self._stub

    @property
    def health_stub(self) -> health_lib.HealthStub:
        if self._health_stub is None:
            self._health_stub = health_lib.HealthStub(self.channel)
        return self._health_stub

    @property
    def stats_stub(self) -> ReplicaStatsStub:
        if self._stats_stub is None:
            self._stats_stub = ReplicaStatsStub(self.channel)
        return self._stats_stub

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = self._health_stub = self._stats_stub = None

    # -- placement state ----------------------------------------------------

    @property
    def placeable(self) -> bool:
        """In the ring: last health probe said SERVING, the breaker is
        closed (an open breaker = quarantined until its half-open probe
        succeeds), and the replica is not asking for a graceful drain --
        ``draining`` takes it out of NEW-stream placement BEFORE health
        ever flips, so its in-flight streams run to completion instead
        of failing over."""
        return (self.serving and self.breaker.state == CLOSED
                and not self.draining)

    @property
    def effective_load(self) -> float:
        """What least-loaded pick compares: in-flight streams scaled by
        the controller's weight (a de-weighted replica looks busier than
        its raw count, shifting new streams away)."""
        return self.inflight / max(self.weight, 1e-6)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Replica({self.endpoint!r}, serving={self.serving}, "
                f"inflight={self.inflight}, burn={self.burn:.2f}, "
                f"weight={self.weight:.2f})")


class FleetController:
    """The PR 7 reactive control loop lifted to fleet level: consume each
    replica's error-budget burn and rebalance NEW-stream placement (the
    weighted ring) before any replica browns out.

    Pure function of the scraped burn values -- no thread of its own; the
    router's poll loop calls :meth:`rebalance` after every stats refresh,
    and tests call it directly with injected replicas. A replica's weight
    is 1.0 while its burn stays at or under ``burn_high`` and decays as
    ``burn_high / burn`` above it, floored at ``weight_floor`` so a
    burning replica keeps serving enough traffic to report recovery (the
    same starve-the-signal reasoning as brownout rung 3's duty cycle)."""

    #: weight moves smaller than this are ignored (gauge/log hygiene)
    DEADBAND = 0.05

    def __init__(self, *, burn_high: float = 0.8,
                 weight_floor: float = 0.1):
        if not 0.0 < weight_floor <= 1.0:
            raise ValueError("weight_floor must be in (0, 1]")
        self.burn_high = burn_high
        self.weight_floor = weight_floor
        self.actions_total = 0

    def target_weight(self, burn: float) -> float:
        if burn <= self.burn_high:
            return 1.0
        return max(self.weight_floor, self.burn_high / burn)

    def rebalance(self, replicas: list[Replica]) -> None:
        for r in replicas:
            target = self.target_weight(r.burn)
            if abs(target - r.weight) <= self.DEADBAND and target != 1.0:
                continue
            if target != r.weight:
                action = ("deweight" if target < r.weight else "reweight")
                if abs(target - r.weight) > self.DEADBAND:
                    self.actions_total += 1
                    obs.FLEET_CONTROLLER_ACTIONS.labels(action=action).inc()
                    log.info(
                        "fleet controller: %s %s weight %.2f -> %.2f "
                        "(burn %.2f)", action, r.endpoint, r.weight,
                        target, r.burn,
                    )
                r.weight = target
            obs.FLEET_REPLICA_WEIGHT.labels(replica=r.endpoint).set(
                r.weight)


class FleetRouter:
    """Health-gated membership + least-loaded stream placement over the
    static replica list.

    One poll thread drives the whole control surface: per-replica health
    probe (the breaker's half-open probe when quarantined), stats scrape
    (inflight/burn), controller rebalance, membership metrics, and the
    ``on_membership(live_count)`` callback the front-end uses to flip its
    own readiness. ``poll_once`` is public so tests drive membership
    deterministically without the thread."""

    def __init__(self, endpoints: list[str], *, poll_s: float = 1.0,
                 probe_timeout_s: float = 1.0, breaker_failures: int = 2,
                 breaker_reset_s: float = 5.0,
                 controller: FleetController | None = None,
                 on_membership: Callable[[int], None] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 channel_factory=grpc.insecure_channel):
        if not endpoints:
            raise ValueError("a fleet needs at least one replica endpoint")
        self.replicas = [
            Replica(ep, breaker_failures=breaker_failures,
                    breaker_reset_s=breaker_reset_s, clock=clock,
                    channel_factory=channel_factory)
            for ep in endpoints
        ]
        self.poll_s = poll_s
        self.probe_timeout_s = probe_timeout_s
        self.controller = controller
        self.on_membership = on_membership
        self._lock = checked_lock("fleet.router")
        self._ring_start = 0  # guarded_by: _lock
        self._last_live = -1  # guarded_by: _lock
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        #: stream-level failovers observed (reroutes + error-completions)
        self.failovers_total = 0  # guarded_by: _lock
        self.failover_frames_rerouted = 0  # guarded_by: _lock
        self.failover_frames_error_completed = 0  # guarded_by: _lock

    # -- membership ----------------------------------------------------------

    def poll_once(self) -> int:
        """One membership tick; returns the live (placeable) count."""
        for r in self.replicas:
            healthy = False
            exc: BaseException | None = None
            try:
                resp = r.health_stub.Check(
                    health_pb2.HealthCheckRequest(service=""),
                    timeout=self.probe_timeout_s,
                )
                healthy = resp.status == health_lib.SERVING
                if not healthy:
                    exc = RuntimeError(
                        f"health status {resp.status} (not SERVING)")
            except Exception as e:  # noqa: BLE001 - any probe failure
                exc = e
            was = r.placeable
            if healthy:
                r.serving = True
                # a healthy probe is the half-open "probe stream": only a
                # breaker that ADMITS one may close on it, so a crashy
                # replica must hold healthy through its reset timeout
                # before rejoining the ring
                if r.breaker.state == CLOSED or r.breaker.allow():
                    r.breaker.record_success()
            else:
                r.serving = False
                r.breaker.record_failure(exc)
            if r.placeable != was:
                log.warning(
                    "fleet membership: replica %s %s (%s)",
                    r.endpoint,
                    "joined" if r.placeable else "dropped out",
                    "healthy" if healthy else exc,
                )
                journal_lib.JOURNAL.append(
                    events.FLEET_MEMBERSHIP,
                    replica=r.endpoint,
                    state="joined" if r.placeable else "dropped",
                    reason="healthy" if healthy else str(exc),
                )
            if r.serving:
                self._scrape_stats(r)
            else:
                obs.FLEET_REPLICA_BURN.labels(replica=r.endpoint).set(0.0)
        if self.controller is not None:
            self.controller.rebalance(self.replicas)
        return self._publish_membership()

    def _scrape_stats(self, r: Replica) -> None:
        """Advisory: a failed scrape never drops a healthy replica --
        placement just keeps using the front-end's own inflight count and
        the last known burn."""
        try:
            stats = fetch_replica_stats(r.stats_stub, self.probe_timeout_s)
        except Exception as exc:  # noqa: BLE001
            log.debug("stats scrape of %s failed: %s", r.endpoint, exc)
            return
        r.stats = stats
        try:
            r.burn = float(stats.get("burn", 0.0))
        except (TypeError, ValueError):
            r.burn = 0.0
        try:
            r.metrics_port = int(stats.get("metrics_port", 0) or 0)
        except (TypeError, ValueError):
            r.metrics_port = 0
        was_draining = r.draining
        r.draining = bool(stats.get("draining", False))
        if r.draining != was_draining:
            log.info(
                "fleet membership: replica %s %s (graceful drain, health "
                "still SERVING)", r.endpoint,
                "draining -- out of new-stream placement" if r.draining
                else "un-drained -- placeable again",
            )
            journal_lib.JOURNAL.append(
                events.FLEET_DRAIN, replica=r.endpoint,
                state="draining" if r.draining else "undrained",
            )
        obs.FLEET_REPLICA_BURN.labels(replica=r.endpoint).set(r.burn)

    def _publish_membership(self) -> int:
        live = self.live_count
        obs.FLEET_REPLICAS_LIVE.set(live)
        obs.FLEET_REPLICAS_QUARANTINED.set(self.quarantined_count)
        obs.FLEET_REPLICAS_DRAINING.set(self.draining_count)
        # the change test runs under the lock: _publish_membership is
        # reached from the poll thread AND from stream handlers
        # (on_stream_error), and an unguarded read-modify-write here can
        # double-fire or swallow a membership transition. The callback
        # runs OUTSIDE the lock -- it flips gRPC health (its own
        # condition), and holding the router lock across it would nest
        # foreign locks for no reason.
        with self._lock:
            changed = live != self._last_live
            if changed:
                self._last_live = live
        if changed and self.on_membership is not None:
            try:
                self.on_membership(live)
            except Exception:  # pragma: no cover - observer bug
                log.exception("fleet membership callback failed")
        return live

    @property
    def live_count(self) -> int:
        return sum(1 for r in self.replicas if r.placeable)

    @property
    def quarantined_count(self) -> int:
        """Replicas held out of the ring by an OPEN breaker (half-open
        counts as quarantined too: it is not placeable until its probe
        succeeds)."""
        return sum(
            1 for r in self.replicas
            if r.serving and r.breaker.state != CLOSED
        )

    @property
    def draining_count(self) -> int:
        """Healthy replicas held out of new-stream placement by their
        own draining flag (NOT quarantined: the breaker is closed and
        in-flight streams keep running)."""
        return sum(
            1 for r in self.replicas
            if r.serving and r.draining and r.breaker.state == CLOSED
        )

    def wait_live(self, min_live: int = 1,
                  timeout_s: float = 30.0) -> bool:
        """Block until at least ``min_live`` replicas are placeable (the
        poll thread must be running) or the timeout expires."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.live_count >= min_live:
                return True
            time.sleep(min(0.05, self.poll_s))
        return self.live_count >= min_live

    # -- placement -----------------------------------------------------------

    def pick(self, exclude: Replica | None = None) -> Replica | None:
        """Place one new stream: the least effectively-loaded placeable
        replica, ties walking the ring (idle fleets round-robin, skewed
        fleets drain toward the emptiest host). Increments the chosen
        replica's inflight; callers MUST :meth:`release` it."""
        with self._lock:
            loads = [
                r.effective_load
                if (r.placeable and r is not exclude) else float("inf")
                for r in self.replicas
            ]
            if not any(load != float("inf") for load in loads):
                return None
            idx = _least_loaded(loads, self._ring_start)
            self._ring_start = (idx + 1) % len(self.replicas)
            r = self.replicas[idx]
            r.inflight += 1
            r.placements += 1
        obs.FLEET_PLACEMENTS.labels(replica=r.endpoint).inc()
        obs.FLEET_REPLICA_STREAMS.labels(replica=r.endpoint).set(r.inflight)
        return r

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)
        obs.FLEET_REPLICA_STREAMS.labels(replica=replica.endpoint).set(
            replica.inflight)

    def count_frame(self, replica: Replica) -> None:
        """One frame relayed through ``replica``. Counted under the
        router lock: concurrent streams share a replica, and the bare
        ``replica.frames += 1`` this replaces dropped increments under
        load (the racecheck RC002 class of bug, cross-object)."""
        with self._lock:
            replica.frames += 1
        obs.FLEET_REPLICA_FRAMES.labels(replica=replica.endpoint).inc()

    def on_stream_ok(self, replica: Replica) -> None:
        """A relayed stream completed cleanly: clears the breaker's
        consecutive-failure count (stream success is as good as a health
        probe)."""
        if replica.breaker.state == CLOSED:
            replica.breaker.record_success()

    def on_stream_error(self, replica: Replica,
                        exc: BaseException | None = None) -> None:
        """A relayed stream died at the transport level: count it toward
        the replica's breaker (an open breaker quarantines the replica
        out of the ring without waiting for the next health poll)."""
        replica.breaker.record_failure(exc)
        self._publish_membership()

    def record_failover(self, *, rerouted: int = 0,
                        error_completed: int = 0) -> None:
        with self._lock:
            self.failovers_total += 1
            self.failover_frames_rerouted += rerouted
            self.failover_frames_error_completed += error_completed
        obs.FLEET_FAILOVERS.inc()
        if rerouted:
            obs.FLEET_FAILOVER_FRAMES.labels(outcome="rerouted").inc(
                rerouted)
        if error_completed:
            obs.FLEET_FAILOVER_FRAMES.labels(
                outcome="error_completed").inc(error_completed)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception:  # pragma: no cover - keep polling
                    log.exception("fleet membership poll failed")

        # one immediate tick so the front-end does not report an empty
        # fleet for a full poll period after boot
        try:
            self.poll_once()
        except Exception:  # pragma: no cover
            log.exception("initial fleet membership poll failed")
        self._thread = threading.Thread(
            target=loop, name="fleet-membership", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for r in self.replicas:
            r.close()
