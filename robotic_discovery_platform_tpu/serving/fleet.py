"""Cross-host serving fleet: membership, placement, and fleet-level SLO
control over per-host replica servers.

The multi-chip router (serving/batching.DeviceRouter) saturates ONE
process's devices; this module is the next ring out -- the Pathways DCN
direction (PAPERS.md): a front-end (serving/frontend.py) fans
``AnalyzeActuatorPerformance`` streams over N per-host replicas, each a
full serving/server.py process with its own chip mesh, reached over
localhost/DCN gRPC. The design deliberately mirrors the chip ring one
level up:

- **Membership is health-gated** on the replicas' existing
  ``grpc.health.v1`` surface: replicas come from a static endpoint list
  (``ServerConfig.fleet_replicas`` / ``RDP_FLEET_REPLICAS``) and are
  polled every ``fleet_poll_s``; a replica whose status flips
  NOT_SERVING (drain, crash, all chips quarantined) drops out of the
  placement ring exactly like a chip drops out of the chip ring, and
  rejoins on recovery through a half-open probe (the per-replica
  :class:`~robotic_discovery_platform_tpu.resilience.CircuitBreaker`
  admits one health probe after ``fleet_breaker_reset_s``; success
  reinstates). A replica reporting ``draining=true`` over the stats RPC
  (a rollout cycle borrowing its chips, serving/rollout.py) leaves
  NEW-stream placement BEFORE health ever flips: a graceful drain, not
  a failover -- its in-flight streams finish normally and the breaker
  never trips.
- **Membership is also elastic**: the same ``rdp.fleet.ReplicaStats``
  RPC surface carries ``Register``/``Renew``/``Leave`` unaries backed
  by a :class:`LeaseRegistry` on the front-end. A replica announces its
  endpoint + metrics port + version on boot (:class:`LeaseClient`,
  wired by server.py from ``RDP_FLEET_REGISTRARS``) and renews on a
  TTL; the router composes these leased members with the static seeds.
  A missed lease expires the member through the EXACT health drop-out
  path above (forced probe failure -> breaker -> quarantined, not
  removed), so a replica respawned on a new port rejoins with zero
  config change by simply registering again; ``Leave`` is the graceful
  path -- the member is treated as draining (PR 13 semantics) while its
  in-flight streams finish.
- **Placement is least-loaded with ring tie-break**, fed by each
  replica's reported inflight/burn: a lightweight stats RPC
  (:func:`add_replica_stats_to_server`, a JSON-over-gRPC unary the
  replica server registers next to health) carries the replica's
  in-flight streams and its ``rdp_slo_error_budget_burn`` reading, so
  the front-end never needs to scrape HTTP /metrics to place a stream.
- **The PR 7 control loop is lifted one level**: a
  :class:`FleetController` consumes the per-replica burn gauges and
  rebalances new-stream placement (a weighted ring -- burning replicas
  are de-weighted toward ``fleet_weight_floor``) BEFORE any replica
  browns out; the replica's own reactive controller still handles its
  intra-host knobs.

Clockwork (Gujarati et al., OSDI 2020) is the other parent: replicas are
exclusively owned by this front-end's placement decisions, and
least-loaded pick with ring tie-break is the work-conserving
simplification of its central scheduler for homogeneous single-model
replicas.

This module is deliberately jax-free: a fleet front-end routes bytes, it
never touches an accelerator.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

import grpc

from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
)
from robotic_discovery_platform_tpu.resilience import CircuitBreaker
from robotic_discovery_platform_tpu.resilience.breaker import CLOSED
from robotic_discovery_platform_tpu.serving import health as health_lib
from robotic_discovery_platform_tpu.serving.proto import (
    health_pb2,
    vision_grpc,
)
from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)


def resolve_fleet_replicas(configured: str) -> list[str]:
    """The replica endpoint list serving should fan out to: the
    ``RDP_FLEET_REPLICAS`` env var when set, else the configured value
    (``ServerConfig.fleet_replicas``), split on commas with blanks
    dropped. Empty list = no fleet (plain single-host serving)."""
    env = os.environ.get("RDP_FLEET_REPLICAS", "").strip()
    spec = env if env else configured
    return [e.strip() for e in spec.split(",") if e.strip()]


def resolve_fleet_registrars(configured: str) -> list[str]:
    """The front-end endpoints a replica should register its membership
    lease with: ``RDP_FLEET_REGISTRARS`` when set, else the configured
    value (``ServerConfig.fleet_registrars``), comma-split with blanks
    dropped. Empty list = static membership only (no lease client)."""
    env = os.environ.get("RDP_FLEET_REGISTRARS", "").strip()
    spec = env if env else configured
    return [e.strip() for e in spec.split(",") if e.strip()]


def resolve_fleet_elastic(configured: bool) -> bool:
    """Front-end elastic-membership switch: ``RDP_FLEET_ELASTIC`` when
    set ("1"/"true"/"on" enable), else the configured value
    (``ServerConfig.fleet_elastic``). Off = static membership only."""
    env = os.environ.get("RDP_FLEET_ELASTIC", "").strip().lower()
    if env:
        return env in ("1", "true", "yes", "on")
    return bool(configured)


def resolve_fleet_peers(configured: str) -> list[str]:
    """Sibling front-end endpoints this front-end gossips lease +
    placement state with over the stats RPC: ``RDP_FLEET_PEERS`` when
    set, else the configured value (``ServerConfig.fleet_peers``),
    comma-split with blanks dropped."""
    env = os.environ.get("RDP_FLEET_PEERS", "").strip()
    spec = env if env else configured
    return [e.strip() for e in spec.split(",") if e.strip()]


def resolve_fleet_advertise(configured: str, default: str = "") -> str:
    """The endpoint a replica advertises in its lease registration:
    ``RDP_FLEET_ADVERTISE`` when set, else the configured value
    (``ServerConfig.fleet_advertise``), else ``default`` (server.py
    passes ``localhost:<bound port>``)."""
    env = os.environ.get("RDP_FLEET_ADVERTISE", "").strip()
    return env or configured.strip() or default


# -- replica stats RPC -------------------------------------------------------
#
# A lightweight unary the replica server registers next to grpc.health.v1:
# request is empty bytes, response is a UTF-8 JSON object (inflight
# streams, frames served, error-budget burn, chips/quarantined, version,
# draining). Hand-built on grpcio's generic APIs like vision_grpc.py /
# health.py -- no protoc plugin in the image, and a JSON payload keeps the
# schema evolvable without wire churn.

STATS_SERVICE = "rdp.fleet.ReplicaStats"
_STATS_PATH = f"/{STATS_SERVICE}/Get"
_DRAIN_PATH = f"/{STATS_SERVICE}/Drain"
_REGISTER_PATH = f"/{STATS_SERVICE}/Register"
_RENEW_PATH = f"/{STATS_SERVICE}/Renew"
_LEAVE_PATH = f"/{STATS_SERVICE}/Leave"


def _identity_bytes(b):
    return bytes(b or b"")


def _decode_json(payload: bytes) -> dict:
    req = json.loads(payload.decode("utf-8") or "{}")
    return req if isinstance(req, dict) else {}


class ReplicaStatsStub:
    """Client stub: ``stub.Get(b"")`` returns the stats JSON bytes;
    ``stub.Drain(b'{"draining": true}')`` asks a replica for a graceful
    drain (the autoscaler's scale-down lever -- remote ``set_draining``,
    PR 13 semantics: held out of NEW-stream placement, in-flight streams
    finish, health stays SERVING)."""

    def __init__(self, channel: grpc.Channel):
        self.Get = channel.unary_unary(
            _STATS_PATH,
            request_serializer=_identity_bytes,
            response_deserializer=_identity_bytes,
        )
        self.Drain = channel.unary_unary(
            _DRAIN_PATH,
            request_serializer=_identity_bytes,
            response_deserializer=_identity_bytes,
        )


class FleetLeaseStub:
    """Client stub for the membership-lease unaries a front-end serves.
    Requests/responses are UTF-8 JSON objects like the stats RPC."""

    def __init__(self, channel: grpc.Channel):
        kw = dict(request_serializer=_identity_bytes,
                  response_deserializer=_identity_bytes)
        self.Register = channel.unary_unary(_REGISTER_PATH, **kw)
        self.Renew = channel.unary_unary(_RENEW_PATH, **kw)
        self.Leave = channel.unary_unary(_LEAVE_PATH, **kw)


def add_fleet_rpcs_to_server(
        server, *, stats_provider: Callable[[], dict] | None = None,
        registry: "LeaseRegistry | None" = None,
        drain: Callable[[bool], None] | None = None) -> None:
    """Register whichever ``rdp.fleet.ReplicaStats`` methods this
    process serves, as ONE generic handler: ``Get`` (stats -- replicas
    and front-ends), ``Drain`` (remote graceful drain -- replicas), and
    ``Register``/``Renew``/``Leave`` (membership leases -- front-ends
    holding a :class:`LeaseRegistry`)."""

    handlers: dict = {}
    hkw = dict(request_deserializer=_identity_bytes,
               response_serializer=_identity_bytes)

    if stats_provider is not None:
        def get(request, context):
            return json.dumps(stats_provider()).encode("utf-8")

        handlers["Get"] = grpc.unary_unary_rpc_method_handler(get, **hkw)

    if drain is not None:
        def do_drain(request, context):
            req = _decode_json(request)
            drain(bool(req.get("draining", True)))
            return json.dumps({"ok": True}).encode("utf-8")

        handlers["Drain"] = grpc.unary_unary_rpc_method_handler(
            do_drain, **hkw)

    if registry is not None:
        def do_register(request, context):
            req = _decode_json(request)
            endpoint = str(req.get("endpoint", "")).strip()
            if not endpoint:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "lease registration needs an endpoint")
            resp = registry.register(
                endpoint,
                metrics_port=req.get("metrics_port", 0),
                version=req.get("version", ""),
            )
            return json.dumps(resp).encode("utf-8")

        def do_renew(request, context):
            req = _decode_json(request)
            resp = registry.renew(str(req.get("endpoint", "")).strip())
            if resp is None:
                # refused: unknown endpoint, lease already expired/left,
                # or the renew lost the race with expiry. The client's
                # recovery is always the same -- re-register.
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              "no active lease; re-register")
            return json.dumps(resp).encode("utf-8")

        def do_leave(request, context):
            req = _decode_json(request)
            resp = registry.leave(str(req.get("endpoint", "")).strip())
            return json.dumps(resp).encode("utf-8")

        handlers["Register"] = grpc.unary_unary_rpc_method_handler(
            do_register, **hkw)
        handlers["Renew"] = grpc.unary_unary_rpc_method_handler(
            do_renew, **hkw)
        handlers["Leave"] = grpc.unary_unary_rpc_method_handler(
            do_leave, **hkw)

    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(STATS_SERVICE, handlers),)
    )


def add_replica_stats_to_server(
        server, provider: Callable[[], dict],
        drain: Callable[[bool], None] | None = None) -> None:
    """Register the stats RPC (and optionally the remote-drain unary);
    ``provider`` returns the stats dict (the serving layer passes
    ``VisionAnalysisService.replica_stats``)."""
    add_fleet_rpcs_to_server(server, stats_provider=provider, drain=drain)


def fetch_replica_stats(stub: ReplicaStatsStub,
                        timeout_s: float | None = None) -> dict:
    payload = stub.Get(b"", timeout=timeout_s)
    stats = json.loads(payload.decode("utf-8") or "{}")
    if not isinstance(stats, dict):
        raise ValueError(f"replica stats payload is {type(stats).__name__},"
                         " not an object")
    return stats


# -- membership leases -------------------------------------------------------
#
# The elastic half of membership: replicas announce themselves and renew
# on a TTL; the front-end's registry runs each endpoint's lease through a
# tiny three-state machine. Expiry is the SIGKILL/partition path (the
# router forces the member through the health drop-out -> breaker
# quarantine it already survives); Leave is the graceful path (treated as
# the PR 13 draining flag). Every transition bumps its counter, journals
# a fleet.lease event, and feeds the injectable observer the explorer
# uses to witness edge coverage -- the breaker's set_observer idiom.

LEASE_ACTIVE = "active"
LEASE_EXPIRED = "expired"
LEASE_LEFT = "left"
#: the lease machine's whole vocabulary, in lifecycle order
LEASE_STATES = (LEASE_ACTIVE, LEASE_EXPIRED, LEASE_LEFT)

#: observer hook for lease transitions (endpoint, frm, to) -- injectable
#: so analysis/explore.py witnesses edges without patching internals
_lease_observer: Callable[[str, str, str], None] | None = None


def set_lease_observer(
        fn: Callable[[str, str, str], None] | None) -> None:
    global _lease_observer
    _lease_observer = fn


class Lease:
    """One endpoint's membership lease. State mutations go through
    :meth:`_transition` (counter + journal + observer); the registry is
    the only caller and holds its lock across them so readers never see
    a half-applied renewal."""

    def __init__(self, endpoint: str, *, ttl_s: float, now: float,
                 metrics_port: int = 0, version: str = ""):
        self.endpoint = endpoint
        self.ttl_s = float(ttl_s)
        self.metrics_port = int(metrics_port or 0)
        self.version = str(version or "")
        self.registered_at = now
        self.expires_at = now + self.ttl_s
        self.renewals = 0
        self.state_changed_at = now
        self._state = LEASE_ACTIVE

    @property
    def state(self) -> str:
        return self._state

    def _transition(self, to: str, now: float, reason: str = "") -> None:
        frm = self._state
        self._state = to
        self.state_changed_at = now
        obs.FLEET_LEASE_TRANSITIONS.labels(state=to).inc()
        journal_lib.JOURNAL.append(
            events.FLEET_LEASE, endpoint=self.endpoint, frm=frm, to=to,
            reason=reason,
        )
        if _lease_observer is not None:
            _lease_observer(self.endpoint, frm, to)

    def refresh(self, now: float, *, ttl_s: float, metrics_port: int = 0,
                version: str = "") -> None:
        """A (re-)registration landed: refresh the advertisement and
        deadline, and re-arm a non-active lease back to active -- the
        respawned-on-a-new-port rejoin edge. A double-register of a
        live endpoint takes no transition (just a longer deadline)."""
        late = now >= self.expires_at
        self.ttl_s = float(ttl_s)
        self.metrics_port = int(metrics_port or 0)
        self.version = str(version or "")
        self.registered_at = now
        self.expires_at = now + self.ttl_s
        if self._state != LEASE_ACTIVE:
            self._transition(
                LEASE_ACTIVE, now,
                reason="re-register (late)" if late else "re-register",
            )

    def expire(self, now: float) -> bool:
        """Take the clocked expiry edge if the deadline has passed."""
        if self._state == LEASE_ACTIVE and now >= self.expires_at:
            self._transition(LEASE_EXPIRED, now,
                             reason=f"missed ttl {self.ttl_s:g}s")
            return True
        return False

    def depart(self, now: float) -> bool:
        """Graceful Leave: only an active lease can leave (an expired
        member sending Leave is already gone; it must re-register)."""
        if self._state == LEASE_ACTIVE:
            self._transition(LEASE_LEFT, now, reason="leave")
            return True
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Lease({self.endpoint!r}, state={self._state}, "
                f"renewals={self.renewals})")


class LeaseRegistry:
    """The front-end's lease table: endpoint -> :class:`Lease`, TTL'd.

    ``register``/``renew``/``leave`` back the Register/Renew/Leave
    unaries; the router's poll loop calls :meth:`sweep` each tick so a
    member that stops renewing expires within one poll of its deadline.
    A renew that arrives at-or-after the deadline is REFUSED rather than
    racing the sweep -- the sweep owns the expiry transition, and the
    refused client re-registers (one spurious re-register beats a lease
    that flaps between alive and expired depending on thread timing)."""

    def __init__(self, *, ttl_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.ttl_s = max(0.1, float(ttl_s))
        self._clock = clock
        self._lock = checked_lock("fleet.leases")
        self._leases: dict[str, Lease] = {}  # guarded_by: _lock

    # -- the lease RPCs ------------------------------------------------------

    def register(self, endpoint: str, *, metrics_port: int = 0,
                 version: str = "") -> dict:
        """Accept a (re-)registration. A double-register of a live
        endpoint just refreshes its deadline and advertisement; an
        expired or left endpoint transitions back to active -- the
        respawned-on-a-new-port rejoin needs nothing else."""
        endpoint = str(endpoint).strip()
        if not endpoint:
            raise ValueError("lease registration needs an endpoint")
        now = self._clock()
        with self._lock:
            lease = self._leases.get(endpoint)
            if lease is None:
                lease = Lease(endpoint, ttl_s=self.ttl_s, now=now,
                              metrics_port=metrics_port, version=version)
                self._leases[endpoint] = lease
                journal_lib.JOURNAL.append(
                    events.FLEET_LEASE, endpoint=endpoint, frm="",
                    to=LEASE_ACTIVE, reason="register",
                )
            else:
                lease.refresh(now, ttl_s=self.ttl_s,
                              metrics_port=metrics_port, version=version)
        obs.FLEET_LEASE_REGISTRATIONS.inc()
        self._publish()
        return {"ok": True, "ttl_s": self.ttl_s}

    def renew(self, endpoint: str) -> dict | None:
        """Extend an active lease; ``None`` refuses (unknown, not
        active, or the renew lost the race with the expiry deadline on
        the shared clock -- the client must re-register)."""
        now = self._clock()
        with self._lock:
            lease = self._leases.get(str(endpoint).strip())
            if lease is None or lease.state != LEASE_ACTIVE:
                return None
            if now >= lease.expires_at:
                journal_lib.JOURNAL.append(
                    events.FLEET_LEASE, endpoint=lease.endpoint,
                    frm=lease.state, to=lease.state,
                    reason="renew_refused (deadline passed)",
                )
                return None
            lease.expires_at = now + self.ttl_s
            lease.renewals += 1
        obs.FLEET_LEASE_RENEWALS.inc()
        return {"ok": True, "ttl_s": self.ttl_s}

    def leave(self, endpoint: str) -> dict:
        """Graceful departure: the member keeps serving its in-flight
        streams but leaves NEW-stream placement (the router treats a
        left lease as the PR 13 draining flag)."""
        now = self._clock()
        with self._lock:
            lease = self._leases.get(str(endpoint).strip())
            if lease is not None:
                lease.depart(now)
        self._publish()
        return {"ok": True}

    def sweep(self) -> list[str]:
        """Expire every active lease whose deadline passed; returns the
        endpoints expired this call. The router runs this each poll
        tick, so expiry lands within ``poll_s`` of the deadline."""
        now = self._clock()
        expired: list[str] = []
        with self._lock:
            for lease in self._leases.values():
                if lease.expire(now):
                    expired.append(lease.endpoint)
        for _ in expired:
            obs.FLEET_LEASE_EXPIRIES.inc()
        if expired:
            self._publish()
        return expired

    # -- readers / maintenance ----------------------------------------------

    def state_of(self, endpoint: str) -> str | None:
        with self._lock:
            lease = self._leases.get(endpoint)
            return lease.state if lease is not None else None

    def get(self, endpoint: str) -> Lease | None:
        with self._lock:
            return self._leases.get(endpoint)

    def endpoints(self, state: str | None = None) -> list[str]:
        with self._lock:
            return [ep for ep, lease in self._leases.items()
                    if state is None or lease.state == state]

    def snapshot(self) -> dict:
        """The gossip payload front-ends exchange over their stats RPC:
        per-endpoint lease state with REMAINING ttl (never absolute
        monotonic deadlines -- each process has its own clock zero)."""
        now = self._clock()
        with self._lock:
            return {
                ep: {
                    "state": lease.state,
                    "expires_in_s": max(0.0, lease.expires_at - now),
                    "metrics_port": lease.metrics_port,
                    "version": lease.version,
                    "renewals": lease.renewals,
                }
                for ep, lease in self._leases.items()
            }

    def adopt(self, endpoint: str, *, expires_in_s: float,
              metrics_port: int = 0, version: str = "") -> bool:
        """Merge one gossiped ACTIVE lease from a sibling front-end:
        unknown endpoints are created, known active ones keep the later
        of the two deadlines. Never resurrects a locally expired/left
        lease -- the member's own re-register is the only way back."""
        endpoint = str(endpoint).strip()
        remaining = min(max(0.0, float(expires_in_s)), self.ttl_s)
        if not endpoint or remaining <= 0.0:
            return False
        now = self._clock()
        adopted = False
        with self._lock:
            lease = self._leases.get(endpoint)
            if lease is None:
                lease = Lease(endpoint, ttl_s=self.ttl_s, now=now,
                              metrics_port=metrics_port, version=version)
                lease.expires_at = now + remaining
                self._leases[endpoint] = lease
                journal_lib.JOURNAL.append(
                    events.FLEET_LEASE, endpoint=endpoint, frm="",
                    to=LEASE_ACTIVE, reason="gossip_adopt",
                )
                adopted = True
            elif lease.state == LEASE_ACTIVE:
                lease.expires_at = max(lease.expires_at, now + remaining)
        if adopted:
            self._publish()
        return adopted

    def force_expire(self, endpoint: str) -> None:
        """Rewind one lease's deadline to NOW (tests + the explorer:
        the next sweep takes the honest clocked expiry edge)."""
        now = self._clock()
        with self._lock:
            lease = self._leases.get(endpoint)
            if lease is not None:
                lease.expires_at = now

    def prunable(self, max_age_s: float) -> list[str]:
        """Endpoints whose lease has sat expired/left longer than
        ``max_age_s`` -- the router forgets these entirely (channel
        closed, probe stopped) once their in-flight count hits zero."""
        now = self._clock()
        with self._lock:
            return [
                ep for ep, lease in self._leases.items()
                if lease.state != LEASE_ACTIVE
                and now - lease.state_changed_at > max_age_s
            ]

    def drop(self, endpoint: str) -> None:
        with self._lock:
            self._leases.pop(endpoint, None)
        self._publish()

    def _publish(self) -> None:
        with self._lock:
            counts = dict.fromkeys(LEASE_STATES, 0)
            for lease in self._leases.values():
                counts[lease.state] = counts.get(lease.state, 0) + 1
        for state, n in counts.items():
            obs.FLEET_LEASE_MEMBERS.labels(state=state).set(n)


class LeaseClient:
    """Replica-side lease loop: register with every configured registrar
    (front-end) on boot, renew at a third of the TTL, and fall back to
    re-registering whenever a renew is refused (the registrar restarted,
    or we lost the race with our own deadline). ``leave`` rides the
    graceful-drain path (server.py fires it from ``drain()``).

    All RPCs are best-effort per registrar: one unreachable front-end
    never blocks the lease with its siblings."""

    def __init__(self, registrars: list[str], *, endpoint: str,
                 metrics_port: int = 0, version: str = "",
                 ttl_s: float = 10.0,
                 channel_factory=grpc.insecure_channel,
                 rpc_timeout_s: float = 2.0):
        self.registrars = [r.strip() for r in registrars if r.strip()]
        self.endpoint = endpoint
        self.metrics_port = int(metrics_port or 0)
        self.version = str(version or "")
        self.ttl_s = max(0.1, float(ttl_s))
        self.rpc_timeout_s = rpc_timeout_s
        self._channel_factory = channel_factory
        self._channels: dict[str, grpc.Channel] = {}
        self._stubs: dict[str, FleetLeaseStub] = {}
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self.registrations = 0
        self.renewals = 0

    def _stub(self, registrar: str) -> FleetLeaseStub:
        if registrar not in self._stubs:
            channel = self._channel_factory(registrar)
            self._channels[registrar] = channel
            self._stubs[registrar] = FleetLeaseStub(channel)
        return self._stubs[registrar]

    def _payload(self) -> bytes:
        return json.dumps({
            "endpoint": self.endpoint,
            "metrics_port": self.metrics_port,
            "version": self.version,
        }).encode("utf-8")

    def register(self) -> int:
        """Register with every registrar; returns how many accepted."""
        ok = 0
        for registrar in self.registrars:
            try:
                self._stub(registrar).Register(
                    self._payload(), timeout=self.rpc_timeout_s)
                ok += 1
            except Exception as exc:  # noqa: BLE001 - per-registrar
                log.debug("lease register with %s failed: %s",
                          registrar, exc)
        if ok:
            self.registrations += 1
        return ok

    def renew_once(self) -> int:
        """One renew round; a refused/failed renew immediately falls
        back to Register on that registrar. Returns renews accepted."""
        ok = 0
        for registrar in self.registrars:
            try:
                self._stub(registrar).Renew(
                    self._payload(), timeout=self.rpc_timeout_s)
                ok += 1
            except Exception as exc:  # noqa: BLE001 - re-register path
                log.debug("lease renew with %s refused/failed (%s); "
                          "re-registering", registrar, exc)
                try:
                    self._stub(registrar).Register(
                        self._payload(), timeout=self.rpc_timeout_s)
                    self.registrations += 1
                except Exception as exc2:  # noqa: BLE001
                    log.debug("lease re-register with %s failed: %s",
                              registrar, exc2)
        if ok:
            self.renewals += 1
        return ok

    def leave(self) -> None:
        for registrar in self.registrars:
            try:
                self._stub(registrar).Leave(
                    self._payload(), timeout=self.rpc_timeout_s)
            except Exception as exc:  # noqa: BLE001 - best-effort
                log.debug("lease leave with %s failed: %s",
                          registrar, exc)

    def start(self) -> None:
        if self._thread is not None or not self.registrars:
            return
        self.register()
        self._stop = threading.Event()
        interval = max(0.05, self.ttl_s / 3.0)

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.renew_once()
                except Exception:  # pragma: no cover - keep renewing
                    log.exception("lease renew round failed")

        self._thread = threading.Thread(
            target=loop, name="fleet-lease", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for channel in self._channels.values():
            channel.close()
        self._channels.clear()
        self._stubs.clear()


class PeerGossip:
    """Coordinator-free shared state between replicated front-ends.

    Each front-end already SERVES a stats RPC of its own (role
    "frontend": its lease table plus per-replica placement loads). This
    is the consuming half: poll every sibling's stats RPC and

    - **adopt** ACTIVE lease advertisements we have not heard directly
      (a replica that registered with sibling A becomes placeable on
      sibling B within one gossip round -- no shared store, no
      coordinator, and :meth:`LeaseRegistry.adopt` never resurrects a
      lease this front-end saw expire or leave);
    - **fold** the siblings' per-replica in-flight counts into this
      router's placement view (:meth:`FleetRouter.set_external_load`),
      so N front-ends placing independently stop dogpiling the replica
      each one sees as idle.

    Best-effort per peer: an unreachable sibling contributes nothing
    this round and its previously gossiped load ages out on the next
    successful round (set_external_load replaces, never accumulates)."""

    def __init__(self, peers: list[str], *, registry: LeaseRegistry,
                 router: "FleetRouter", poll_s: float = 1.0,
                 rpc_timeout_s: float = 2.0,
                 channel_factory=grpc.insecure_channel):
        self.peers = [p.strip() for p in peers if p.strip()]
        self.registry = registry
        self.router = router
        self.poll_s = max(0.05, float(poll_s))
        self.rpc_timeout_s = rpc_timeout_s
        self._channel_factory = channel_factory
        self._channels: dict[str, grpc.Channel] = {}
        self._stubs: dict[str, ReplicaStatsStub] = {}
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self.rounds = 0
        self.adopted_total = 0

    def _stub(self, peer: str) -> ReplicaStatsStub:
        if peer not in self._stubs:
            channel = self._channel_factory(peer)
            self._channels[peer] = channel
            self._stubs[peer] = ReplicaStatsStub(channel)
        return self._stubs[peer]

    def poll_once(self) -> int:
        """One gossip round; returns how many peers answered."""
        reached = 0
        loads: dict[str, int] = {}
        for peer in self.peers:
            try:
                payload = _decode_json(
                    self._stub(peer).Get(b"", timeout=self.rpc_timeout_s))
            except Exception as exc:  # noqa: BLE001 - per-peer
                log.debug("gossip with %s failed: %s", peer, exc)
                continue
            reached += 1
            for ep, lease in (payload.get("leases") or {}).items():
                if lease.get("state") != LEASE_ACTIVE:
                    continue
                if self.registry.adopt(
                        ep,
                        expires_in_s=float(lease.get("expires_in_s", 0.0)),
                        metrics_port=int(lease.get("metrics_port", 0)),
                        version=str(lease.get("version", ""))):
                    self.adopted_total += 1
            for ep, n in (payload.get("replica_loads") or {}).items():
                try:
                    loads[ep] = loads.get(ep, 0) + int(n)
                except (TypeError, ValueError):
                    continue
        self.rounds += 1
        self.router.set_external_load(loads)
        return reached

    def start(self) -> None:
        if self._thread is not None or not self.peers:
            return
        self._stop = threading.Event()
        # Boot-time seed (registrar quorum hygiene): a front-end that
        # (re)starts with an empty lease table would otherwise place
        # blind for up to poll_s while members it never heard of renew
        # elsewhere -- the ~1 TTL blind spot after a registrar restart.
        # One synchronous round now adopts every sibling-advertised
        # ACTIVE lease before the first stream is placed; adopt still
        # never resurrects a lease THIS front-end saw expire or leave.
        try:
            self.poll_once()
        except Exception:  # noqa: BLE001 - seed is best-effort
            log.exception("boot-time gossip seed failed")

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception:  # pragma: no cover - keep gossiping
                    log.exception("gossip round failed")

        self._thread = threading.Thread(
            target=loop, name="fleet-gossip", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for channel in self._channels.values():
            channel.close()
        self._channels.clear()
        self._stubs.clear()


# -- placement ---------------------------------------------------------------


def _least_loaded(loads, start: int = 0) -> int:
    """Index of the minimum of ``loads``, ties broken in ring order from
    ``start`` -- parallel/mesh.least_loaded re-stated here so the
    front-end never imports jax just to walk a ring."""
    n = len(loads)
    best = start % n
    for off in range(1, n):
        i = (start + off) % n
        if loads[i] < loads[best]:
            best = i
    return best


class Replica:
    """One fleet member: endpoint, lazy gRPC plumbing, and the live state
    placement reads (health verdict, breaker, inflight, burn, weight).

    The channel/stubs are created on first use so placement units can
    drive a router over fake replicas without any sockets."""

    def __init__(self, endpoint: str, *, breaker_failures: int = 2,
                 breaker_reset_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 channel_factory=grpc.insecure_channel):
        self.endpoint = endpoint
        self.breaker = CircuitBreaker(
            failure_threshold=max(1, breaker_failures),
            reset_timeout_s=breaker_reset_s,
            name=f"replica:{endpoint}",
            clock=clock,
        )
        self._channel_factory = channel_factory
        self._channel: grpc.Channel | None = None
        self._stub = None
        self._health_stub = None
        self._stats_stub = None
        #: last health-poll verdict (SERVING and reachable)
        self.serving = False
        #: replica reports draining=true over the stats RPC: healthy but
        #: asking for no NEW streams (rollout drain / pre-stop). Distinct
        #: from a health drop-out on purpose -- in-flight streams finish
        #: normally instead of failing over, and the breaker never trips.
        self.draining = False
        #: front-end-placed streams currently open on this replica
        self.inflight = 0
        #: streams SIBLING front-ends report placed here (gossip-fed;
        #: folds into effective_load so N replicated front-ends don't
        #: all dogpile the replica each sees as idle)
        self.external = 0
        #: frames relayed through this replica (front-end count)
        self.frames = 0
        #: streams ever placed here
        self.placements = 0
        #: last scraped rdp_slo_error_budget_burn (0.0 when unknown)
        self.burn = 0.0
        #: FleetController placement weight (1.0 = full share)
        self.weight = 1.0
        #: last full stats payload (diagnostics)
        self.stats: dict = {}
        #: metrics-exposition port the replica advertised over the stats
        #: RPC (0 = none); the federation/trace-stitch scrapes need it
        self.metrics_port = 0

    @property
    def metrics_base_url(self) -> str | None:
        """Base URL of this replica's metrics server (federated scrape +
        /debug/spans stitching target), once the stats RPC has
        advertised a port."""
        if not self.metrics_port or self.metrics_port <= 0:
            return None
        host = self.endpoint.rsplit(":", 1)[0] or "localhost"
        return f"http://{host}:{self.metrics_port}"

    # -- wiring (lazy) ------------------------------------------------------

    @property
    def channel(self) -> grpc.Channel:
        if self._channel is None:
            self._channel = self._channel_factory(self.endpoint)
        return self._channel

    @property
    def stub(self) -> vision_grpc.VisionAnalysisServiceStub:
        if self._stub is None:
            self._stub = vision_grpc.VisionAnalysisServiceStub(self.channel)
        return self._stub

    @property
    def health_stub(self) -> health_lib.HealthStub:
        if self._health_stub is None:
            self._health_stub = health_lib.HealthStub(self.channel)
        return self._health_stub

    @property
    def stats_stub(self) -> ReplicaStatsStub:
        if self._stats_stub is None:
            self._stats_stub = ReplicaStatsStub(self.channel)
        return self._stats_stub

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = self._health_stub = self._stats_stub = None

    # -- placement state ----------------------------------------------------

    @property
    def placeable(self) -> bool:
        """In the ring: last health probe said SERVING, the breaker is
        closed (an open breaker = quarantined until its half-open probe
        succeeds), and the replica is not asking for a graceful drain --
        ``draining`` takes it out of NEW-stream placement BEFORE health
        ever flips, so its in-flight streams run to completion instead
        of failing over."""
        return (self.serving and self.breaker.state == CLOSED
                and not self.draining)

    @property
    def effective_load(self) -> float:
        """What least-loaded pick compares: in-flight streams (our own
        placements plus what sibling front-ends gossip they placed
        here) scaled by the controller's weight (a de-weighted replica
        looks busier than its raw count, shifting new streams away)."""
        return (self.inflight + self.external) / max(self.weight, 1e-6)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Replica({self.endpoint!r}, serving={self.serving}, "
                f"inflight={self.inflight}, burn={self.burn:.2f}, "
                f"weight={self.weight:.2f})")


class FleetController:
    """The PR 7 reactive control loop lifted to fleet level: consume each
    replica's error-budget burn and rebalance NEW-stream placement (the
    weighted ring) before any replica browns out.

    Pure function of the scraped burn values -- no thread of its own; the
    router's poll loop calls :meth:`rebalance` after every stats refresh,
    and tests call it directly with injected replicas. A replica's weight
    is 1.0 while its burn stays at or under ``burn_high`` and decays as
    ``burn_high / burn`` above it, floored at ``weight_floor`` so a
    burning replica keeps serving enough traffic to report recovery (the
    same starve-the-signal reasoning as brownout rung 3's duty cycle)."""

    #: weight moves smaller than this are ignored (gauge/log hygiene)
    DEADBAND = 0.05

    def __init__(self, *, burn_high: float = 0.8,
                 weight_floor: float = 0.1):
        if not 0.0 < weight_floor <= 1.0:
            raise ValueError("weight_floor must be in (0, 1]")
        self.burn_high = burn_high
        self.weight_floor = weight_floor
        self.actions_total = 0

    def target_weight(self, burn: float) -> float:
        if burn <= self.burn_high:
            return 1.0
        return max(self.weight_floor, self.burn_high / burn)

    def rebalance(self, replicas: list[Replica]) -> None:
        for r in replicas:
            target = self.target_weight(r.burn)
            if abs(target - r.weight) <= self.DEADBAND and target != 1.0:
                continue
            if target != r.weight:
                action = ("deweight" if target < r.weight else "reweight")
                if abs(target - r.weight) > self.DEADBAND:
                    self.actions_total += 1
                    obs.FLEET_CONTROLLER_ACTIONS.labels(action=action).inc()
                    log.info(
                        "fleet controller: %s %s weight %.2f -> %.2f "
                        "(burn %.2f)", action, r.endpoint, r.weight,
                        target, r.burn,
                    )
                r.weight = target
            obs.FLEET_REPLICA_WEIGHT.labels(replica=r.endpoint).set(
                r.weight)


class FleetRouter:
    """Health-gated membership + least-loaded stream placement over the
    static replica list.

    One poll thread drives the whole control surface: per-replica health
    probe (the breaker's half-open probe when quarantined), stats scrape
    (inflight/burn), controller rebalance, membership metrics, and the
    ``on_membership(live_count)`` callback the front-end uses to flip its
    own readiness. ``poll_once`` is public so tests drive membership
    deterministically without the thread."""

    #: expired/left leases older than this many TTLs are forgotten
    #: entirely (replica removed, channel closed) once idle
    PRUNE_TTLS = 10.0

    def __init__(self, endpoints: list[str], *, poll_s: float = 1.0,
                 probe_timeout_s: float = 1.0, breaker_failures: int = 2,
                 breaker_reset_s: float = 5.0,
                 controller: FleetController | None = None,
                 on_membership: Callable[[int], None] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 channel_factory=grpc.insecure_channel,
                 registry: LeaseRegistry | None = None):
        if not endpoints and registry is None:
            raise ValueError("a fleet needs at least one replica endpoint")
        self.replicas = [
            Replica(ep, breaker_failures=breaker_failures,
                    breaker_reset_s=breaker_reset_s, clock=clock,
                    channel_factory=channel_factory)
            for ep in endpoints
        ]
        #: the static seeds: never pruned, membership is purely
        #: health-gated for them even if one also registers a lease
        self._static = frozenset(endpoints)
        self.registry = registry
        self.poll_s = poll_s
        self.probe_timeout_s = probe_timeout_s
        self.controller = controller
        self.on_membership = on_membership
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        self._clock = clock
        self._channel_factory = channel_factory
        self._lock = checked_lock("fleet.router")
        self._ring_start = 0  # guarded_by: _lock
        self._last_live = -1  # guarded_by: _lock
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        #: stream-level failovers observed (reroutes + error-completions)
        self.failovers_total = 0  # guarded_by: _lock
        self.failover_frames_rerouted = 0  # guarded_by: _lock
        self.failover_frames_error_completed = 0  # guarded_by: _lock

    # -- membership ----------------------------------------------------------

    def poll_once(self) -> int:
        """One membership tick; returns the live (placeable) count."""
        if self.registry is not None:
            self.registry.sweep()
            self.sync_leases()
        for r in list(self.replicas):
            healthy = False
            exc: BaseException | None = None
            if self._lease_expired(r.endpoint):
                # a missed lease IS a failed probe: the member stopped
                # renewing (SIGKILL, partition, wedged renew loop), so it
                # takes the exact NOT_SERVING drop-out path below even if
                # a zombie socket still answers health checks. It stays
                # in the replica list -- quarantined, not dropped -- and a
                # re-register readmits it through the half-open probe.
                exc = RuntimeError(
                    f"lease expired ({r.endpoint} stopped renewing)")
            else:
                try:
                    resp = r.health_stub.Check(
                        health_pb2.HealthCheckRequest(service=""),
                        timeout=self.probe_timeout_s,
                    )
                    healthy = resp.status == health_lib.SERVING
                    if not healthy:
                        exc = RuntimeError(
                            f"health status {resp.status} (not SERVING)")
                except Exception as e:  # noqa: BLE001 - any probe failure
                    exc = e
            was = r.placeable
            if healthy:
                r.serving = True
                # a healthy probe is the half-open "probe stream": only a
                # breaker that ADMITS one may close on it, so a crashy
                # replica must hold healthy through its reset timeout
                # before rejoining the ring
                if r.breaker.state == CLOSED or r.breaker.allow():
                    r.breaker.record_success()
            else:
                r.serving = False
                r.breaker.record_failure(exc)
            if r.placeable != was:
                log.warning(
                    "fleet membership: replica %s %s (%s)",
                    r.endpoint,
                    "joined" if r.placeable else "dropped out",
                    "healthy" if healthy else exc,
                )
                journal_lib.JOURNAL.append(
                    events.FLEET_MEMBERSHIP,
                    replica=r.endpoint,
                    state="joined" if r.placeable else "dropped",
                    reason="healthy" if healthy else str(exc),
                )
            if r.serving:
                self._scrape_stats(
                    r, lease_left=self._lease_left(r.endpoint))
            else:
                obs.FLEET_REPLICA_BURN.labels(replica=r.endpoint).set(0.0)
        if self.controller is not None:
            self.controller.rebalance(list(self.replicas))
        if self.registry is not None:
            self._prune_leases()
        return self._publish_membership()

    def _lease_expired(self, endpoint: str) -> bool:
        return (self.registry is not None
                and self.registry.state_of(endpoint) == LEASE_EXPIRED)

    def _lease_left(self, endpoint: str) -> bool:
        return (self.registry is not None
                and self.registry.state_of(endpoint) == LEASE_LEFT)

    def sync_leases(self) -> None:
        """Fold newly ACTIVE leased endpoints into the probe set. Public
        so tests and the explorer admit a member without waiting for (or
        racing) the poll thread; idempotent, and the poll loop runs it
        every tick anyway."""
        if self.registry is None:
            return
        with self._lock:
            known = {r.endpoint for r in self.replicas}
        for ep in self.registry.endpoints(LEASE_ACTIVE):
            if ep in known:
                continue
            r = Replica(ep, breaker_failures=self._breaker_failures,
                        breaker_reset_s=self._breaker_reset_s,
                        clock=self._clock,
                        channel_factory=self._channel_factory)
            lease = self.registry.get(ep)
            if lease is not None and lease.metrics_port:
                r.metrics_port = lease.metrics_port
            with self._lock:
                self.replicas.append(r)
            log.info("fleet membership: leased replica %s joined the "
                     "probe set", ep)

    def _prune_leases(self) -> None:
        """Forget members whose lease has sat expired/left for
        ``PRUNE_TTLS`` TTLs: quarantine is for members expected back, a
        week-old lease is config debt. Static seeds just shed the stale
        lease and return to plain health gating."""
        for ep in self.registry.prunable(
                self.PRUNE_TTLS * self.registry.ttl_s):
            if ep in self._static:
                self.registry.drop(ep)
                continue
            removed: Replica | None = None
            with self._lock:
                for i, r in enumerate(self.replicas):
                    if r.endpoint == ep and r.inflight == 0:
                        removed = self.replicas.pop(i)
                        break
            if removed is not None:
                removed.close()
                self.registry.drop(ep)
                log.info("fleet membership: pruned long-dead leased "
                         "replica %s", ep)
                journal_lib.JOURNAL.append(
                    events.FLEET_MEMBERSHIP, replica=ep, state="pruned",
                    reason="lease stale beyond prune horizon",
                )

    def set_external_load(self, loads: dict[str, int]) -> None:
        """Gossip feed: streams sibling front-ends report placed on each
        replica (an absolute snapshot, not a delta), folded into
        ``effective_load`` so replicated front-ends don't all dogpile
        the replica each one sees as locally idle."""
        with self._lock:
            for r in self.replicas:
                r.external = max(0, int(loads.get(r.endpoint, 0)))

    @property
    def static_endpoints(self) -> frozenset:
        """The configured seeds: health-gated only, never pruned, and
        never the autoscaler's scale-down pick."""
        return self._static

    def placement_loads(self) -> dict[str, int]:
        """This front-end's own placements per replica -- the load half
        of the gossip payload siblings fold into their rings."""
        with self._lock:
            return {r.endpoint: r.inflight for r in self.replicas}

    def _scrape_stats(self, r: Replica, lease_left: bool = False) -> None:
        """Advisory: a failed scrape never drops a healthy replica --
        placement just keeps using the front-end's own inflight count and
        the last known burn. ``lease_left`` ORs into draining: a member
        that sent Leave is treated exactly like one reporting
        draining=true, even before its own flag flips."""
        try:
            stats = fetch_replica_stats(r.stats_stub, self.probe_timeout_s)
        except Exception as exc:  # noqa: BLE001
            log.debug("stats scrape of %s failed: %s", r.endpoint, exc)
            if lease_left and not r.draining:
                r.draining = True
                journal_lib.JOURNAL.append(
                    events.FLEET_DRAIN, replica=r.endpoint,
                    state="draining",
                )
            return
        r.stats = stats
        try:
            r.burn = float(stats.get("burn", 0.0))
        except (TypeError, ValueError):
            r.burn = 0.0
        try:
            r.metrics_port = int(stats.get("metrics_port", 0) or 0)
        except (TypeError, ValueError):
            r.metrics_port = 0
        was_draining = r.draining
        r.draining = bool(stats.get("draining", False)) or lease_left
        if r.draining != was_draining:
            log.info(
                "fleet membership: replica %s %s (graceful drain, health "
                "still SERVING)", r.endpoint,
                "draining -- out of new-stream placement" if r.draining
                else "un-drained -- placeable again",
            )
            journal_lib.JOURNAL.append(
                events.FLEET_DRAIN, replica=r.endpoint,
                state="draining" if r.draining else "undrained",
            )
        obs.FLEET_REPLICA_BURN.labels(replica=r.endpoint).set(r.burn)

    def _publish_membership(self) -> int:
        live = self.live_count
        obs.FLEET_REPLICAS_LIVE.set(live)
        obs.FLEET_REPLICAS_QUARANTINED.set(self.quarantined_count)
        obs.FLEET_REPLICAS_DRAINING.set(self.draining_count)
        # the change test runs under the lock: _publish_membership is
        # reached from the poll thread AND from stream handlers
        # (on_stream_error), and an unguarded read-modify-write here can
        # double-fire or swallow a membership transition. The callback
        # runs OUTSIDE the lock -- it flips gRPC health (its own
        # condition), and holding the router lock across it would nest
        # foreign locks for no reason.
        with self._lock:
            changed = live != self._last_live
            if changed:
                self._last_live = live
        if changed and self.on_membership is not None:
            try:
                self.on_membership(live)
            except Exception:  # pragma: no cover - observer bug
                log.exception("fleet membership callback failed")
        return live

    @property
    def live_count(self) -> int:
        return sum(1 for r in self.replicas if r.placeable)

    @property
    def quarantined_count(self) -> int:
        """Replicas held out of the ring by an OPEN breaker (half-open
        counts as quarantined too: it is not placeable until its probe
        succeeds)."""
        return sum(
            1 for r in self.replicas
            if r.serving and r.breaker.state != CLOSED
        )

    @property
    def draining_count(self) -> int:
        """Healthy replicas held out of new-stream placement by their
        own draining flag (NOT quarantined: the breaker is closed and
        in-flight streams keep running)."""
        return sum(
            1 for r in self.replicas
            if r.serving and r.draining and r.breaker.state == CLOSED
        )

    def wait_live(self, min_live: int = 1,
                  timeout_s: float = 30.0) -> bool:
        """Block until at least ``min_live`` replicas are placeable (the
        poll thread must be running) or the timeout expires."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.live_count >= min_live:
                return True
            time.sleep(min(0.05, self.poll_s))
        return self.live_count >= min_live

    # -- placement -----------------------------------------------------------

    def pick(self, exclude: Replica | None = None) -> Replica | None:
        """Place one new stream: the least effectively-loaded placeable
        replica, ties walking the ring (idle fleets round-robin, skewed
        fleets drain toward the emptiest host). Increments the chosen
        replica's inflight; callers MUST :meth:`release` it."""
        with self._lock:
            loads = [
                r.effective_load
                if (r.placeable and r is not exclude) else float("inf")
                for r in self.replicas
            ]
            if not any(load != float("inf") for load in loads):
                return None
            idx = _least_loaded(loads, self._ring_start)
            self._ring_start = (idx + 1) % len(self.replicas)
            r = self.replicas[idx]
            r.inflight += 1
            r.placements += 1
        obs.FLEET_PLACEMENTS.labels(replica=r.endpoint).inc()
        obs.FLEET_REPLICA_STREAMS.labels(replica=r.endpoint).set(r.inflight)
        return r

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)
        obs.FLEET_REPLICA_STREAMS.labels(replica=replica.endpoint).set(
            replica.inflight)

    def count_frame(self, replica: Replica) -> None:
        """One frame relayed through ``replica``. Counted under the
        router lock: concurrent streams share a replica, and the bare
        ``replica.frames += 1`` this replaces dropped increments under
        load (the racecheck RC002 class of bug, cross-object)."""
        with self._lock:
            replica.frames += 1
        obs.FLEET_REPLICA_FRAMES.labels(replica=replica.endpoint).inc()

    def on_stream_ok(self, replica: Replica) -> None:
        """A relayed stream completed cleanly: clears the breaker's
        consecutive-failure count (stream success is as good as a health
        probe)."""
        if replica.breaker.state == CLOSED:
            replica.breaker.record_success()

    def on_stream_error(self, replica: Replica,
                        exc: BaseException | None = None) -> None:
        """A relayed stream died at the transport level: count it toward
        the replica's breaker (an open breaker quarantines the replica
        out of the ring without waiting for the next health poll)."""
        replica.breaker.record_failure(exc)
        self._publish_membership()

    def record_failover(self, *, rerouted: int = 0,
                        error_completed: int = 0) -> None:
        with self._lock:
            self.failovers_total += 1
            self.failover_frames_rerouted += rerouted
            self.failover_frames_error_completed += error_completed
        obs.FLEET_FAILOVERS.inc()
        if rerouted:
            obs.FLEET_FAILOVER_FRAMES.labels(outcome="rerouted").inc(
                rerouted)
        if error_completed:
            obs.FLEET_FAILOVER_FRAMES.labels(
                outcome="error_completed").inc(error_completed)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception:  # pragma: no cover - keep polling
                    log.exception("fleet membership poll failed")

        # one immediate tick so the front-end does not report an empty
        # fleet for a full poll period after boot
        try:
            self.poll_once()
        except Exception:  # pragma: no cover
            log.exception("initial fleet membership poll failed")
        self._thread = threading.Thread(
            target=loop, name="fleet-membership", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for r in self.replicas:
            r.close()
