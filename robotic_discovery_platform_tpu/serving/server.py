"""The vision analysis gRPC server, TPU-backed.

Capability-parity rebuild of the reference server (reference:
services/vision_analysis/server.py): same wire contract, same insecure-port
serving loop, same metrics CSV, same registry-driven model resolution --
with the compute path swapped for the fused XLA graph (ops/pipeline.py) and
the reference's documented-but-missing behaviors implemented:

- the model is resolved through the ``staging`` alias first, falling back to
  the latest version (README.md:147 documents staging; server.py:81 actually
  loads /latest -- SURVEY.md section 2.1 "retraining pipeline");
- ``status``, ``mask_coverage`` and ``proc_time_ms`` response fields are
  populated for real (declared in the proto but never set by the reference);
- per-frame errors produce an error-status response and keep the stream
  alive instead of tearing it down;
- metrics writes are buffered and thread-safe (serving/metrics.py).

Resilience (resilience/ package):

- registry resolution runs under a per-service circuit breaker: a sustained
  registry outage opens the breaker, the hot-reload poller fast-fails
  without touching the network, and the server keeps serving its current
  engine (state transitions are logged once each -- this replaces the old
  module-global rate-limited warning, whose shared timestamp let one
  server's warning silence another's for 60 s);
- each frame honors the client's gRPC deadline and cancellation BEFORE
  paying decode + device time, and dispatcher submits carry that deadline;
- an overloaded batch dispatcher sheds load with RESOURCE_EXHAUSTED; the
  dispatcher itself is pipelined (serving/batching.py: collector/stager ->
  bounded in-flight window -> completer), with
  ServerConfig.max_inflight_dispatches / RDP_INFLIGHT capping how many
  batches hold device memory at once, and its stop() drains both pipeline
  queues so close()/hot-reload teardown never strands a frame;
- the standard grpc.health.v1 health service (serving/health.py) reports
  readiness, flipping to SERVING only after model warm-up and back to
  NOT_SERVING when a drain begins;
- close() drains in-flight streams (bounded by ServerConfig.drain_grace_s)
  before tearing the engines down.

Observability (observability/ package):

- every frame feeds the rdp_* metric families (frames by status, per-stage
  latency histograms, in-flight streams; the batch dispatcher and the
  registry breaker export their own) and ``GET /metrics`` serves them in
  Prometheus text format when ServerConfig.metrics_port / RDP_METRICS_PORT
  is set -- started here, stopped in close();
- each stream adopts the client's ``traceparent`` (W3C trace context) from
  gRPC metadata, so client- and server-side log lines carry the same
  [trace=...] stamp -- and per-frame error statuses / shed
  RESOURCE_EXHAUSTED details carry ``[trace=...]`` too, so a client-side
  failure joins its ``GET /debug/spans`` timeline;
- per-stage and end-to-end latency additionally feed streaming-quantile
  summaries (``rdp_*_summary_seconds``: P^2 p50/p95/p99/p99.9), and when
  ServerConfig.slo_ms / RDP_SLO_MS sets an objective every frame feeds
  the SLO tracker (``rdp_slo_violations_total``, error-budget burn).

Drift observability (monitoring/profile.py):

- every OK/degraded frame's free signals -- mask coverage, mean/max
  curvature, depth-validity fraction, segmentation confidence margin
  (mean |sigmoid-0.5|, computed inside the fused graph) -- feed an online
  DriftMonitor: per-signal sliding windows scored (PSI / Jensen-Shannon)
  against a reference profile loaded from
  ``ServerConfig.drift_profile_path`` / ``RDP_DRIFT_PROFILE``, the served
  registry version's ``drift_profile.json`` artifact, or a self-baseline
  over the first frames; hot-reload re-stamps the reference for the new
  generation;
- sustained scores above ``drift_psi_threshold`` fire ONE structured
  retrain recommendation per excursion (sustain + cooldown hysteresis):
  counted (``rdp_drift_recommendations_total``), pinned in the flight
  recorder, and surfaced -- with live-vs-reference histograms and
  per-signal scores -- at ``GET /debug/drift``;
- all of it is host-side Python bookkeeping off the compute path: the
  f32 serial bitwise-parity guarantee and the jit cache are untouched.

Host-path ingest (serving/ingest.py):

- frame decode runs through the ingest layer: a decode worker pool
  (``ServerConfig.decode_workers`` / ``RDP_DECODE_WORKERS``; 0 = inline,
  the bitwise-parity mode) with per-stream read-ahead, pre-decode
  deadline shedding, and watchdog restart; raw-format wire payloads
  (``Image.format = 1``) bypass ``imdecode`` entirely as zero-copy
  views of the gRPC message buffer;
- per-stream camera geometry (intrinsics + depth scale) is converted --
  and, on the direct path, ``device_put`` -- once per distinct content
  through the geometry cache, not once per frame;
- warm-up's synthetic frame pair is built once per (width, height) per
  process and reused across generations/hot-reloads.

Overload control (serving/admission.py, serving/controller.py):

- the dispatcher's backlog is deadline-aware: at the cap the queued
  frame with the least remaining headroom is evicted (not the newcomer
  blindly rejected), and frames whose deadline is unmeetable are shed
  before staging (``rdp_shed_by_deadline_total``);
- with ServerConfig.controller_enabled / RDP_CONTROLLER, a reactive
  controller consumes the error-budget burn gauge and retunes
  max_inflight / batch window / bucket floor / dispatch mode online,
  with a brownout ladder under sustained burn > 1 whose top rung
  refuses new streams (UNAVAILABLE -> clients fail over);
- a mesh chip whose dispatches keep failing is quarantined by its
  per-chip circuit breaker: removed from the ring, its
  ``rdp.serving.chip.<i>`` health entry flips NOT_SERVING, in-flight
  frames fail over to healthy chips, and a half-open probe dispatch
  reinstates it on recovery.
"""

from __future__ import annotations

import functools
import math
import os
import threading
import time
from concurrent import futures
from typing import Any, NamedTuple

import grpc
import jax
import numpy as np

from robotic_discovery_platform_tpu import tracking
from robotic_discovery_platform_tpu.io.frames import load_calibration
from robotic_discovery_platform_tpu.models import variants as variants_lib
from robotic_discovery_platform_tpu.monitoring import profile as profile_lib
from robotic_discovery_platform_tpu.observability import (
    events,
    exposition,
    instruments as obs,
    journal as journal_lib,
    recorder as recorder_lib,
    slo as slo_lib,
    trace,
)
from robotic_discovery_platform_tpu.ops import pipeline
from robotic_discovery_platform_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    inject,
)
from robotic_discovery_platform_tpu.resilience import (
    sites as fault_sites,
)
from robotic_discovery_platform_tpu.serving import (
    controller as controller_lib,
    egress as egress_lib,
    entropy as entropy_lib,
    fleet as fleet_lib,
    health as health_lib,
    ingest as ingest_lib,
    rollout as rollout_lib,
    zoo as zoo_lib,
)
from robotic_discovery_platform_tpu.ops.pallas import quant
from robotic_discovery_platform_tpu.serving.batching import (
    OverloadedError,
    resolve_dispatch_mode,
    resolve_precision,
    resolve_serving_chips,
)
from robotic_discovery_platform_tpu.serving.metrics import MetricsWriter
from robotic_discovery_platform_tpu.serving.proto import vision_grpc, vision_pb2
from robotic_discovery_platform_tpu.utils.config import (
    GeometryConfig,
    ServerConfig,
)
from robotic_discovery_platform_tpu.utils.logging import get_logger
from robotic_discovery_platform_tpu.utils.profiling import StageTimer

log = get_logger(__name__)


def resolve_serving_version(cfg: ServerConfig, store=None, *,
                            raise_on_error: bool = False) -> int | None:
    """The registry version serving should run: the ``staging`` alias when
    set, else the latest version; None when the registry is empty or
    unreachable (callers decide whether that is fatal). With
    ``raise_on_error`` the failure propagates instead -- that is how the
    service's circuit breaker observes outcomes (serving/server.py used to
    rate-limit this warning through a module-global timestamp shared by
    every server instance; the per-service breaker replaced it).

    Uses a store SCOPED to ``cfg.tracking_uri`` (tracking.store_for):
    the reload poller calls this from a background thread, and mutating
    the process-global tracking URI from there would silently re-point
    every other component's tracking mid-run. Callers that poll should
    pass a cached ``store`` -- rebuilding an MLflow-backed store every
    tick would churn clients and scratch dirs."""
    try:
        inject(fault_sites.SERVING_RESOLVE)
        store = store if store is not None else tracking.store_for(
            cfg.tracking_uri
        )
        version = store.get_alias(cfg.model_name, cfg.model_alias)
        if version is not None:
            return int(version)
        return int(store.latest_version(cfg.model_name)["version"])
    except Exception as exc:
        if raise_on_error:
            raise
        log.warning(
            "registry %s unreachable/empty (%s: %s); serving keeps its "
            "current model", cfg.tracking_uri, type(exc).__name__, exc,
        )
        return None


def resolve_serving_model(cfg: ServerConfig):
    """staging alias first, latest fallback.
    Returns (model, variables, version)."""
    tracking.set_tracking_uri(cfg.tracking_uri)
    version = resolve_serving_version(cfg)
    if version is not None:
        uri = f"models:/{cfg.model_name}/{version}"
        model, variables = tracking.load_model(uri)
        log.info("loaded %s (alias %r first)", uri, cfg.model_alias)
        return model, variables, version
    # fall through for the error message of the plain path
    model, variables = tracking.load_model(f"models:/{cfg.model_name}/latest")
    return model, variables, None


# focal-length default lives with the ingest/geometry machinery now; the
# alias keeps this module's historical import surface (tests use it)
_default_intrinsics = ingest_lib.default_intrinsics


@functools.lru_cache(maxsize=8)
def _warm_frames(width: int, height: int) -> tuple[np.ndarray, np.ndarray]:
    """The synthetic warm-up frame pair for one camera geometry, built --
    and encode/decode-roundtripped -- ONCE per (width, height) per
    process. warmup() used to re-encode its dummy JPEG/PNG on every call,
    so every hot-reload and every test server paid two image encodes and
    two decodes for identical bytes."""
    import cv2

    dummy = np.zeros((height, width, 3), np.uint8)
    ok, png = cv2.imencode(".png", np.zeros((height, width), np.uint16))
    if not ok:
        raise ValueError("warm-up depth encode failed")
    req = vision_pb2.AnalysisRequest(
        color_image=vision_pb2.Image(
            data=cv2.imencode(".jpg", dummy)[1].tobytes(),
            width=width, height=height,
        ),
        depth_image=vision_pb2.Image(data=png.tobytes(), width=width,
                                     height=height),
    )
    rgb, depth, _ = ingest_lib.decode_request(req)
    return rgb, depth


class _FrameResult(NamedTuple):
    """One analyzed frame's host-side outputs (response fields + the
    drift-monitor signals the frame already computed)."""

    mean_k: float
    max_k: float
    spline: np.ndarray
    #: the response ``mask`` payload in the REQUESTED wire format
    #: (mask_format 0 = legacy PNG bytes, 1 = packed bits, 2 = RLE);
    #: empty when egress was skipped for a dead stream
    mask_png: bytes
    coverage: float
    valid: bool
    confidence_margin: float
    depth_valid_fraction: float
    #: the aux head's defect/anomaly score (None for "segment" heads --
    #: i.e. always None on the default model's bitwise path)
    anomaly: float | None = None
    #: the packed-spline response payload (f32 LE triples) for packed
    #: wire formats; b"" on the legacy path, so the response field
    #: serializes to zero bytes and legacy responses stay bitwise
    spline_wire: bytes = b""


class Engine(NamedTuple):
    """One served model generation: everything a frame touches, swapped as
    a unit so a hot-reload can never mix old variables with a new forward
    (SURVEY.md section 3.4: the reference's promotion only takes effect at
    restart -- 'a running server keeps its old model')."""

    analyze: Any
    variables: Any
    dispatcher: Any
    version: int | None


class VisionAnalysisService(vision_grpc.VisionAnalysisServiceServicer):
    def __init__(
        self,
        model,
        variables,
        intrinsics: np.ndarray | None,
        depth_scale: float,
        cfg: ServerConfig = ServerConfig(),
        geom_cfg: GeometryConfig = GeometryConfig(),
        metrics: MetricsWriter | None = None,
        version: int | None = None,
    ):
        self.cfg = cfg
        self.geom_cfg = geom_cfg
        self.intrinsics = intrinsics
        self.depth_scale = depth_scale
        # Host-path ingest (serving/ingest.py): the decode worker pool
        # (0 workers = inline decode in the handler thread, the
        # bitwise-parity mode) and the per-stream geometry cache that
        # replaces the per-frame np.asarray(intrinsics) conversion and
        # -- on the direct path -- its per-frame device staging.
        self.ingest = ingest_lib.DecodePool(
            ingest_lib.resolve_decode_workers(cfg.decode_workers),
            prefetch=cfg.ingest_prefetch,
            onchip=ingest_lib.resolve_onchip_decode(cfg.onchip_decode),
        )
        if self.ingest.workers:
            log.info("ingest decode pool: %d worker(s), read-ahead %d",
                     self.ingest.workers, self.ingest.prefetch)
        if self.ingest.onchip:
            log.info("on-chip split decode: host entropy-decodes baseline "
                     "JPEG; dequant+IDCT+upsample+color ride the device")
        # Host-path egress (serving/egress.py): the encode worker pool
        # (0 workers = inline encode in the handler thread, the
        # bitwise-parity mode) that takes legacy PNG encode -- and the
        # packed/RLE wire encodes -- off the stream-handler hot path.
        self.egress = egress_lib.EncodePool(
            egress_lib.resolve_egress_workers(cfg.egress_workers)
        )
        if self.egress.workers:
            log.info("egress encode pool: %d worker(s)", self.egress.workers)
        # direct-path (unbatched) decode+analyze graphs for
        # coefficient-lane frames, memoized per (h, w, subsampling);
        # rebuilt lazily after every engine swap (_make_engine clears it)
        self._coef_direct: dict[tuple, Any] = {}  # guarded_by: _coef_direct_lock
        self._coef_direct_lock = threading.Lock()
        self._geom_cache = ingest_lib.GeometryCache()
        # one scoped store for the reload poller's lifetime (thread-safe
        # to build here; rebuilding per poll would churn MLflow clients
        # and scratch dirs)
        self._registry_store = tracking.store_for(cfg.tracking_uri)
        # Serving mesh (multi-chip dispatch): built ONCE at startup and
        # shared by every engine generation -- hot-reload swaps analyzers
        # and variables, never devices. Only meaningful when micro-batching
        # is on (the single-frame path has no dispatch window to route).
        self.dispatch_mode = resolve_dispatch_mode(cfg.dispatch_mode)
        # Serving precision tier (ops/pallas/quant.py): resolved ONCE at
        # startup; every engine generation re-applies it in _make_engine,
        # so a hot-reload of new registry weights re-quantizes. The
        # pre-transform (f32) model/variables of the CURRENT generation
        # are kept as the warm-up parity-gate reference.
        self.precision = resolve_precision(cfg.precision)
        self._pristine: tuple[Any, Any] = (model, variables)
        #: warm-up parity report for bf16/int8 (None at f32 / pre-warm)
        self.parity: dict | None = None
        for p in quant.PRECISIONS:
            obs.SERVING_PRECISION.labels(precision=p).set(
                1.0 if p == self.precision else 0.0
            )
        if self.precision != "f32":
            log.info("serving precision tier: %s", self.precision)
        # Model zoo roster (serving/zoo.py + models/variants.py): the
        # named engine generations this server holds side by side. The
        # empty roster is the legacy single-model server -- one entry
        # (the seed segmenter), no placer, serving path bitwise
        # identical to pre-zoo. The default entry's NAME labels every
        # per-model metric even on legacy servers.
        self._zoo_names = variants_lib.resolve_zoo_models(cfg.zoo_models)
        self.model_label = self._zoo_names[0]
        obs.ZOO_MODELS.set(len(self._zoo_names))
        self._serving_mesh = None
        chips = resolve_serving_chips(cfg.serving_mesh)
        if cfg.batch_window_ms > 0 and chips > 1:
            from robotic_discovery_platform_tpu.parallel import (
                mesh as mesh_lib,
            )

            self._serving_mesh = mesh_lib.make_serving_mesh(chips)
            log.info(
                "serving mesh: %d chip(s), %s dispatch",
                chips, self.dispatch_mode,
            )
        #: devices the batch dispatcher routes across (1 = single-device)
        self.serving_chips = chips if self._serving_mesh is not None else 1
        # resolved BEFORE the first engine build: a controller-enabled
        # server binds BOTH routed layouts (per-chip replicas and the
        # mesh-replicated copy) so the controller can flip dispatch modes
        # online
        self._controller_enabled = controller_lib.resolve_controller_enabled(
            cfg.controller_enabled
        )
        # brownout rung 3: the controller flips this and _enter_stream
        # refuses every other new stream (UNAVAILABLE -> clients fail
        # over; the duty cycle keeps the SLO signal alive). Both ride
        # the stream condition: the writer is the controller thread, the
        # readers are every handler thread.
        self._refusing_streams = False  # guarded_by: _streams_cond
        self._brownout_tick = 0  # guarded_by: _streams_cond
        # ZooPlacer (statistical multiplexing): built BEFORE the engine
        # so the dispatcher can consult it per launch. Only a real
        # multi-model zoo pays for one; the legacy server routes exactly
        # as before.
        self.placer: zoo_lib.ZooPlacer | None = None
        if len(self._zoo_names) > 1:
            self.placer = zoo_lib.ZooPlacer(
                self._zoo_names,
                chips=max(1, chips if self._serving_mesh is not None else 1),
                mode=zoo_lib.resolve_zoo_placement(cfg.zoo_placement),
                interval_s=cfg.zoo_rate_interval_s,
                window=cfg.zoo_rate_window,
                rebalance_s=cfg.zoo_rebalance_s,
                corr_cap=cfg.zoo_corr_cap,
            )
            log.info("model zoo: %s (%s placement over %d chip(s))",
                     ",".join(self._zoo_names), self.placer.mode,
                     self.placer.chips)
        self._engine = self._make_engine(model, variables, version)
        self._warm_shape: tuple[int, int] | None = None
        self._reload_stop: threading.Event | None = None
        self._reload_thread: threading.Thread | None = None
        # at most one reload in flight: the poller thread and direct
        # callers (tests, admin hooks) must not interleave two
        # resolve/build/swap sequences -- unserialized, engines could swap
        # in arbitrary order and a generation's dispatcher could miss its
        # scheduled stop
        self._reload_lock = threading.Lock()
        self._reload_busy = False
        self._closed = False
        # pending grace-delayed (timer, old_dispatcher) teardowns; close()
        # cancels the timers and stops the dispatchers immediately
        self._grace_stops: list[tuple[threading.Timer, Any]] = []
        # Per-service registry breaker: a sustained registry outage opens
        # it and the reload poller fast-fails without touching the network
        # (and without per-tick log spam -- the breaker logs transitions).
        self.registry_breaker = CircuitBreaker(
            failure_threshold=cfg.registry_breaker_failures,
            reset_timeout_s=cfg.registry_breaker_reset_s,
            name=f"registry:{cfg.tracking_uri}",
        )
        # grpc.health.v1 state: NOT_SERVING until warm-up completes
        # (build_server / warmup flip it), NOT_SERVING again once a drain
        # begins.
        self.health = health_lib.HealthServicer()
        self.health.set(vision_grpc.SERVICE_NAME, health_lib.NOT_SERVING)
        # one readiness entry per routed chip: a probe can enumerate
        # rdp.serving.chip.<i> until NOT_FOUND to read the mesh width;
        # the entries flip with overall readiness (set_all)
        for i in range(self.serving_chips):
            self.health.set(f"rdp.serving.chip.{i}", health_lib.NOT_SERVING)
        obs.SERVING_CHIPS.set(self.serving_chips)
        # in-flight stream accounting for graceful drain
        self._streams_cond = threading.Condition()
        self._active_streams = 0  # guarded_by: _streams_cond
        self._draining = False  # guarded_by: _streams_cond
        # Rollout wiring (serving/rollout.py): the shadow tap mirrors a
        # fraction of analyzed frames (inputs + this generation's
        # outputs) to a gated candidate -- installed/cleared by the
        # rollout manager for the SHADOW stage, a single attribute read
        # per frame otherwise. `rollout` is the shared RolloutManager
        # drift recommendations are forwarded to when one is attached.
        self._shadow_hook = None
        self.rollout: rollout_lib.RolloutManager | None = None
        # frames served over this process's lifetime (every terminal
        # status); reported over the replica stats RPC so a fleet
        # front-end can read per-replica progress without scraping
        # /metrics over HTTP. Incremented by every handler thread, so it
        # rides the stream condition too (the bare += it replaces lost
        # counts under concurrent streams).
        self._frames_total = 0  # guarded_by: _streams_cond
        self.metrics = metrics or MetricsWriter(
            cfg.metrics_csv, cfg.metrics_flush_every
        )
        # Prometheus exposition endpoint; build_server starts one when
        # cfg.metrics_port / RDP_METRICS_PORT asks for it, close() stops it
        self.metrics_server: exposition.MetricsServer | None = None
        # elastic membership (serving/fleet.py): set by build_server when
        # registrars are configured; drain() sends Leave, close() stops it
        self.lease_client: fleet_lib.LeaseClient | None = None
        self.bound_port = 0  # set by build_server after add_insecure_port
        # End-to-end latency SLO (observability/slo.py): every frame's
        # total latency feeds the violation counter and the error-budget
        # burn gauge. Off unless cfg.slo_ms / RDP_SLO_MS sets an objective.
        self.slo: slo_lib.SloTracker | None = None
        slo_ms = slo_lib.resolve_slo_ms(cfg.slo_ms)
        if slo_ms is not None:
            self.slo = slo_lib.SloTracker(
                slo_ms / 1e3, budget=cfg.slo_budget, window=cfg.slo_window,
                name="e2e",
                violations=obs.SLO_VIOLATIONS.labels(objective="e2e"),
                # model="" = the all-models aggregate: what the reactive
                # controller and the fleet front-end consume; per-model
                # burn children ride next to it under a zoo
                burn_gauge=obs.SLO_BURN.labels(objective="e2e", model=""),
                objective_gauge=obs.SLO_OBJECTIVE.labels(objective="e2e"),
            )
            log.info("SLO tracking: %.1f ms objective, %.2f%% budget",
                     slo_ms, 100 * cfg.slo_budget)
        # Online drift monitor (monitoring/profile.py): every served
        # frame's free signals feed per-signal sliding windows scored
        # against a reference profile (registry artifact / explicit path /
        # self-baseline). Strictly host-side deque+histogram bookkeeping
        # OFF the compute path -- no device transfers, no jit retraces --
        # so the f32 serial bitwise-parity guarantee is untouched.
        self.drift: profile_lib.DriftMonitor | None = None
        if cfg.drift_enabled:
            reference = self._load_drift_profile(version)
            self.drift = profile_lib.DriftMonitor(
                reference=reference,
                window=cfg.drift_window,
                baseline_frames=cfg.drift_baseline_frames,
                score_every=cfg.drift_score_every,
                psi_threshold=cfg.drift_psi_threshold,
                sustain_s=cfg.drift_sustain_s,
                cooldown_s=cfg.drift_cooldown_s,
                generation=version,
                on_score=self._on_drift_score,
                on_recommendation=self._on_drift_recommendation,
            )
            obs.DRIFT_REFERENCE_AGE.set(
                -1.0 if reference is None else reference.age_s
            )
        # Reactive SLO controller (serving/controller.py): consumes the
        # tracker's burn signal and retunes the LIVE engine's dispatcher
        # (the indirection follows hot-reload swaps). Needs an objective
        # to control against and a dispatcher to actuate.
        self.controller: controller_lib.ReactiveController | None = None
        if (self._controller_enabled and self.slo is not None
                and cfg.batch_window_ms > 0):
            self.controller = controller_lib.ReactiveController(
                dispatcher=lambda: self._engine.dispatcher,
                burn=lambda: self.slo.burn,
                refuse_streams=self._set_refuse_streams,
                interval_s=cfg.controller_interval_s,
                burn_high=cfg.controller_burn_high,
                burn_low=cfg.controller_burn_low,
                sustain_s=cfg.controller_sustain_s,
                cooldown_s=cfg.controller_cooldown_s,
                inflight_cap=cfg.controller_inflight_cap,
                samples=lambda: self.slo.observed_total,
            )
            self.controller.start()
        elif self._controller_enabled:
            log.warning(
                "controller enabled but idle: it needs slo_ms > 0 (got "
                "%s) and batch_window_ms > 0 (got %s)",
                cfg.slo_ms, cfg.batch_window_ms,
            )
        # Model zoo entries (serving/zoo.py): the default entry is this
        # server's legacy engine state under its catalog name; extras
        # are built from their own registry entries and bound onto the
        # SHARED dispatcher. Per-model frame counts ride the stream
        # condition like _frames_total.
        self._model_frames: dict[str, int] = {}  # guarded_by: _streams_cond
        self.zoo = zoo_lib.ModelZoo(default=self.model_label)
        self.zoo.add(zoo_lib.ZooEntry(
            name=self.model_label,
            variant=variants_lib.VARIANTS[self.model_label],
            analyze=None,  # the default model reads through self._engine
            variables=None, version=version, precision=self.precision,
        ))
        self._build_zoo_entries(version)

    def _set_refuse_streams(self, refusing: bool) -> None:
        """Controller brownout rung 3 actuator."""
        with self._streams_cond:
            changed = refusing != self._refusing_streams
            self._refusing_streams = refusing
        if changed:
            log.warning(
                "overload brownout: %s new analysis streams",
                "refusing" if refusing else "accepting",
            )

    def _on_chip_health(self, chip: int, serving: bool) -> None:
        """DeviceRouter quarantine hook: a quarantined chip's
        ``rdp.serving.chip.<i>`` health entry goes NOT_SERVING so probes
        and dashboards see the degraded mesh; reinstatement flips it
        back."""
        self.health.set(
            f"rdp.serving.chip.{chip}",
            health_lib.SERVING if serving else health_lib.NOT_SERVING,
        )

    # -- drift observability ------------------------------------------------

    def _load_drift_profile(
            self, version: int | None, model_name: str | None = None,
            allow_explicit: bool = True,
    ) -> profile_lib.FeatureProfile | None:
        """Resolve the reference profile: an explicit path
        (cfg.drift_profile_path / RDP_DRIFT_PROFILE) wins, else the
        ``drift_profile.json`` artifact next to the served registry
        version's weights; None means self-baseline. ``model_name``
        selects the registry entry (default: the server's default
        model); the explicit-path override only ever applies to the
        default model -- one path cannot reference M distributions."""
        model_name = model_name or self.cfg.model_name
        if allow_explicit:
            path = profile_lib.resolve_drift_profile_path(
                self.cfg.drift_profile_path
            )
            if path is not None:
                try:
                    return profile_lib.FeatureProfile.load(path)
                except Exception as exc:
                    log.warning(
                        "drift profile %s unusable (%s: %s); falling back "
                        "to registry artifact / self-baseline",
                        path, type(exc).__name__, exc,
                    )
        if version is None:
            return None
        try:
            artifact = (
                self._registry_store.version_path(
                    model_name, version
                ) / profile_lib.DRIFT_PROFILE_FILE
            )
            if artifact.exists():
                return profile_lib.FeatureProfile.load(artifact)
        except Exception as exc:
            log.warning(
                "no drift profile artifact for %s v%s (%s: %s); "
                "self-baselining", model_name, version,
                type(exc).__name__, exc,
            )
        return None

    def _on_drift_score(self, signal: str,
                        score: profile_lib.DriftScore) -> None:
        obs.DRIFT_SCORE.labels(signal=signal,
                               model=self.model_label).set(score.psi)
        if self.drift is not None:
            age = self.drift.reference_age_s
            obs.DRIFT_REFERENCE_AGE.set(-1.0 if age is None else age)

    def _on_model_drift_score(self, model: str, signal: str,
                              score: profile_lib.DriftScore) -> None:
        """Per-zoo-model drift scoring hook (extras; the default model's
        monitor keeps the legacy ``_on_drift_score`` path)."""
        obs.DRIFT_SCORE.labels(signal=signal, model=model).set(score.psi)

    def _on_model_drift_recommendation(
            self, model: str,
            rec: profile_lib.RetrainRecommendation) -> None:
        """A non-default zoo model drifted: counted, pinned, logged. NOT
        forwarded to the rollout manager -- the drain/retrain/shadow
        cycle drives the default model's generation; extra zoo models
        retrain through their own registry workflow (their promotion is
        an alias move this server's reload poller does not watch yet)."""
        obs.DRIFT_RECOMMENDATIONS.inc()
        recorder_lib.RECORDER.pin(recorder_lib.RECORDER.record_event(
            "serving.drift_recommendation", model=model,
            signals=",".join(rec.signals),
            generation=str(rec.generation),
            reference=rec.reference_source,
            reason=rec.reason,
        ))
        journal_lib.JOURNAL.append(
            events.DRIFT_RECOMMENDATION, rec.reason, model=model,
            signals=",".join(rec.signals), generation=str(rec.generation),
        )
        log.warning("DRIFT[%s]: %s -- recommend retraining", model,
                    rec.reason)

    def _on_drift_recommendation(
            self, rec: profile_lib.RetrainRecommendation) -> None:
        """Hysteresis-gated: at most one of these per sustained excursion.
        Counted, pinned in the flight recorder (a recommendation is
        evidence that must survive ring wrap-around), logged -- and, when
        a rollout manager is attached (serving/rollout.py), handed to it:
        the recommendation becomes a supervised drain -> retrain ->
        shadow -> gate -> promote cycle instead of terminating here."""
        obs.DRIFT_RECOMMENDATIONS.inc()
        recorder_lib.RECORDER.pin(recorder_lib.RECORDER.record_event(
            "serving.drift_recommendation",
            signals=",".join(rec.signals),
            generation=str(rec.generation),
            reference=rec.reference_source,
            reason=rec.reason,
        ))
        journal_lib.JOURNAL.append(
            events.DRIFT_RECOMMENDATION, rec.reason,
            signals=",".join(rec.signals), generation=str(rec.generation),
        )
        log.warning(
            "DRIFT: %s -- recommend retraining (workflows.retraining)",
            rec.reason,
        )
        manager = self.rollout
        if manager is not None:
            try:
                manager.on_recommendation(rec)
            except Exception:  # pragma: no cover - manager bug
                log.exception("rollout manager rejected the "
                              "recommendation")

    def _apply_drift_reference(
            self, version: int | None,
            reference: profile_lib.FeatureProfile | None) -> None:
        """Adopt the swapped-in generation's drift reference -- its
        profile artifact when it shipped one, else a fresh self-baseline,
        re-stamping the reference generation either way. Callers hold
        ``_reload_lock``: the reference must change in the SAME critical
        section as the engine swap, so a scrape can never pair new
        weights with the old reference (or vice versa)."""
        if self.drift is None:
            return
        if reference is not None:
            self.drift.set_reference(reference)
            obs.DRIFT_REFERENCE_AGE.set(reference.age_s)
        else:
            self.drift.rebaseline(generation=version)
            obs.DRIFT_REFERENCE_AGE.set(-1.0)

    def version_and_reference(self) -> tuple[int | None, object]:
        """The (engine generation, drift reference generation) pair read
        under the reload lock -- the consistency the promotion swap
        guarantees: both move together, so this never returns a mixed
        pair (tests and /debug consumers assert it)."""
        with self._reload_lock:
            version = self._engine.version
            if self.drift is None:
                return version, None
            ref = self.drift.reference
            gen = (ref.generation if ref is not None
                   and ref.generation is not None
                   else self.drift.generation)
            return version, gen

    def drift_debug(self) -> dict:
        """The ``GET /debug/drift`` payload. Snapshot and engine version
        are read under the reload lock so a mid-promotion request sees a
        consistent (weights, reference) pair."""
        if self.drift is None:
            return {"enabled": False,
                    "reason": "drift monitoring disabled "
                              "(ServerConfig.drift_enabled)"}
        with self._reload_lock:
            snap = self.drift.snapshot()
            snap["model_version"] = self._engine.version
        return snap

    @property
    def variables(self):
        return self._engine.variables

    @property
    def analyze(self):
        return self._engine.analyze

    @property
    def dispatcher(self):
        return self._engine.dispatcher

    @property
    def current_version(self) -> int | None:
        return self._engine.version

    def _make_engine(self, model, variables, version) -> Engine:
        cfg, geom_cfg = self.cfg, self.geom_cfg
        # precision tier applied per GENERATION: the pristine (f32) pair is
        # kept for the parity gate, the engine binds the transformed pair.
        # At f32 apply_precision returns its inputs untouched, so that tier
        # stays bitwise identical to pre-tier serving.
        self._pristine = (model, variables)
        model, variables, qreport = quant.apply_precision(
            model, variables, self.precision
        )
        if qreport is not None and qreport.get("layers"):
            log.info(
                "int8-quantized %d conv kernels for version %s "
                "(max |err| %.3g, %.1f%% rel; %d int8 bytes vs %d f32)",
                qreport["layers"], version, qreport["max_abs_err"],
                100 * qreport["max_rel_err"], qreport["int8_bytes"],
                qreport["f32_bytes"],
            )
        # Stage the weight tree explicitly ONCE per engine generation
        # (already the per-chip policy under a serving mesh): a
        # checkpoint-restored tree can surface as host numpy, and passing
        # that to the jitted analyzer re-transfers every weight on every
        # dispatch -- implicitly, which RDP_TRANSFER_GUARD=strict rightly
        # refuses. Gated on the tree actually holding host arrays so an
        # all-device tree keeps OBJECT identity (the f32 tier's
        # bitwise-identical-by-construction contract is literally "same
        # objects in, same objects out").
        if any(not isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(variables)):
            variables = jax.device_put(variables)
        if self._serving_mesh is not None:
            # the Pallas-fused forward closes over default-device buffers
            # and has no partitioning rules, so under a serving mesh every
            # chip runs the Flax/XLA forward (the trainer applies the same
            # policy under its mesh)
            if cfg.model_forward == "pallas":
                log.warning(
                    "model_forward='pallas' cannot route across a serving "
                    "mesh; using the Flax/XLA forward on every chip"
                )
            forward = None
        else:
            forward = self._build_forward(model, variables, cfg)
        analyze = pipeline.make_frame_analyzer(
            model, img_size=cfg.model_img_size, geom_cfg=geom_cfg,
            forward=forward,
        )

        # Coefficient-lane analyzer factory (split JPEG decode): builds
        # the decode+analyze graph for one (geometry, subsampling), closed
        # over THIS generation's model + variables. Shared by the batch
        # dispatcher (lazily memoized per key) and the direct path
        # (self._coef_direct). Default model only: zoo extras keep pixel
        # formats -- their variables never ride this closure.
        def coef_factory(model_key: str, height: int, width: int,
                         subsampling: str, _model=model,
                         _variables=variables, _forward=forward):
            if model_key:
                raise ValueError(
                    "the coefficient lane serves the default model only; "
                    f"model {model_key!r} frames must use pixel formats"
                )
            coef_analyze = pipeline.make_coef_batch_analyzer(
                _model, img_size=cfg.model_img_size, geom_cfg=geom_cfg,
                forward=_forward, height=height, width=width,
                subsampling=subsampling, pack=cfg.egress_pack,
            )
            return (lambda y, cb, cr, qy, qc, depths, intr, scales:
                    coef_analyze(_variables, y, cb, cr, qy, qc, depths,
                                 intr, scales))

        self._coef_factory_fn = coef_factory
        with self._coef_direct_lock:
            # stale closures must not outlive the generation that built
            # them -- direct coef graphs rebuild lazily on first use
            self._coef_direct.clear()
        dispatcher = None
        if cfg.batch_window_ms > 0:
            from robotic_discovery_platform_tpu.serving.batching import (
                BatchDispatcher,
                DeviceRouter,
                resolve_max_inflight,
            )

            if cfg.batch_impl == "dense":
                make_batched = pipeline.make_batch_analyzer
            elif cfg.batch_impl == "scan":
                make_batched = pipeline.make_scan_batch_analyzer
            else:
                raise ValueError(f"unknown batch_impl {cfg.batch_impl!r}")
            # egress_pack: the analyzer graph ends in the fused egress pack
            # stage (ops/pipeline.pack_analysis), so the completer's D2H
            # is ONE [B, P] uint8 fetch per dispatch and dispatcher
            # results are serving/egress.PackedResult rows
            batch_analyze = make_batched(
                model, img_size=cfg.model_img_size, geom_cfg=geom_cfg,
                forward=forward, pack=cfg.egress_pack,
            )
            router = None
            if self._serving_mesh is not None:
                from robotic_discovery_platform_tpu.parallel import (
                    mesh as mesh_lib,
                )

                # bind the model weights to each placement ONCE per engine
                # generation: per-chip replicas (round_robin) or one
                # mesh-replicated copy (sharded). Passing uncommitted
                # variables would re-transfer the whole weight tree on
                # every routed dispatch.
                chips = self.serving_chips
                analyzers = None
                sharded_analyzer = None
                if self.dispatch_mode == "round_robin":
                    analyzers = [
                        (lambda frames, depths, intr, scales, _v=v:
                         batch_analyze(_v, frames, depths, intr, scales))
                        for v in (
                            jax.device_put(variables, d)
                            for d in mesh_lib.device_ring(self._serving_mesh)
                        )
                    ]
                    # controller-enabled round_robin servers additionally
                    # bind the mesh-replicated layout when the geometry
                    # permits it, so the controller can flip to sharded
                    # dispatch online (one extra replicated weight copy)
                    if (self._controller_enabled
                            and not (chips & (chips - 1))
                            and cfg.max_batch >= chips
                            and cfg.max_batch % chips == 0):
                        v_repl = mesh_lib.shard_pytree(
                            self._serving_mesh, variables
                        )
                        sharded_analyzer = (
                            lambda frames, depths, intr, scales:
                            batch_analyze(v_repl, frames, depths, intr,
                                          scales)
                        )
                else:
                    v_repl = mesh_lib.shard_pytree(
                        self._serving_mesh, variables
                    )
                    analyzers = [
                        lambda frames, depths, intr, scales: batch_analyze(
                            v_repl, frames, depths, intr, scales
                        )
                    ]
                router = DeviceRouter(
                    self._serving_mesh, self.dispatch_mode, analyzers,
                    sharded_analyzer=sharded_analyzer,
                    breaker_failures=cfg.chip_breaker_failures,
                    breaker_reset_s=cfg.chip_breaker_reset_s,
                    on_health=self._on_chip_health,
                )
            dispatcher = BatchDispatcher(
                lambda frames, depths, intr, scales: batch_analyze(
                    variables, frames, depths, intr, scales
                ),
                window_ms=cfg.batch_window_ms,
                max_batch=cfg.max_batch,
                max_backlog=cfg.max_backlog,
                submit_timeout_s=cfg.submit_deadline_s,
                watchdog_interval_s=cfg.watchdog_interval_s,
                max_inflight=resolve_max_inflight(
                    cfg.max_inflight_dispatches
                ),
                router=router,
                admission=cfg.admission_policy,
                placer=self.placer,
                model_label=self.model_label,
                coef_analyzer_factory=coef_factory,
            )
            # a hot-reload builds a FRESH dispatcher for the new default
            # generation; the zoo's extra models (whose generations did
            # not move) re-bind onto it so their serving is uninterrupted
            existing_zoo = getattr(self, "zoo", None)
            if existing_zoo is not None:
                for entry in existing_zoo.extras():
                    if entry.batch_analyze is not None:
                        dispatcher.bind_model(
                            entry.name, entry.batch_analyze,
                            entry.per_chip_analyzers,
                            entry.sharded_analyzer,
                        )
        return Engine(analyze, variables, dispatcher, version)

    @staticmethod
    def _build_forward(model, variables, cfg: ServerConfig):
        """Pick the model-forward implementation per ServerConfig.model_forward
        ("auto" = Pallas-fused kernels on TPU, Flax/XLA otherwise)."""
        from robotic_discovery_platform_tpu.ops import pallas as pallas_ops

        mode = cfg.model_forward
        if mode == "flax" or (mode == "auto" and not pallas_ops.use_pallas()):
            return None
        if mode not in ("auto", "pallas"):
            raise ValueError(f"unknown model_forward {mode!r}")
        pnet = pallas_ops.make_pallas_unet(model, variables)
        log.info("serving with Pallas-fused U-Net forward")
        return lambda _variables, x: pnet(x)

    # -- model zoo -----------------------------------------------------------

    def _build_zoo_entries(self, default_version: int | None) -> None:
        """Load and bind every non-default zoo model: its own registry
        entry (alias-first, like the default), precision transform,
        analyzers bound onto the SHARED dispatcher, per-model drift
        monitor, and per-model SLO tracker. A model whose registry entry
        is missing is skipped with a warning -- the server serves what
        exists rather than refusing to boot (the zoo is additive)."""
        cfg = self.cfg
        self._model_slo: dict[str, slo_lib.SloTracker] = {}
        if len(self._zoo_names) > 1:
            slo_ms = slo_lib.resolve_slo_ms(cfg.slo_ms)
            if slo_ms is not None:
                # per-model burn for the default model too; the
                # aggregate tracker (self.slo, model="") keeps feeding
                # the controller and the fleet
                self._model_slo[self.model_label] = slo_lib.SloTracker(
                    slo_ms / 1e3, budget=cfg.slo_budget,
                    window=cfg.slo_window,
                    name=f"e2e/{self.model_label}",
                    burn_gauge=obs.SLO_BURN.labels(
                        objective="e2e", model=self.model_label),
                )
        for name in self._zoo_names[1:]:
            variant = variants_lib.VARIANTS[name]
            reg_name = variants_lib.registered_name(
                variant, cfg.model_name)
            try:
                alias = self._registry_store.get_alias(
                    reg_name, cfg.model_alias)
                version = (int(alias) if alias is not None else int(
                    self._registry_store.latest_version(
                        reg_name)["version"]))
                zmodel, zvariables = tracking.load_model(
                    f"models:/{reg_name}/{version}",
                    store=self._registry_store,
                )
            except Exception as exc:
                log.warning(
                    "zoo model %r (%s) unavailable (%s: %s); serving "
                    "without it", name, reg_name,
                    type(exc).__name__, exc,
                )
                continue
            try:
                entry = self._make_zoo_entry(name, variant, reg_name,
                                             zmodel, zvariables, version)
            except Exception:
                log.exception("zoo model %r failed to build; serving "
                              "without it", name)
                continue
            self.zoo.add(entry)
            log.info("zoo model %r: %s v%s (%s tier, %s head)",
                     name, reg_name, version, entry.precision,
                     variant.head)

    def _make_zoo_entry(self, name: str, variant, reg_name: str,
                        model, variables,
                        version: int | None) -> zoo_lib.ZooEntry:
        """One non-default zoo entry: mirror of the default engine build
        (precision transform, explicit weight staging, per-chip/sharded
        router bindings) against this model's own weights."""
        cfg, geom_cfg = self.cfg, self.geom_cfg
        pristine = (model, variables)
        model_q, variables_q, qreport = quant.apply_precision(
            model, variables, self.precision
        )
        if qreport is not None and qreport.get("layers"):
            log.info("int8-quantized %d conv kernels for zoo model %r",
                     qreport["layers"], name)
        if any(not isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(variables_q)):
            variables_q = jax.device_put(variables_q)
        # zoo extras always run the Flax/XLA forward: the Pallas-fused
        # net binds one model's weights at build time and has no
        # multi-model dispatch (same policy as serving meshes)
        analyze = pipeline.make_frame_analyzer(
            model_q, img_size=cfg.model_img_size, geom_cfg=geom_cfg,
        )
        batch_analyze = per_chip = sharded = None
        dispatcher = self._engine.dispatcher
        if dispatcher is not None:
            make_batched = (pipeline.make_batch_analyzer
                            if cfg.batch_impl == "dense"
                            else pipeline.make_scan_batch_analyzer)
            batched = make_batched(
                model_q, img_size=cfg.model_img_size, geom_cfg=geom_cfg,
                pack=cfg.egress_pack,
            )
            batch_analyze = (
                lambda frames, depths, intr, scales,
                       _b=batched, _v=variables_q:
                _b(_v, frames, depths, intr, scales)
            )
            if self._serving_mesh is not None:
                from robotic_discovery_platform_tpu.parallel import (
                    mesh as mesh_lib,
                )

                if self.dispatch_mode == "round_robin":
                    # per-(model, chip) committed weight replicas, like
                    # the default model's router bindings: an
                    # uncommitted tree would re-transfer per dispatch
                    per_chip = [
                        (lambda frames, depths, intr, scales,
                                _b=batched, _v=v:
                         _b(_v, frames, depths, intr, scales))
                        for v in (
                            jax.device_put(variables_q, d)
                            for d in mesh_lib.device_ring(
                                self._serving_mesh)
                        )
                    ]
                else:
                    v_repl = mesh_lib.shard_pytree(
                        self._serving_mesh, variables_q
                    )
                    sharded = (
                        lambda frames, depths, intr, scales,
                               _b=batched, _v=v_repl:
                        _b(_v, frames, depths, intr, scales)
                    )
            dispatcher.bind_model(name, batch_analyze, per_chip, sharded)
        drift = None
        if cfg.drift_enabled:
            reference = self._load_drift_profile(
                version, model_name=reg_name, allow_explicit=False)
            drift = profile_lib.DriftMonitor(
                reference=reference,
                window=cfg.drift_window,
                baseline_frames=cfg.drift_baseline_frames,
                score_every=cfg.drift_score_every,
                psi_threshold=cfg.drift_psi_threshold,
                sustain_s=cfg.drift_sustain_s,
                cooldown_s=cfg.drift_cooldown_s,
                generation=version,
                on_score=functools.partial(
                    self._on_model_drift_score, name),
                on_recommendation=functools.partial(
                    self._on_model_drift_recommendation, name),
            )
        slo_ms = slo_lib.resolve_slo_ms(cfg.slo_ms)
        slo_tracker = None
        if slo_ms is not None:
            slo_tracker = slo_lib.SloTracker(
                slo_ms / 1e3, budget=cfg.slo_budget,
                window=cfg.slo_window, name=f"e2e/{name}",
                burn_gauge=obs.SLO_BURN.labels(objective="e2e",
                                               model=name),
            )
            self._model_slo[name] = slo_tracker
        return zoo_lib.ZooEntry(
            name=name, variant=variant, analyze=analyze,
            variables=variables_q, version=version,
            precision=self.precision, pristine=pristine, drift=drift,
            slo=slo_tracker, batch_analyze=batch_analyze,
            per_chip_analyzers=per_chip, sharded_analyzer=sharded,
        )

    def _resolve_model(self, name: str) -> tuple[str, Any]:
        """Map one wire ``model`` field to (metric label, zoo entry).
        "" and the default name both resolve to (default label, None) --
        None meaning "use the legacy engine path", which is how the
        default model stays byte-for-byte pre-zoo. Unknown names raise
        :class:`zoo_lib.UnknownModelError` (a per-frame error)."""
        if not name or name == self.model_label:
            return self.model_label, None
        entry = self.zoo.get(name)
        if entry is None:
            raise zoo_lib.UnknownModelError(
                f"model {name!r} is not in this server's zoo "
                f"({', '.join(self.zoo.names())})"
            )
        return name, entry

    def zoo_debug(self) -> dict:
        """The ``GET /debug/zoo`` payload: roster, per-model versions /
        heads / frame counts, the placer's live placement + rate
        correlations, and the (model, placement, bucket) warm set."""
        with self._streams_cond:
            frames = dict(self._model_frames)
        models = {}
        for n in self.zoo.names():
            e = self.zoo.get(n)
            models[n] = {
                "version": (self._engine.version
                            if n == self.model_label else e.version),
                "head": e.variant.head,
                "registered_name": variants_lib.registered_name(
                    e.variant, self.cfg.model_name),
                "precision": e.precision,
                "frames": frames.get(n, 0),
                "parity": e.parity if n != self.model_label else self.parity,
            }
        dispatcher = self._engine.dispatcher
        return {
            "enabled": len(self._zoo_names) > 1,
            "default": self.model_label,
            "models": models,
            "placement": (self.placer.snapshot()
                          if self.placer is not None else None),
            "warmed": (sorted(
                [list(map(str, k)) for k in dispatcher.warmed])
                if dispatcher is not None else []),
        }

    # -- per-frame ----------------------------------------------------------

    def _decode(self, request: vision_pb2.AnalysisRequest):
        """One inline decode through the ingest core (RGB out; the
        BGR->RGB conversion now lives in decode, one cv2 pass)."""
        rgb, depth, _ = self.ingest.decode(request)
        return rgb, depth

    def _analyze_frame(self, rgb: np.ndarray, depth: np.ndarray,
                       timer: StageTimer | None = None,
                       timeout_s: float | None = None,
                       model: str = "",
                       mask_format: int = 0,
                       active=None):
        inject(fault_sites.SERVING_ANALYZE)
        timer = timer or StageTimer()
        t_entry = time.monotonic()
        # split-decode frames carry coefficients, not pixels: the device
        # decodes them fused ahead of the analyzer (CoefficientFrame's
        # .shape property keeps every geometry read below uniform)
        coef = isinstance(rgb, entropy_lib.CoefficientFrame)
        h, w = rgb.shape[:2]
        # per-stream geometry cache: identical intrinsics content never
        # re-converts to float32 (and, on the direct path, never
        # re-stages) -- the per-frame np.asarray at the old call sites
        # is one dict hit now
        geom = self._geom_cache.lookup(self.intrinsics, w, h,
                                       self.depth_scale)
        # zoo resolution: "" / the default name keep the legacy engine
        # path verbatim (entry None); an unknown name is a per-frame
        # error raised before any device work
        _, entry = self._resolve_model(model)
        # ONE read of the engine per frame: analyze/variables/dispatcher
        # swap together, so a concurrent hot-reload cannot mix generations
        eng = self._engine
        with timer.stage("device"):
            if eng.dispatcher is not None:
                # coalesce with co-arriving frames of the SAME model from
                # other streams; the submit carries the caller's
                # remaining deadline so a cancelled/expired client frees
                # this thread instead of parking it on an unbounded wait
                submit = (eng.dispatcher.submit_coef if coef
                          else eng.dispatcher.submit)
                out = submit(
                    rgb, depth, geom.k_f32, self.depth_scale,
                    timeout_s=timeout_s,
                    model=entry.name if entry is not None else "",
                )
            elif coef:
                out = self._analyze_coef_direct(rgb, depth, geom, entry)
            else:
                # explicit H2D for the frame inputs: the jitted entry runs
                # under the transfer guard, and relying on implicit
                # per-call transfers is exactly the host-path tax the
                # guard exists to flag (device_put is async -- it does
                # not block the handler thread). Intrinsics + depth scale
                # ride the geometry cache's committed copies: staged once
                # per distinct content, not once per frame.
                k_dev, scale_dev = geom.staged()
                frames_dev = jax.device_put((rgb, depth))
                if entry is not None:
                    out = entry.analyze(entry.variables, *frames_dev,
                                        k_dev, scale_dev)
                else:
                    out = eng.analyze(eng.variables, *frames_dev, k_dev,
                                      scale_dev)
            if isinstance(out, jax.Array):
                # the direct coefficient path under packing hands back a
                # bare [P] uint8 payload row (its own single fetch)
                out = egress_lib.PackedResult(np.asarray(out))
            packed = out if isinstance(out, egress_lib.PackedResult) else None
            if packed is not None:
                # packed egress: the scalars ride the f32 sidecar of the
                # completer's single per-dispatch fetch -- bitwise the
                # values the legacy per-leaf fetches carried; the
                # full-resolution mask only unpacks when something
                # actually needs pixels
                mask = None
                coverage, mean_k, max_k, valid, margin = packed.scalars()
                spline = (packed.spline() if not mask_format
                          else np.zeros((0, 3), np.float32))
            else:
                # host fetch of the fused result (direct pixel path)
                mask = np.asarray(out.mask)
                coverage = float(out.mask_coverage)
                prof = out.profile
                valid = bool(prof.valid)
                mean_k = float(prof.mean_curvature) if valid else 0.0
                max_k = float(prof.max_curvature) if valid else 0.0
                spline = (np.asarray(prof.spline_points) if valid
                          else np.zeros((0, 3)))
                margin = float(np.asarray(out.confidence_margin))
            # drift signal the frame already paid for: the depth-validity
            # fraction is one host-side count over the raw depth frame
            depth_valid = float(np.count_nonzero(depth)) / max(depth.size, 1)
        try:
            spline_wire = b""
            if mask_format:
                # packed wire formats skip the per-point Point3D loop:
                # the spline rides packed_spline as f32 LE triples
                spline_wire = (packed.spline_wire() if packed is not None
                               else np.ascontiguousarray(
                                   spline, dtype="<f4").tobytes())
                spline = np.zeros((0, 3), np.float32)
            # bugfix (ISSUE 20): a frame whose stream is already cancelled
            # or whose deadline expired while it rode the device must not
            # pay encode cost (PNG + the mask*255 full-frame allocation)
            # for an answer nobody will receive
            dead = ((active is not None and not active())
                    or (timeout_s is not None
                        and time.monotonic() - t_entry >= timeout_s))
            with timer.stage("encode"):
                if dead:
                    mask_bytes = b""
                elif mask_format == egress_lib.MASK_FORMAT_BITS:
                    # zero-transform: the wire payload IS the packed
                    # staging rows behind a small header
                    bits = (packed.mask_bits if packed is not None
                            else np.packbits(mask, axis=-1))
                    shape = ((packed.h, packed.w) if packed is not None
                             else mask.shape[:2])
                    mask_bytes = self.egress.encode(
                        "bits", bits=bits, shape=shape, timeout_s=timeout_s
                    )
                elif mask_format == egress_lib.MASK_FORMAT_RLE:
                    mask_bytes = self.egress.encode(
                        "rle", mask=mask,
                        bits=packed.mask_bits if packed is not None else None,
                        shape=((packed.h, packed.w) if packed is not None
                               else mask.shape[:2]),
                        timeout_s=timeout_s,
                    )
                else:
                    # legacy PNG (and any unknown mask_format): the
                    # historical wire bytes exactly
                    m = packed.unpack_mask() if packed is not None else mask
                    mask_bytes = self.egress.encode(
                        "png", mask=m, timeout_s=timeout_s
                    )
            anomaly = None
            if entry is not None and entry.variant.head == "anomaly":
                # the aux head's product: defect/anomaly score off the
                # confidence margin the fused graph already computed
                anomaly = variants_lib.anomaly_score(margin)
                obs.MODEL_ANOMALY_SCORE.observe(anomaly)
            res = _FrameResult(mean_k, max_k, spline, mask_bytes,
                               coverage, valid, margin, depth_valid,
                               anomaly, spline_wire)
            if (entry is None and not coef
                    and self._shadow_hook is not None):
                # only default-model frames mirror to a rollout shadow:
                # the shadow diff gates the DEFAULT generation's
                # replacement -- and only pixel frames can (a split-decode
                # frame's RGB never materializes on the host, which is its
                # point). Checked here so a packed frame only unpacks its
                # mask when a shadow tap is actually installed.
                if mask is None:
                    mask = packed.unpack_mask()
                self._mirror_shadow(rgb, depth, geom.k_f32, mask, res)
            return res
        finally:
            # hand the packed row's share of the pooled staging buffer
            # back to the dispatcher (everything needed was copied out)
            if packed is not None:
                packed.release()

    def _analyze_coef_direct(self, frame, depth, geom, entry):
        """Direct-path (unbatched) ride for a coefficient-lane frame: the
        batch-1 decode+analyze graph, lazily built + memoized per
        (h, w, subsampling) for the current engine generation, with the
        leading batch axis squeezed off the result tree."""
        if entry is not None:
            raise ValueError(
                "the coefficient lane serves the default model only; "
                f"model {entry.name!r} frames must use pixel formats"
            )
        key = (frame.height, frame.width, frame.subsampling)
        with self._coef_direct_lock:
            analyze = self._coef_direct.get(key)
        if analyze is None:
            analyze = self._coef_factory_fn(
                "", frame.height, frame.width, frame.subsampling
            )
            with self._coef_direct_lock:
                analyze = self._coef_direct.setdefault(key, analyze)
        staged = pipeline.stage_coef_batch(
            frame.y[None], frame.cb[None], frame.cr[None],
            frame.qy[None], frame.qc[None], depth[None],
            geom.k_f32[None],
            np.asarray([self.depth_scale], np.float32),
        )
        out = analyze(*staged)
        return jax.tree.map(lambda a: a[0], out)

    def _observe_drift(self, res: _FrameResult,
                       entry=None) -> None:
        """Feed one analyzed frame's signals to its model's drift
        monitor and the confidence-margin histogram -- pure host-side
        Python, after the response is already built."""
        obs.MODEL_CONFIDENCE_MARGIN.observe(res.confidence_margin)
        monitor = self.drift if entry is None else entry.drift
        if monitor is None:
            return
        monitor.observe_frame({
            "mask_coverage": res.coverage,
            "mean_curvature": res.mean_k if res.valid else math.nan,
            "max_curvature": res.max_k if res.valid else math.nan,
            "depth_valid_fraction": res.depth_valid_fraction,
            "confidence_margin": res.confidence_margin,
        })

    def _enter_stream(self) -> bool:
        with self._streams_cond:
            if self._draining or self._closed:
                return False
            if self._refusing_streams:
                # brownout rung 3 duty-cycles: every other new stream is
                # refused. Refusing ALL streams would starve the SLO
                # signal (refused streams never observe a frame) and
                # deadlock the ladder at its top rung; half keeps burn
                # flowing so the symmetric exit stays reachable.
                self._brownout_tick += 1
                if self._brownout_tick % 2:
                    return False
            self._active_streams += 1
        obs.INFLIGHT_STREAMS.inc()
        return True

    def _exit_stream(self) -> None:
        obs.INFLIGHT_STREAMS.dec()
        with self._streams_cond:
            self._active_streams -= 1
            self._streams_cond.notify_all()

    @property
    def active_streams(self) -> int:
        with self._streams_cond:
            return self._active_streams

    @property
    def is_draining(self) -> bool:
        with self._streams_cond:
            return self._draining

    def set_draining(self, draining: bool) -> None:
        """Rollout drain control: flip ONLY the draining flag. Unlike
        :meth:`drain` (the shutdown path), health stays SERVING -- the
        fleet front-end reads ``draining`` off the stats RPC and stops
        placing NEW streams here while in-flight streams finish normally
        (graceful drain, not failover), and ``set_draining(False)``
        reverses it (rollback / rejoin). New direct-dial streams are
        refused UNAVAILABLE meanwhile, exactly like a shutdown drain.
        A closed service cannot be un-drained."""
        draining = bool(draining)
        with self._streams_cond:
            if self._closed and not draining:
                return
            changed = self._draining != draining
            self._draining = draining
            self._streams_cond.notify_all()
        if changed:
            log.info(
                "replica %s: %s new streams (health stays up)",
                "draining" if draining else "un-draining",
                "refusing" if draining else "accepting",
            )

    def set_shadow(self, hook) -> None:
        """Install (or clear with ``None``) the rollout shadow tap: a
        callable receiving one :class:`~robotic_discovery_platform_tpu.
        serving.rollout.ShadowSample` per analyzed frame. The hook is
        invoked on the handler thread AFTER the response is computed and
        must never block (the rollout ShadowRunner's hook samples and
        ``put_nowait``s)."""
        self._shadow_hook = hook

    def _mirror_shadow(self, rgb, depth, k, mask,
                       res: _FrameResult) -> None:
        """One attribute read per frame when no tap is installed; with a
        tap, hand the frame's inputs + this generation's outputs to the
        rollout shadow. A hook failure never fails the frame."""
        hook = self._shadow_hook
        if hook is None:
            return
        try:
            hook(rollout_lib.ShadowSample(
                rgb=rgb, depth=depth, k=np.asarray(k),
                depth_scale=self.depth_scale, mask=mask,
                coverage=res.coverage, mean_curvature=res.mean_k,
                max_curvature=res.max_k, valid=res.valid,
                confidence_margin=res.confidence_margin,
                depth_valid_fraction=res.depth_valid_fraction,
            ))
        except Exception:  # noqa: BLE001 - shadow must not fail serving
            log.exception("shadow mirror hook failed; frame served "
                          "normally")

    def replica_stats(self) -> dict:
        """The lightweight per-replica stats payload the fleet front-end
        scrapes over gRPC (serving/fleet.add_replica_stats_to_server):
        in-flight streams + error-budget burn feed least-loaded placement
        and the FleetController's weighted ring; the rest is diagnostics
        a fleet dashboard wants next to them."""
        eng = self._engine
        router = eng.dispatcher.router if eng.dispatcher is not None else None
        # version + drift reference generation as ONE consistent pair
        # (read under the reload lock): a scrape racing a promotion sees
        # either the old pair or the new pair, never a mix
        version, drift_generation = self.version_and_reference()
        host, role = trace.identity()
        with self._streams_cond:
            model_frames = dict(self._model_frames)
        # per-model demand next to the aggregate: the capacity planner's
        # per-model rate inputs (ROADMAP) and the fleet dashboard's
        # multi-tenant view ride this block
        rates = self.placer.rates() if self.placer is not None else {}
        models = {
            name: {
                "frames": model_frames.get(name, 0),
                "rate": round(rates.get(name, 0.0), 3),
            }
            for name in self.zoo.names()
        }
        return {
            "inflight_streams": self.active_streams,
            "frames_total": self._frames_total,
            "models": models,
            "burn": self.slo.burn if self.slo is not None else 0.0,
            "slo_ms": self.cfg.slo_ms,
            "chips": self.serving_chips,
            "quarantined_chips": (len(router.quarantined)
                                  if router is not None else 0),
            "version": version,
            "drift_generation": drift_generation,
            "draining": self.is_draining,
            "refusing_streams": self._refusing_streams,
            "pid": os.getpid(),
            # observability-plane discovery: the fleet front-end scrapes
            # this replica's /metrics + /debug/spans for federation and
            # cross-host trace stitching at the advertised port (0 = no
            # metrics endpoint), attributing them to host/role identity
            "metrics_port": (self.metrics_server.port
                             if self.metrics_server is not None else 0),
            "host": host,
            "role": role,
        }

    def AnalyzeActuatorPerformance(self, request_iterator, context):
        if not self._enter_stream():
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          "server is draining or in overload brownout; "
                          "retry against another replica")
        # Adopt the client's trace: the stream runs inside a span whose
        # trace ID came over the wire (traceparent metadata), so client-
        # and server-side log lines for the same stream carry the same
        # [trace=...] stamp. No metadata -> a fresh server-side trace.
        # (Setting the contextvar inside this generator deliberately leaks
        # to the handler thread between yields: gRPC drives one stream's
        # generator from one thread, and log lines emitted while it runs
        # should carry the stream's trace.)
        remote = trace.from_metadata(context.invocation_metadata())
        try:
            yield from self._stream_frames(request_iterator, context, remote)
        finally:
            self._exit_stream()

    def _stream_frames(self, request_iterator, context, remote):
        with trace.span("serving.stream", parent=remote):
            log.info(
                "analysis stream opened (%s trace)",
                "client" if remote is not None else "local",
            )
            # per-stream stage breakdown (decode / device / encode),
            # summarized at stream end so proc_time_ms has an explanation
            # in the logs -- and routed sample-by-sample into the
            # rdp_stage_latency_seconds histogram (ONE timing system: the
            # exported histogram and the log summary observe the same
            # measurements)
            def _observe_stage(stage: str, dt: float) -> None:
                # the host-split decode AND encode samples are observed by
                # the ingest/egress pools themselves (actual work wherever
                # it ran); the handler-side numbers here are just the WAIT
                # when a pool ran the stage off-thread
                obs.STAGE_LATENCY.labels(stage=stage).observe(dt)
                obs.STAGE_LATENCY_SUMMARY.labels(stage=stage).observe(dt)

            timer = StageTimer(observer=_observe_stage)
            # ingest iterator: cancellation + client-deadline checks, and
            # decode itself, live in serving/ingest.py now. With
            # decode_workers = 0 this is the historical inline
            # read-check-decode loop, bitwise; with workers it reads
            # ahead so frame k+1 decodes while frame k rides the device.
            frames = self.ingest.iter_decoded(
                request_iterator,
                active=context.is_active,
                time_remaining=context.time_remaining,
            )
            for inf in frames:
                remaining = inf.time_remaining
                t0 = time.perf_counter()
                label = self.model_label
                entry = None
                try:
                    # handler-side decode cost (inline: the decode itself;
                    # pooled: the wait, ~0 once read-ahead is primed)
                    timer.observe("decode", inf.wait_s)
                    if inf.error is not None:
                        raise inf.error
                    label, entry = self._resolve_model(inf.model)
                    res = self._analyze_frame(inf.rgb, inf.depth, timer,
                                              timeout_s=remaining,
                                              model=inf.model,
                                              mask_format=inf.mask_format,
                                              active=context.is_active)
                    status = ("OK" if res.valid
                              else "DEGRADED: insufficient geometry")
                    if res.anomaly is not None:
                        # the aux head's verdict rides the status text:
                        # wire-compatible (clients key on OK/DEGRADED/
                        # ERROR prefixes), and only ever present on
                        # frames that explicitly asked for this model
                        status += f" anomaly={res.anomaly:.4f}"
                    # packed wire formats carry the spline as
                    # packed_spline bytes and res.spline is empty (the
                    # per-point Point3D loop runs zero times); on the
                    # legacy path spline_wire is b"" and serializes to
                    # zero bytes -- pre-PR responses stay bitwise
                    response = vision_pb2.AnalysisResponse(
                        mean_curvature=res.mean_k,
                        max_curvature=res.max_k,
                        spline_points=[
                            vision_pb2.Point3D(x=float(p[0]), y=float(p[1]), z=float(p[2]))
                            for p in res.spline
                        ],
                        status=status,
                        mask=res.mask_png,
                        mask_coverage=res.coverage,
                        packed_spline=res.spline_wire,
                    )
                    self.metrics.append(res.mean_k, res.max_k, res.coverage)
                    self._observe_drift(res, entry)
                    status_label = "ok" if res.valid else "degraded"
                except zoo_lib.UnknownModelError as exc:
                    # a typo'd model name is a bad frame, not a dead
                    # stream: per-frame error, bounded metric
                    # cardinality (requested names never become labels)
                    label = "unknown"
                    response = vision_pb2.AnalysisResponse(
                        status=f"ERROR: UnknownModel: {exc} "
                               f"[trace={trace.current_trace_id() or '-'}]"
                    )
                    status_label = "error"
                except OverloadedError as exc:
                    # load shedding is a STREAM-level, retryable condition:
                    # surface the standard backpressure status instead of a
                    # per-frame error payload the client cannot distinguish
                    # from a bad frame. The trace ID rides the details so
                    # the client-side failure joins its /debug/spans
                    # timeline; a shed frame also burned SLO budget.
                    obs.FRAMES.labels(status="shed", model=label).inc()
                    if self.slo is not None:
                        self.slo.observe(float("inf"), ok=False)
                    mslo = self._model_slo.get(label)
                    if mslo is not None:
                        mslo.observe(float("inf"), ok=False)
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"{exc} [trace={trace.current_trace_id() or '-'}]",
                    )
                except DeadlineExceeded as exc:
                    # per-submit deadline (client deadline or
                    # cfg.submit_deadline_s) ran out while the frame was
                    # queued/processing: report per-frame and keep the
                    # stream alive -- the handler thread is free again
                    log.warning("frame missed its deadline: %s", exc)
                    response = vision_pb2.AnalysisResponse(
                        status=f"ERROR: DeadlineExceeded: {exc} "
                               f"[trace={trace.current_trace_id() or '-'}]"
                    )
                    status_label = "deadline"
                except Exception as exc:  # keep the stream alive per frame
                    log.exception("analysis error")
                    # trace ID in the wire status AND a pinned recorder
                    # event: the client-side failure and the server-side
                    # /debug/spans evidence join on the same 32-hex ID
                    trace_id = trace.current_trace_id()
                    recorder_lib.RECORDER.record_event(
                        "serving.frame_error", trace_id=trace_id,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                    response = vision_pb2.AnalysisResponse(
                        status=f"ERROR: {type(exc).__name__}: {exc} "
                               f"[trace={trace_id or '-'}]"
                    )
                    status_label = "error"
                total_s = time.perf_counter() - t0
                response.proc_time_ms = total_s * 1e3
                with self._streams_cond:
                    self._frames_total += 1
                    self._model_frames[label] = (
                        self._model_frames.get(label, 0) + 1)
                obs.FRAMES.labels(status=status_label, model=label).inc()
                obs.STAGE_LATENCY.labels(stage="total").observe(total_s)
                obs.STAGE_LATENCY_SUMMARY.labels(stage="total").observe(
                    total_s)
                obs.FRAME_LATENCY_SUMMARY.observe(total_s)
                frame_ok = status_label in ("ok", "degraded")
                if self.slo is not None:
                    self.slo.observe(total_s, ok=frame_ok)
                mslo = self._model_slo.get(label)
                if mslo is not None:
                    # per-model burn next to the aggregate: which tenant
                    # is burning its budget is the question multi-model
                    # dashboards (and the capacity planner) ask
                    mslo.observe(total_s, ok=frame_ok)
                yield response
            self.metrics.flush()
            if timer.totals:
                log.info("stream stage breakdown: %s", timer.summary())

    # -- hot-reload ---------------------------------------------------------

    def _resolve_version(self) -> int | None:
        """Registry resolution under the per-service circuit breaker.

        Closed: failures log a warning and count toward the threshold.
        Open: the poll is skipped entirely -- no network touch, no log
        line, serving keeps its current engine; the breaker logs the
        open/half-open/closed transitions exactly once each."""
        try:
            return self.registry_breaker.call(
                lambda: resolve_serving_version(
                    self.cfg, self._registry_store, raise_on_error=True
                )
            )
        except CircuitOpenError:
            return None
        except Exception as exc:
            log.warning(
                "registry %s unreachable/empty (%s: %s); serving keeps "
                "its current model (breaker: %d/%d failures)",
                self.cfg.tracking_uri, type(exc).__name__, exc,
                self.registry_breaker.failure_count,
                self.registry_breaker.failure_threshold,
            )
            return None

    def start_reloader(self) -> None:
        """Poll the registry every ``cfg.reload_poll_s`` seconds; when the
        staging alias (or latest version) moves, build + warm the new
        model OFF the serving path and atomically swap it in -- promotion
        takes effect on a RUNNING server, closing the reference's
        implicit-handoff gap (SURVEY.md section 3.4)."""
        if self.cfg.reload_poll_s <= 0 or self._reload_thread is not None:
            return
        self._reload_stop = threading.Event()

        def loop():
            while not self._reload_stop.wait(self.cfg.reload_poll_s):
                try:
                    self.maybe_reload()
                except Exception:
                    log.exception("model hot-reload failed; keeping current")

        self._reload_thread = threading.Thread(
            target=loop, name="model-reloader", daemon=True
        )
        self._reload_thread.start()

    def maybe_reload(self) -> bool:
        """One reload check; returns True when a new version was swapped in.

        The expensive phase (registry resolve, model load, engine build,
        XLA warm) runs OUTSIDE ``_reload_lock``, guarded by a busy flag so
        at most one reload is ever in flight; the lock is held only for the
        engine swap. close() and warmup() therefore block at most for a
        swap, never for a compile (review finding: a SIGTERM mid-reload
        must not stall shutdown for a full warm)."""
        with self._reload_lock:
            if self._closed or self._reload_busy:
                return False
            self._reload_busy = True
            current_version = self._engine.version
        engine = None
        try:
            version = self._resolve_version()
            if version is None or version == current_version:
                return False
            # scoped store: this runs on the poller thread (see
            # resolve_serving_version's docstring)
            model, variables = tracking.load_model(
                f"models:/{self.cfg.model_name}/{version}",
                store=self._registry_store,
            )
            engine = self._make_engine(model, variables, version)
            # the new generation's drift reference is RESOLVED here
            # (registry I/O, off-lock) but ADOPTED inside the swap's
            # critical section below: engine generation and drift
            # reference move atomically, so a concurrent scrape never
            # pairs new weights with the old reference
            drift_reference = (self._load_drift_profile(version)
                               if self.drift is not None else None)
            if self._closed:
                return False  # skip the warm entirely; finally cleans up
            # compile + run every graph live frames will hit, off the
            # serving path, so in-flight streams never pay the new
            # generation's XLA compilation -- including the dispatcher's
            # per-bucket batched graphs when micro-batching is on.
            # Snapshot-and-recheck: a concurrent warmup() can record a NEW
            # camera shape while we warm for the old one (or for none);
            # the swap below only proceeds once the engine is warm for the
            # shape that is current at swap time, else we re-warm.
            old = None
            warmed_shape = object()  # sentinel: warmed for nothing yet
            while True:
                shape = self._warm_shape
                if shape is not None and shape != warmed_shape:
                    self._warm_engine(engine, shape)
                warmed_shape = shape
                with self._reload_lock:
                    if self._closed:
                        return False  # never swap into a closed service
                    if (self._warm_shape is not None
                            and self._warm_shape != warmed_shape):
                        continue  # warmup() raced us; warm the new shape
                    old, self._engine = self._engine, engine
                    engine = None  # went live; finally must not stop it
                    # same critical section as the engine swap: the new
                    # generation's reference (artifact or re-baseline)
                    # goes live with its weights, never after them
                    self._apply_drift_reference(version, drift_reference)
                    if old.dispatcher is not None:
                        # Grace-delayed stop: a frame thread that read the
                        # OLD engine just before the swap may still be
                        # about to submit(); give in-flight frames ample
                        # time to finish on the old dispatcher before
                        # tearing it down (stop() itself is drain-safe, so
                        # a straggler past the grace window gets a
                        # per-frame error, not a hang -- and per-frame
                        # errors don't drop the stream).
                        t = threading.Timer(
                            self.cfg.reload_grace_s, old.dispatcher.stop
                        )
                        t.daemon = True
                        self._grace_stops = [
                            (tm, d) for tm, d in self._grace_stops
                            if tm.is_alive()
                        ]
                        self._grace_stops.append((t, old.dispatcher))
                        t.start()
                    break
            log.info("hot-reloaded model: version %s -> %s",
                     old.version, version)
            return True
        finally:
            # never went live (error, closed mid-build/-warm, or the swap
            # was refused): tear down its dispatcher (whose collector
            # thread started in _make_engine) so a repeatedly-failing
            # promotion can't leak one thread plus its compiled graphs per
            # poll tick
            if engine is not None and engine.dispatcher is not None:
                engine.dispatcher.stop()
            with self._reload_lock:
                self._reload_busy = False

    def _warm_engine(self, engine: Engine,
                     shape: tuple[int, int] | None = None) -> None:
        """Pre-compile the graphs live frames will actually dispatch to on
        ``engine``: the batched per-bucket graphs when it carries a
        dispatcher (the path every frame takes then), the single-frame
        analyze otherwise. ``shape`` pins the camera (w, h) explicitly
        (reload's snapshot-and-recheck needs that); defaults to the shape
        warmup() recorded, a no-op when there is none yet."""
        shape = shape if shape is not None else self._warm_shape
        if shape is None:
            return
        w, h = shape
        k = (self.intrinsics if self.intrinsics is not None
             else _default_intrinsics(w, h))
        if engine.dispatcher is None:
            engine.analyze(
                engine.variables,
                np.zeros((h, w, 3), np.uint8),
                np.zeros((h, w), np.uint16),
                np.asarray(k, np.float32),
                np.float32(self.depth_scale),
            )
            return
        # the dispatcher pads each dispatch to min(next_pow2(n), max_batch)
        # -- with a sharded router the floor rises to the chip count -- so
        # the reachable bucket sizes are bucket_for() over the powers of
        # two below max_batch plus max_batch itself (the top bucket even
        # when it is not a power of two). warm() compiles each bucket on
        # EVERY routed placement, so a load burst's first dispatch to any
        # chip is already compiled.
        dispatcher = engine.dispatcher
        sizes, b = set(), 1
        while b < self.cfg.max_batch:
            sizes.add(dispatcher.bucket_for(b))
            b *= 2
        sizes.add(dispatcher.bucket_for(self.cfg.max_batch))
        for b in sorted(sizes):
            dispatcher.warm(
                np.zeros((b, h, w, 3), np.uint8),
                np.zeros((b, h, w), np.uint16),
                np.repeat(np.asarray(k, np.float32)[None], b, 0),
                np.full((b,), self.depth_scale, np.float32),
            )

    def warmup(self, width: int, height: int) -> None:
        """Pre-compile the fused graph for a camera geometry so the first
        real frame does not pay XLA compilation. The synthetic warm frame
        pair is built (and image-roundtripped) once per (width, height)
        per process -- every later warmup()/hot-reload warm for the same
        camera reuses it instead of re-encoding identical bytes."""
        self._warm_shape = (width, height)
        color, depth = _warm_frames(width, height)
        # pre-compile every graph a load burst could hit (single-frame or
        # per-bucket batched -- shared with the hot-reload warm) BEFORE
        # exercising the real per-frame path: the exercise frame's
        # dispatch ride feeds the admission service-time estimate, and a
        # ride that pays XLA compilation would poison it (every early
        # deadline would look unmeetable). Under the reload lock:
        # otherwise a poll tick that read _warm_shape as None could swap
        # in a never-warmed engine while we warm the old one.
        with self._reload_lock:
            self._warm_engine(self._engine)
        self._analyze_frame(color, depth)
        # CAPPED zoo warm (lazy elsewhere): each extra model pre-compiles
        # zoo_eager_warm home placements for the single-frame bucket;
        # every other (model, chip, bucket) combo compiles on its first
        # dispatch -- an M-model zoo must not multiply startup by
        # M x chips x buckets
        self._warm_zoo(width, height)
        # bf16/int8 tiers must PROVE parity against the f32 goldens before
        # readiness ever flips -- a quantized engine that fails its gate
        # never serves a frame (per zoo model: each entry gates against
        # its OWN pristine f32 pair)
        self._parity_gate(width, height)
        if self.ingest.onchip:
            # on-chip split decode: every baseline JPEG this server
            # admits rides the coefficient lane, so readiness must also
            # imply THOSE graphs are compiled -- otherwise the first
            # live burst pays the fused decode+analyze compilation
            # inside its frame deadlines
            self.warmup_coef(width, height)
        # readiness flips ONLY here: a probe sees SERVING once the first
        # real frame path has compiled and run, never before
        self.mark_ready()
        log.info("warmed up %dx%d analyzer on %s", width, height,
                 jax.default_backend())

    def warmup_coef(self, width: int, height: int,
                    subsampling: str = "420") -> None:
        """Pre-compile the coefficient-lane (``format = 2``) graphs for a
        camera geometry: the direct single-frame decode+analyze when the
        server has no dispatcher, otherwise every reachable bucket via
        ``warm_coef`` (the same bucket sweep ``_warm_engine`` runs for
        the pixel lane). ``warmup()`` calls this automatically when the
        server itself runs with on-chip decode enabled; benches and
        deployments whose CLIENTS ship ``format = 2`` against a
        pixel-decode server call it explicitly before load arrives."""
        import cv2

        color, depth = _warm_frames(width, height)
        sf = {
            "444": cv2.IMWRITE_JPEG_SAMPLING_FACTOR_444,
            "420": cv2.IMWRITE_JPEG_SAMPLING_FACTOR_420,
            "422": cv2.IMWRITE_JPEG_SAMPLING_FACTOR_422,
        }[subsampling]
        ok, jpg = cv2.imencode(
            ".jpg", color[..., ::-1],
            [int(cv2.IMWRITE_JPEG_SAMPLING_FACTOR), int(sf)],
        )
        if not ok:
            raise ValueError("warm-up coefficient encode failed")
        cf = entropy_lib.parse_jpeg(jpg.tobytes())
        dispatcher = self._engine.dispatcher
        if dispatcher is None:
            # direct (unbatched) path: exercising one coefficient frame
            # memoizes its decode+analyze graph in _coef_direct
            self._analyze_frame(cf, depth)
            return
        k = np.asarray(
            self.intrinsics if self.intrinsics is not None
            else _default_intrinsics(width, height), np.float32,
        )
        sizes, b = set(), 1
        while b < self.cfg.max_batch:
            sizes.add(dispatcher.bucket_for(b))
            b *= 2
        sizes.add(dispatcher.bucket_for(self.cfg.max_batch))
        for b in sorted(sizes):
            dispatcher.warm_coef(
                cf,
                np.zeros((b, height, width), np.uint16),
                np.repeat(k[None], b, 0),
                np.full((b,), self.depth_scale, np.float32),
            )

    def _warm_zoo(self, width: int, height: int) -> None:
        """Capped eager warm for the non-default zoo entries."""
        if len(self.zoo) <= 1:
            return
        color, depth = _warm_frames(width, height)
        k = np.asarray(
            self.intrinsics if self.intrinsics is not None
            else _default_intrinsics(width, height), np.float32,
        )
        dispatcher = self._engine.dispatcher
        full = self.cfg.zoo_eager_warm < 0
        for entry in self.zoo.extras():
            if dispatcher is None:
                entry.analyze(
                    entry.variables, color, depth, k,
                    np.float32(self.depth_scale),
                )
                continue
            if full:
                # zoo_eager_warm < 0: the pre-zoo full eager warm per
                # model -- every reachable bucket on every placement
                # (benchmarks measuring steady-state multiplexing, and
                # deployments that prefer slow boots over first-burst
                # compile stalls)
                sizes, b = set(), 1
                while b < self.cfg.max_batch:
                    sizes.add(dispatcher.bucket_for(b))
                    b *= 2
                sizes.add(dispatcher.bucket_for(self.cfg.max_batch))
            else:
                sizes = {dispatcher.bucket_for(1)}
            home: list[int] | None = None
            if (not full and self.placer is not None
                    and self.serving_chips > 1):
                cap = max(1, int(self.cfg.zoo_eager_warm))
                home = list(self.placer.chips_for(entry.name)[:cap])
            for b in sorted(sizes):
                dispatcher.warm(
                    np.repeat(color[None], b, 0),
                    np.repeat(depth[None], b, 0),
                    np.repeat(k[None], b, 0),
                    np.full((b,), self.depth_scale, np.float32),
                    model=entry.name, chips=home,
                )

    def _parity_gate(self, width: int, height: int) -> dict | None:
        """Warm-up parity check for the reduced-precision tiers: run the
        golden synthetic frames through BOTH an f32 reference analyzer
        (built from each generation's pristine variables) and the live
        engine path (dispatcher when batching, single-frame analyze
        otherwise), publish the rdp_quant_parity_* gauges per zoo model,
        and refuse to come up when the thresholds are breached. No-op at
        f32. Every zoo entry gates against its OWN goldens -- one
        model's quantization error can never hide behind another's."""
        if self.precision == "f32":
            return None
        eng = self._engine
        report = self._parity_gate_for(
            self.model_label, self._pristine,
            got_path=(None if eng.dispatcher is not None else
                      (eng.analyze, eng.variables)),
            submit_model="", width=width, height=height,
        )
        self.parity = report
        for entry in self.zoo.extras():
            entry.parity = self._parity_gate_for(
                entry.name, entry.pristine,
                got_path=(None if eng.dispatcher is not None else
                          (entry.analyze, entry.variables)),
                submit_model=entry.name, width=width, height=height,
            )
        return report

    def _parity_gate_for(self, name: str, pristine, got_path,
                         submit_model: str, width: int,
                         height: int) -> dict:
        """One model's golden-frame parity gate (fail-closed)."""
        cfg = self.cfg
        ref_model, ref_variables = pristine
        ref_analyze = pipeline.make_frame_analyzer(
            ref_model, img_size=cfg.model_img_size, geom_cfg=self.geom_cfg
        )
        k = np.asarray(
            self.intrinsics if self.intrinsics is not None
            else _default_intrinsics(width, height), np.float32,
        )
        scale = np.float32(self.depth_scale)
        eng = self._engine
        refs, gots = [], []
        for rgb, depth in quant.golden_frames(
            cfg.quant_parity_frames, height, width
        ):
            refs.append(ref_analyze(ref_variables, rgb, depth, k, scale))
            if got_path is None:
                got = eng.dispatcher.submit(
                    rgb, depth, k, float(scale), model=submit_model)
                if isinstance(got, egress_lib.PackedResult):
                    # the packed serving path: reconstruct the
                    # FrameAnalysis view the parity report reads (mask +
                    # scalars are exact through the pack/unpack pair)
                    analysis = got.to_analysis()
                    got.release()
                    got = analysis
                gots.append(got)
            else:
                analyze, variables = got_path
                gots.append(analyze(variables, rgb, depth, k, scale))
        report = quant.parity_report(refs, gots)
        obs.QUANT_PARITY_IOU.labels(model=name).set(
            report["mask_iou_mean"])
        obs.QUANT_PARITY_CURV.labels(stat="mean", model=name).set(
            report["curvature_err_mean"])
        obs.QUANT_PARITY_CURV.labels(stat="max", model=name).set(
            report["curvature_err_max"])
        if not quant.parity_gates_pass(
            report, cfg.quant_parity_min_iou, cfg.quant_parity_max_curv_err
        ):
            raise RuntimeError(
                f"{self.precision} serving of model {name!r} failed its "
                f"parity gate vs the f32 goldens: mean IoU "
                f"{report['mask_iou_mean']:.4f} "
                f"(floor {cfg.quant_parity_min_iou}), max |d curvature| "
                f"{report['curvature_err_max']:.4f} (ceiling "
                f"{cfg.quant_parity_max_curv_err}) over "
                f"{report['frames']} frames"
            )
        log.info(
            "%s parity gate passed for %s: mean IoU %.4f, curvature err "
            "mean %.4g / max %.4g over %d goldens",
            self.precision, name, report["mask_iou_mean"],
            report["curvature_err_mean"], report["curvature_err_max"],
            report["frames"],
        )
        return report

    def mark_ready(self) -> None:
        self.health.set_all(health_lib.SERVING)
        journal_lib.JOURNAL.append(
            events.SERVER_READY, version=str(self.current_version))

    def drain(self, timeout_s: float | None = None) -> bool:
        """Begin graceful shutdown: flip readiness to NOT_SERVING, refuse
        new streams (UNAVAILABLE, so clients fail over), and wait up to
        ``timeout_s`` (default ``cfg.drain_grace_s``) for in-flight streams
        to finish. Returns True when the server drained fully. Idempotent;
        close() calls it first."""
        timeout_s = self.cfg.drain_grace_s if timeout_s is None else timeout_s
        with self._streams_cond:
            already = self._draining
            self._draining = True
        if not already:
            self.health.set_all(health_lib.NOT_SERVING)
            journal_lib.JOURNAL.append(
                events.SERVER_DRAIN, streams=str(self.active_streams))
            # graceful departure beats lease expiry: tell every registrar
            # NOW so front-ends mark this member draining (left) instead
            # of waiting a TTL to quarantine it as failed
            if self.lease_client is not None:
                self.lease_client.leave()
            log.info("draining: readiness down, waiting for %d in-flight "
                     "stream(s)", self.active_streams)
        deadline = time.monotonic() + timeout_s
        with self._streams_cond:
            while self._active_streams > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning(
                        "drain grace (%.1fs) expired with %d stream(s) "
                        "still in flight", timeout_s, self._active_streams,
                    )
                    return False
                self._streams_cond.wait(remaining)
        return True

    def close(self) -> None:
        # readiness down + bounded wait for in-flight streams BEFORE
        # tearing down the engines they are using
        self.drain()
        # flag first: an in-flight reload re-checks it before swapping, so
        # a generation built after this point never goes live
        self._closed = True
        if self.lease_client is not None:
            self.lease_client.stop()
            self.lease_client = None
        if self.controller is not None:
            self.controller.stop()
        if self._reload_stop is not None:
            self._reload_stop.set()
        if self._reload_thread is not None:
            self._reload_thread.join(timeout=5)
            self._reload_thread = None
        # flush pending grace-delayed teardowns NOW: cancel each timer and
        # stop its dispatcher immediately (stop() is drain-safe and
        # idempotent, so racing an already-fired timer is harmless) --
        # otherwise a close() shortly after a reload would leave a live
        # timer firing against torn-down state. An in-flight reload is NOT
        # waited for: its swap re-checks _closed under this same lock, so
        # any swap serialized after this drain is refused and the reload's
        # finally-block stops the never-live dispatcher itself.
        with self._reload_lock:
            pending, self._grace_stops = self._grace_stops, []
            engine = self._engine
        for timer, dispatcher in pending:
            timer.cancel()
            dispatcher.stop()
        if engine.dispatcher is not None:
            engine.dispatcher.stop()
        self.ingest.stop()
        self.egress.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        self.metrics.close()


def build_server(
    cfg: ServerConfig = ServerConfig(),
    geom_cfg: GeometryConfig | None = None,
    warmup_shape: tuple[int, int] | None = None,
) -> tuple[grpc.Server, VisionAnalysisService]:
    """Load every resource and return an unstarted (server, servicer).
    Aborts (raises) when the model or calibration is unusable, mirroring the
    reference's fail-fast startup (server.py:168-170).

    ``geom_cfg`` defaults to the serving geometry profile
    (``stride=cfg.geometry_stride``); pass an explicit GeometryConfig to
    override (e.g. stride=1 for reference-exact dense semantics)."""
    if geom_cfg is None:
        geom_cfg = GeometryConfig(stride=cfg.geometry_stride)
    # this process serves frames: spans and journal events it records are
    # attributed to the replica role in merged multi-process output (the
    # front-end's stitched /debug/trace and federated journal reads)
    trace.set_identity(role="replica")
    model, variables, version = resolve_serving_model(cfg)
    intrinsics = None
    depth_scale = cfg.default_depth_scale
    try:
        intrinsics, _, scale = load_calibration(cfg.calibration_path)
        if scale is not None:
            depth_scale = scale
        log.info("calibration loaded from %s", cfg.calibration_path)
    except (FileNotFoundError, KeyError) as exc:
        log.warning(
            "no calibration at %s (%s); using focal-length defaults",
            cfg.calibration_path, exc,
        )
    servicer = VisionAnalysisService(
        model, variables, intrinsics, depth_scale, cfg, geom_cfg,
        version=version,
    )
    # /metrics rides the servicer lifecycle: up before the first frame,
    # down in servicer.close() (cfg.metrics_port / RDP_METRICS_PORT;
    # off by default)
    servicer.metrics_server = exposition.maybe_start_metrics_server(
        cfg.metrics_port
    )
    if servicer.metrics_server is not None:
        # /debug/drift serves the monitor's live state (histograms,
        # scores, recommendation ladder) next to /debug/spans
        servicer.metrics_server.set_drift_provider(servicer.drift_debug)
        # /debug/zoo: roster, per-model versions/frames, live placement
        # + rate correlations, and the (model, placement, bucket) warm set
        servicer.metrics_server.set_zoo_provider(servicer.zoo_debug)
        # /debug/rollout resolves the manager per request, so attaching
        # one after boot (rollout_lib.attach_rollout) makes the endpoint
        # live without re-wiring
        servicer.metrics_server.set_rollout_provider(
            lambda: (servicer.rollout.snapshot()
                     if servicer.rollout is not None
                     else {"enabled": False,
                           "reason": "no rollout manager attached "
                                     "(RolloutConfig.enabled / "
                                     "RDP_ROLLOUT)"})
        )
    if warmup_shape is not None:
        servicer.warmup(*warmup_shape)  # flips readiness at the end
    else:
        # no warm-up requested: the model is loaded and the engine built,
        # which is as warm as this deployment gets -- readiness up now
        servicer.mark_ready()
    servicer.start_reloader()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=cfg.max_workers))
    vision_grpc.add_VisionAnalysisServiceServicer_to_server(servicer, server)
    # standard grpc.health.v1 surface: `grpc_health_probe -addr=...` and
    # Kubernetes native gRPC probes work against this port unmodified
    health_lib.add_HealthServicer_to_server(servicer.health, server)
    # replica stats next to health: the fleet front-end scrapes in-flight
    # streams + error-budget burn here to place streams (serving/fleet.py).
    # Drain rides the same surface so the autoscaler can retire this
    # member remotely through the exact PR 13 set_draining path.
    fleet_lib.add_replica_stats_to_server(
        server, servicer.replica_stats, drain=servicer.set_draining)
    port = server.add_insecure_port(cfg.address)
    # the OS-assigned port when cfg.address asked for :0 -- replica.py's
    # worker main reports THIS port instead of binding a second one, so
    # the advertised lease endpoint and the parent's handle always agree
    servicer.bound_port = port
    # elastic membership: when registrars are configured
    # (cfg.fleet_registrars / RDP_FLEET_REGISTRARS) this replica announces
    # itself and renews its lease; a replica respawned on a NEW port
    # rejoins the fleet with zero config edits because the advertised
    # endpoint defaults to the port the OS just bound
    registrars = fleet_lib.resolve_fleet_registrars(cfg.fleet_registrars)
    if registrars:
        advertise = fleet_lib.resolve_fleet_advertise(
            cfg.fleet_advertise, default=f"localhost:{port}")
        servicer.lease_client = fleet_lib.LeaseClient(
            registrars,
            endpoint=advertise,
            metrics_port=(servicer.metrics_server.port
                          if servicer.metrics_server is not None else 0),
            version=str(servicer.current_version),
            ttl_s=cfg.fleet_lease_ttl_s,
        )
        servicer.lease_client.start()
        log.info("fleet lease: advertising %s to %s (ttl %.1fs)",
                 advertise, ",".join(registrars), cfg.fleet_lease_ttl_s)
    return server, servicer


def serve(cfg: ServerConfig = ServerConfig(), warmup_shape=(640, 480)) -> None:
    server, servicer = build_server(cfg, warmup_shape=warmup_shape)
    server.start()
    log.info("vision analysis server listening on %s", cfg.address)
    try:
        server.wait_for_termination()
    except KeyboardInterrupt:
        log.info("interrupt: beginning graceful shutdown")
    finally:
        # readiness down first so load balancers stop routing here, then a
        # bounded drain of in-flight streams, then the hard stop
        servicer.drain()
        server.stop(grace=cfg.drain_grace_s).wait()
        servicer.close()


if __name__ == "__main__":
    from robotic_discovery_platform_tpu.utils.config import parse_config

    serve(parse_config().server)
