"""Deadline-aware admission control for the batch dispatcher's backlog.

PR 2 made the collector queue *bounded* (a submit arriving at the cap is
shed instead of growing latency without bound), but the policy was blind:
shed by queue position. Under overload that is exactly backwards -- the
newcomer may have a generous deadline while a frame that has been queuing
for most of its budget is already doomed; serving the doomed frame wastes
device time that a meetable frame needed (InferLine's SLO-driven argument,
PAPERS.md). This module makes the backlog deadline-aware:

- every queued item carries an absolute ``deadline_t`` (monotonic seconds;
  None = no deadline, infinite headroom);
- :class:`DeadlineQueue.put` at the cap finds the queued item with the
  LEAST remaining headroom and evicts it in favor of the newcomer -- but
  only when the newcomer's headroom exceeds the evictee's by a margin
  (the current service-time estimate): with homogeneous deadlines the
  difference is queue-wait noise and the newcomer, last in, is shed
  exactly as before ("fifo"-equivalent degenerate behavior);
- :class:`ServiceTimeEstimator` keeps an EWMA of per-frame dispatch
  service time so the collector can drop (error-complete) frames whose
  deadline is already unmeetable *before* paying host staging + H2D +
  device time for them -- shed work is work never staged.

The queue is a drop-in for the dispatcher's ``queue.Queue`` surface
(``get``/``get_nowait`` raise :class:`queue.Empty`, ``put(None)`` is the
shutdown sentinel and bypasses the cap) plus ``requeue`` for chip-failover
re-admission (already-admitted frames re-enter at the FRONT, keeping
their place in deadline order, and never count against the cap).

``policy="fifo"`` preserves the PR 2 behavior bit-for-bit (reject the
newcomer at the cap, no eviction, no stale shedding margin) -- the
controller-off leg of ``bench_load.py --controller both`` and any
deployment that wants position-based shedding back.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable

from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock

POLICIES = ("deadline", "fifo")


class OverloadedError(RuntimeError):
    """The dispatcher's backlog cap was hit; the frame was shed, not
    queued. Retryable by the client (the server surfaces it as
    RESOURCE_EXHAUSTED)."""


#: the estimator key every legacy (un-keyed) observation lands under
DEFAULT_ESTIMATE_KEY = ("", 0)


class ServiceTimeEstimator:
    """Per-frame service-time estimate (one frame's dispatch ride: host
    staging through completed D2H), as the MINIMUM over a sliding window
    of completed rides. The minimum, not a mean: shedding kills work
    permanently, so the question admission must answer is "could this
    frame make it even under best-case service?" -- and a best-case
    bound is also robust to one-off spikes (an XLA compile riding a
    dispatch once poisoned an EWMA here so badly that every later frame
    looked unmeetable). Thread-safe. Zero until the first observation --
    admission never sheds on a guess it has not earned.

    Keyed per ``(model, bucket)``: under a model zoo one global window
    mixed every model's rides, so a cheap aux-head ride (sub-ms) could
    drive the minimum down and make the heavy segmenter's deadlines look
    meetable (never shed, queue grows) -- or the segmenter's rides could
    make the aux head's generous deadlines look doomed. ``s_for(model)``
    answers the admission question per model (best case over that
    model's buckets only); the legacy ``.s`` property is the minimum
    over everything, exactly the old single-model behavior when only one
    model observes."""

    def __init__(self, window: int = 16):
        self._lock = checked_lock("admission.estimator")
        self._maxlen = max(1, int(window))
        self._windows: dict[tuple, deque[float]] = {}  # guarded_by: _lock
        self._n = 0  # guarded_by: _lock

    def observe(self, seconds: float,
                key: tuple | None = None) -> None:
        """One completed ride; ``key`` is ``(model, bucket)`` (None = the
        legacy un-keyed bucket)."""
        if seconds < 0:
            return
        key = DEFAULT_ESTIMATE_KEY if key is None else key
        with self._lock:
            self._n += 1
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = deque(maxlen=self._maxlen)
            win.append(float(seconds))

    def s_for(self, model: str = "") -> float:
        """Best-case service time over ``model``'s keys only (0 = that
        model has no completed rides yet -- admission never sheds a
        model on another model's history)."""
        with self._lock:
            mins = [min(w) for k, w in self._windows.items()
                    if k[0] == model and w]
        return min(mins) if mins else 0.0

    @property
    def s(self) -> float:
        """Best-case per-frame service time in seconds over the recent
        window of EVERY key (0 = no observations yet) -- the pre-zoo
        single-model semantics."""
        with self._lock:
            mins = [min(w) for w in self._windows.values() if w]
        return min(mins) if mins else 0.0

    @property
    def observations(self) -> int:
        with self._lock:
            return self._n


def headroom(item: Any, now: float) -> float:
    """Seconds until ``item``'s deadline; inf when it carries none."""
    deadline_t = getattr(item, "deadline_t", None)
    if deadline_t is None:
        return float("inf")
    return deadline_t - now


class DeadlineQueue:
    """Bounded FIFO whose overflow policy understands deadlines.

    Args:
        max_backlog: queued-item cap (0 = every put at the cap sheds,
            exactly the old bounded-queue semantics).
        policy: "deadline" (least-headroom eviction at the cap) or
            "fifo" (reject the newcomer at the cap, PR 2 behavior).
        on_evict: called with each evicted item BEFORE the newcomer is
            admitted (the dispatcher error-completes the evictee's
            submitter here). Runs under the queue lock -- must not call
            back into the queue.
        clock: injectable monotonic clock for deterministic tests.
    """

    def __init__(self, max_backlog: int, policy: str = "deadline",
                 on_evict: Callable[[Any], None] | None = None,
                 clock: Callable[[], float] = time.monotonic):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown admission policy {policy!r}; one of {POLICIES}"
            )
        self.max_backlog = int(max_backlog)
        self.policy = policy
        self._on_evict = on_evict
        self._clock = clock
        # a Condition, not a checked_lock: waiters need wait/notify, and
        # the sanitizer wrapper deliberately does not impersonate the
        # Condition protocol (its _is_owned fallback probes with a
        # non-blocking acquire, which the re-acquisition check would
        # rightly reject)
        self._cond = threading.Condition()
        self._items: deque[Any] = deque()  # guarded_by: _cond
        #: items shed by least-headroom eviction since construction
        self.evictions = 0  # guarded_by: _cond

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)

    def put(self, item: Any, margin_s: float = 0.0) -> None:
        """Admit ``item``, evicting the least-headroom queued item when at
        the cap (deadline policy) or raising :class:`OverloadedError`.

        ``margin_s`` is the eviction hysteresis: the newcomer must beat
        the evictee's headroom by at least this much (the caller passes
        its service-time estimate), so FIFO-ordered frames with identical
        budgets never churn. ``None`` is the shutdown sentinel and always
        enqueues."""
        with self._cond:
            if item is not None and len(self._items) >= self.max_backlog:
                evicted = self._pick_eviction(item, margin_s)
                if evicted is None:
                    raise OverloadedError(
                        f"dispatcher backlog at cap ({self.max_backlog} "
                        "frames queued); shedding load"
                    )
                self._items.remove(evicted)
                self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(evicted)
            self._items.append(item)
            self._cond.notify()

    def _pick_eviction(self, newcomer: Any, margin_s: float) -> Any | None:
        """The queued item to shed in favor of ``newcomer``: the one with
        the least remaining headroom, and only when the newcomer beats it
        by ``margin_s``. None = shed the newcomer instead (fifo policy,
        empty queue, or no queued item is meaningfully worse off)."""
        if self.policy != "deadline" or not self._items:
            return None
        now = self._clock()
        candidates = [i for i in self._items if i is not None]
        if not candidates:
            return None
        worst = min(candidates, key=lambda i: headroom(i, now))
        worst_headroom = headroom(worst, now)
        if worst_headroom == float("inf"):
            return None  # nobody carries a deadline: position sheds
        if headroom(newcomer, now) > worst_headroom + max(margin_s, 1e-3):
            return worst
        return None

    def requeue(self, items: list[Any]) -> None:
        """Re-admit already-admitted items at the FRONT, preserving their
        relative order, never counting against the cap (chip failover:
        these frames hold submitters that are still waiting)."""
        with self._cond:
            for item in reversed(items):
                self._items.appendleft(item)
            self._cond.notify(len(items))

    def get(self, timeout: float | None = None) -> Any:
        """Pop the head; blocks (forever when ``timeout`` is None) and
        raises :class:`queue.Empty` on timeout -- the ``queue.Queue``
        contract the collector already speaks."""
        with self._cond:
            if timeout is None:
                while not self._items:
                    self._cond.wait()
            else:
                deadline = self._clock() + timeout
                while not self._items:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        raise queue.Empty
                    self._cond.wait(remaining)
            return self._items.popleft()

    def get_nowait(self) -> Any:
        with self._cond:
            if not self._items:
                raise queue.Empty
            return self._items.popleft()
