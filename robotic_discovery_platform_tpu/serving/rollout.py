"""Drift-triggered rollout: the state machine that turns the crank.

PR 9 made drift a live signal (a ``RetrainRecommendation`` per sustained
excursion) and PR 10 gave the fleet health-gated membership -- but the
recommendation terminated in a log line. This module closes the loop:
signal -> plan -> safe rollout, the third level of InferLine's
planner/reactive split (PAPERS.md), with Clockwork-style predictability
preserved by never letting training and serving contend for the same
chips (training runs only on a replica the front-end has stopped placing
streams on).

The :class:`RolloutManager` drives one supervised lifecycle per accepted
recommendation::

    IDLE -> DRAINING -> RETRAINING -> SHADOW -> CANARY -> PROMOTING
                                                        -> REJOINING -> IDLE

- **DRAINING**: the least-loaded replica's ``draining`` flag goes up
  (``VisionAnalysisService.set_draining``). The front-end stops placing
  NEW streams there (serving/fleet.py treats ``draining=true`` as
  unplaceable *before* health ever flips -- a graceful drain, not a
  failover), in-flight streams finish normally, and the stage waits for
  the replica's stream count to reach zero.
- **RETRAINING**: ``workflows/retraining.run_retraining_pipeline`` runs
  on the drained replica's mesh (``parallel/dp.py``), registering the
  candidate under ``RolloutConfig.candidate_alias`` -- never under
  ``staging``, so the serving alias cannot move before the gates pass.
- **SHADOW**: the serving replicas mirror ``shadow_fraction`` of their
  live frames to the candidate (a bounded queue the handler threads never
  block on; candidate results are never returned to callers). Each
  mirrored frame is diffed against the serving generation's own output:
  mask IoU, |delta curvature|, and the five drift signals.
- **CANARY**: the promotion gates are evaluated fail-closed -- the PR 8
  parity fixtures (candidate vs the live generation over
  ``quant.golden_frames``), the live shadow-diff deltas, and the
  candidate-vs-serving drift scores. Every verdict is counted
  (``rdp_rollout_gate_verdicts_total``); ANY failure rejects the
  candidate.
- **PROMOTING**: the registry ``staging`` alias moves to the candidate
  and every replica promotes through the existing hot-reload
  engine-generation swap -- which re-stamps the drift reference
  ATOMICALLY with the engine (serving/server.py), so a mid-promotion
  scrape never pairs new weights with the old reference.
- **REJOINING**: the drained replica un-drains and rejoins the placement
  ring on the front-end's next stats scrape.

Every unhappy path -- retrain crash, gate failure, replica death
mid-shadow, any stage exceeding its ``RolloutConfig`` timeout -- rolls
back: the candidate is discarded, the replica un-drains, the fleet keeps
serving the old generation, and the state machine lands in IDLE. The
drift excursion re-arms only per the PR 9 hysteresis (recovery +
cooldown), so a rolled-back cycle cannot machine-gun retraining.

Every transition is counted (``rdp_rollout_transitions_total``), pinned
in the flight recorder, and visible -- with per-stage timings, gate
verdicts, and cycle history -- at ``GET /debug/rollout``. The clock and
sleep are injectable, so the whole ladder is fake-clock testable like
serving/controller.py and the drift monitor.
"""

from __future__ import annotations

import inspect
import os
import queue
import threading
import time
from typing import Callable, NamedTuple, Sequence

from robotic_discovery_platform_tpu.monitoring import profile as profile_lib
from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
    recorder as recorder_lib,
)
from robotic_discovery_platform_tpu.utils.config import (
    GeometryConfig,
    RolloutConfig,
    ServerConfig,
)
from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

# -- states ------------------------------------------------------------------

IDLE = "idle"
DRAINING = "draining"
RETRAINING = "retraining"
SHADOW = "shadow"
CANARY = "canary"
PROMOTING = "promoting"
REJOINING = "rejoining"

#: every stage, in cycle order (the gauge publishes one label per state)
STATES = (IDLE, DRAINING, RETRAINING, SHADOW, CANARY, PROMOTING, REJOINING)

_ROLLOUT_ENV_VAR = "RDP_ROLLOUT"


def resolve_rollout_enabled(configured: bool) -> bool:
    """``RDP_ROLLOUT`` overrides ``RolloutConfig.enabled`` (1/true/on)."""
    raw = os.environ.get(_ROLLOUT_ENV_VAR, "").strip().lower()
    if not raw:
        return bool(configured)
    return raw in ("1", "true", "yes", "on")


class StageError(RuntimeError):
    """A rollout stage failed; ``stage`` names where the cycle died."""

    def __init__(self, stage: str, message: str):
        super().__init__(message)
        self.stage = stage


class StageTimeout(StageError):
    """A rollout stage exceeded its RolloutConfig timeout."""


# -- shadow mirroring --------------------------------------------------------


class ShadowSample(NamedTuple):
    """One live frame mirrored to the candidate: the decoded inputs plus
    the serving generation's own outputs to diff against (the mask rides
    along decoded -- re-decoding the response PNG per mirrored frame
    would tax the shadow thread for nothing)."""

    rgb: object
    depth: object
    k: object  # float32 intrinsics (the geometry cache's converted copy)
    depth_scale: float
    mask: object  # the live engine's binary mask (model-resolution)
    coverage: float
    mean_curvature: float
    max_curvature: float
    valid: bool
    confidence_margin: float
    depth_valid_fraction: float

    def live_signals(self) -> dict[str, float]:
        """The serving generation's drift-signal values for this frame
        (same shape as profile_lib.frame_signals)."""
        import math

        return {
            "mask_coverage": self.coverage,
            "mean_curvature": (self.mean_curvature if self.valid
                               else math.nan),
            "max_curvature": (self.max_curvature if self.valid
                              else math.nan),
            "depth_valid_fraction": self.depth_valid_fraction,
            "confidence_margin": self.confidence_margin,
        }


class ShadowRunner:
    """Mirrors a fraction of live frames to the candidate and accumulates
    the diff evidence the CANARY gates consume.

    The ``hook`` side runs on serving handler threads and must never
    block: it samples deterministically by fraction and does a
    ``put_nowait`` into a bounded queue (overflow is dropped and
    counted). The ``process`` side runs on the rollout cycle's own
    thread: pop a sample, run the candidate analyzer, score the diff."""

    def __init__(self, analyze: Callable, variables, *,
                 fraction: float = 0.5, max_queue: int = 64):
        self._analyze = analyze
        self._variables = variables
        self.fraction = min(max(float(fraction), 0.0), 1.0)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(max_queue)))
        self._lock = checked_lock("rollout.shadow")
        self._seen = 0  # guarded_by: _lock
        self._taken = 0  # guarded_by: _lock
        self.mirrored = 0  # guarded_by: _lock
        self.dropped = 0  # guarded_by: _lock
        self.errors = 0
        self.ious: list[float] = []
        self.curv_errs: list[float] = []
        self._live_signals: dict[str, list[float]] = {
            name: [] for name in profile_lib.SERVING_SIGNALS
        }
        self._cand_signals: dict[str, list[float]] = {
            name: [] for name in profile_lib.SERVING_SIGNALS
        }

    # -- handler-thread side -------------------------------------------------

    def hook(self, sample: ShadowSample) -> None:
        """The mirror tap the serving replicas call per analyzed frame."""
        with self._lock:
            self._seen += 1
            take = self._seen * self.fraction >= self._taken + 1
            if take:
                self._taken += 1
        if not take:
            return
        try:
            self._q.put_nowait(sample)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            obs.ROLLOUT_SHADOW_FRAMES.labels(outcome="dropped").inc()
            return
        with self._lock:
            self.mirrored += 1
        obs.ROLLOUT_SHADOW_FRAMES.labels(outcome="mirrored").inc()

    # -- cycle-thread side ---------------------------------------------------

    def process_one(self, timeout_s: float = 0.1) -> bool:
        """Pop and diff one mirrored frame; False when none arrived
        within ``timeout_s``."""
        import math

        import numpy as np

        from robotic_discovery_platform_tpu.ops.pallas import quant

        try:
            sample = self._q.get(timeout=timeout_s)
        except queue.Empty:
            return False
        try:
            import jax

            # explicit H2D for the mirrored inputs: the candidate
            # analyzer runs under the transfer guard like every hot
            # jitted entry, and implicit per-call transfers are exactly
            # what RDP_TRANSFER_GUARD=strict refuses
            inputs = jax.device_put((
                sample.rgb, sample.depth, sample.k,
                np.float32(sample.depth_scale),
            ))
            out = self._analyze(self._variables, *inputs)
            cand_mask = np.asarray(out.mask)
            cand_signals = profile_lib.frame_signals(out, sample.depth)
        except Exception as exc:  # noqa: BLE001 - candidate bug = evidence
            self.errors += 1
            obs.ROLLOUT_SHADOW_FRAMES.labels(outcome="error").inc()
            log.warning("shadow candidate failed on a mirrored frame "
                        "(%s: %s)", type(exc).__name__, exc)
            return True
        self.ious.append(quant.mask_iou(sample.mask, cand_mask))
        cand_valid = not math.isnan(cand_signals["mean_curvature"])
        if sample.valid and cand_valid:
            self.curv_errs.append(abs(
                cand_signals["mean_curvature"] - sample.mean_curvature
            ))
        elif sample.valid != cand_valid:
            # validity flip scored like quant.parity_report: the worst
            # curvature outcome, visible to the gate
            self.curv_errs.append(
                abs(sample.mean_curvature if sample.valid
                    else cand_signals["mean_curvature"])
            )
        live = sample.live_signals()
        for name in self._live_signals:
            lv, cv = live.get(name), cand_signals.get(name)
            if lv is not None and math.isfinite(lv):
                self._live_signals[name].append(lv)
            if cv is not None and math.isfinite(cv):
                self._cand_signals[name].append(cv)
        obs.ROLLOUT_SHADOW_FRAMES.labels(outcome="diffed").inc()
        return True

    @property
    def diffed(self) -> int:
        return len(self.ious) + self.errors

    def report(self) -> dict:
        """The shadow evidence the gates evaluate: per-frame diff
        aggregates plus the worst candidate-vs-serving PSI across the
        drift signals (scored over the SAME mirrored frames, so the two
        sides share their sampling noise)."""
        import numpy as np

        psi_by_signal: dict[str, float] = {}
        for name, spec in profile_lib.SERVING_SIGNALS.items():
            live = self._live_signals[name]
            cand = self._cand_signals[name]
            if len(live) < 2 or len(cand) < 2:
                continue
            score = profile_lib.score_value_lists(spec, live, cand)
            psi_by_signal[name] = score.psi - score.noise_floor
        with self._lock:
            mirrored, dropped = self.mirrored, self.dropped
        return {
            "frames": len(self.ious),
            "errors": self.errors,
            "mirrored": mirrored,
            "dropped": dropped,
            "mask_iou_mean": (float(np.mean(self.ious))
                              if self.ious else 0.0),
            "mask_iou_min": (float(np.min(self.ious))
                             if self.ious else 0.0),
            "curvature_err_mean": (float(np.mean(self.curv_errs))
                                   if self.curv_errs else 0.0),
            "curvature_err_max": (float(np.max(self.curv_errs))
                                  if self.curv_errs else 0.0),
            "psi": psi_by_signal,
            "psi_max": (max(psi_by_signal.values())
                        if psi_by_signal else 0.0),
        }


# -- targets -----------------------------------------------------------------


class RolloutTarget:
    """The rollout control surface over one in-process replica servicer
    (serving/server.VisionAnalysisService). Duck-typed on purpose: tests
    drive the manager with fakes exposing the same six members, and a
    future remote-target can speak RPC behind the identical surface."""

    def __init__(self, name: str, servicer):
        self.name = name
        self.servicer = servicer

    @property
    def active_streams(self) -> int:
        return self.servicer.active_streams

    @property
    def draining(self) -> bool:
        return self.servicer.is_draining

    @property
    def current_version(self):
        return self.servicer.current_version

    def set_draining(self, draining: bool) -> None:
        self.servicer.set_draining(draining)

    def set_shadow(self, hook) -> None:
        self.servicer.set_shadow(hook)

    def promote(self) -> bool:
        """Drive one hot-reload check NOW (the poller would get there on
        its own tick; promotion should not wait for it)."""
        return bool(self.servicer.maybe_reload())

    def reference_analyzer(self):
        """An f32 analyzer over the CURRENT generation's pristine pair --
        the fixture gate's reference side. The weight tree is staged
        explicitly (a registry-loaded tree surfaces as host numpy, and
        implicit per-call re-transfers are what the transfer guard
        refuses); the servicer's own pair is never mutated."""
        import jax

        from robotic_discovery_platform_tpu.ops import pipeline

        model, variables = self.servicer._pristine
        if any(not isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(variables)):
            variables = jax.device_put(variables)
        cfg = self.servicer.cfg
        analyze = pipeline.make_frame_analyzer(
            model, img_size=cfg.model_img_size,
            geom_cfg=self.servicer.geom_cfg,
        )
        return lambda rgb, depth, k, scale: analyze(
            variables, rgb, depth, k, scale)

    def training_mesh(self):
        """The drained replica's device mesh for the retraining run
        (parallel/dp.py); None when no mesh is buildable (single-device
        CPU smoke trains unmeshed)."""
        try:
            from robotic_discovery_platform_tpu.parallel import (
                mesh as mesh_lib,
            )

            chips = max(1, getattr(self.servicer, "serving_chips", 1))
            return mesh_lib.make_serving_mesh(chips)
        except Exception as exc:  # noqa: BLE001 - mesh is best-effort
            log.warning("no training mesh for %s (%s: %s); retraining "
                        "runs unmeshed", self.name, type(exc).__name__,
                        exc)
            return None


# -- gates -------------------------------------------------------------------


def evaluate_gates(cfg: RolloutConfig, fixture_report: dict,
                   shadow_report: dict) -> tuple[bool, dict]:
    """Fail-closed promotion verdict: every gate must pass. Returns
    ``(passed, verdicts)`` where ``verdicts`` maps gate name to
    ``{"value", "threshold", "pass"}``; each verdict is also counted in
    ``rdp_rollout_gate_verdicts_total``."""
    verdicts = {
        "fixture_iou": {
            "value": fixture_report["mask_iou_mean"],
            "threshold": cfg.gate_fixture_min_iou,
            "pass": (fixture_report["mask_iou_mean"]
                     >= cfg.gate_fixture_min_iou),
        },
        "fixture_curv": {
            "value": fixture_report["curvature_err_max"],
            "threshold": cfg.gate_fixture_max_curv_err,
            "pass": (fixture_report["curvature_err_max"]
                     <= cfg.gate_fixture_max_curv_err),
        },
        "shadow_frames": {
            "value": shadow_report["frames"],
            "threshold": cfg.shadow_min_frames,
            "pass": shadow_report["frames"] >= cfg.shadow_min_frames,
        },
        "shadow_iou": {
            "value": shadow_report["mask_iou_mean"],
            "threshold": cfg.gate_shadow_min_iou,
            "pass": (shadow_report["mask_iou_mean"]
                     >= cfg.gate_shadow_min_iou),
        },
        "shadow_curv": {
            "value": shadow_report["curvature_err_max"],
            "threshold": cfg.gate_shadow_max_curv_err,
            "pass": (shadow_report["curvature_err_max"]
                     <= cfg.gate_shadow_max_curv_err),
        },
        "shadow_psi": {
            "value": shadow_report["psi_max"],
            "threshold": cfg.gate_shadow_max_psi,
            "pass": shadow_report["psi_max"] <= cfg.gate_shadow_max_psi,
        },
    }
    for gate, v in verdicts.items():
        obs.ROLLOUT_GATE_VERDICTS.labels(
            gate=gate, verdict="pass" if v["pass"] else "fail"
        ).inc()
    return all(v["pass"] for v in verdicts.values()), verdicts


# -- the manager -------------------------------------------------------------


class RolloutManager:
    """Consumes retrain recommendations and drives the drain -> retrain
    -> shadow -> gate -> promote/rollback cycle over a set of
    :class:`RolloutTarget`-shaped replicas.

    ``train_fn(target) -> PipelineResult`` is injectable (tests and the
    smoke harness register crafted candidates); the default runs the real
    ``workflows/retraining`` pipeline on the drained target's mesh with
    the ``train_cfg``/``model_cfg`` given at construction. ``clock`` and
    ``sleep`` are injectable for fake-clock tests. ``run_cycle`` is
    public and synchronous so tests drive the ladder deterministically;
    ``start()`` adds the worker thread that services live
    recommendations."""

    #: completed cycles kept for /debug/rollout
    HISTORY = 16

    def __init__(
        self,
        targets: Sequence,
        cfg: RolloutConfig = RolloutConfig(),
        server_cfg: ServerConfig = ServerConfig(),
        *,
        train_fn: Callable | None = None,
        train_cfg=None,
        model_cfg=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.targets = list(targets)
        self.cfg = cfg
        self.server_cfg = server_cfg
        self._train_fn = train_fn
        self._train_cfg = train_cfg
        self._model_cfg = model_cfg
        self._clock = clock
        self._sleep = sleep
        self._lock = checked_lock("rollout.manager")
        self._state = IDLE  # guarded_by: _lock
        self._current: dict | None = None  # guarded_by: _lock
        self.history: list[dict] = []  # guarded_by: _lock
        self._cycles = 0  # guarded_by: _lock
        self._inbox: queue.Queue = queue.Queue(maxsize=1)
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self._publish_state(IDLE)

    # -- wiring --------------------------------------------------------------

    def add_target(self, target) -> None:
        self.targets.append(target)

    def on_recommendation(self, rec) -> bool:
        """The drift monitor's callback (serving/server.py forwards it).
        Non-blocking: enqueues the recommendation for the worker when the
        machine is idle, else counts it skipped -- at most one cycle runs
        at a time, and the PR 9 hysteresis already throttles the stream
        to one recommendation per excursion."""
        with self._lock:
            busy = self._state != IDLE
        if busy:
            obs.ROLLOUT_SKIPPED.labels(reason="busy").inc()
            log.info("rollout busy (%s); recommendation skipped",
                     self.state)
            return False
        try:
            self._inbox.put_nowait(rec)
        except queue.Full:
            obs.ROLLOUT_SKIPPED.labels(reason="busy").inc()
            return False
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.is_set():
                try:
                    rec = self._inbox.get(timeout=0.2)
                except queue.Empty:
                    continue
                if rec is None:
                    return
                try:
                    self.run_cycle(rec)
                except Exception:  # pragma: no cover - cycle self-guards
                    log.exception("rollout cycle crashed")

        self._thread = threading.Thread(target=loop, name="rollout-manager",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
            try:
                self._inbox.put_nowait(None)
            except queue.Full:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _publish_state(self, state: str) -> None:
        for s in STATES:
            obs.ROLLOUT_STATE.labels(state=s).set(1.0 if s == state else 0.0)

    def _transition(self, to: str, cycle: dict | None = None,
                    **labels) -> None:
        with self._lock:
            frm, self._state = self._state, to
            if cycle is not None:
                cycle["stages"].append(
                    {"stage": to, "at_s": round(self._clock(), 3)})
        self._publish_state(to)
        obs.ROLLOUT_TRANSITIONS.labels(to=to).inc()
        # pinned: a rollout transition is promotion-audit evidence that
        # must survive ring wrap-around
        recorder_lib.RECORDER.pin(recorder_lib.RECORDER.record_event(
            "serving.rollout.transition", frm=frm, to=to,
            **{k: str(v) for k, v in labels.items()},
        ))
        journal_lib.JOURNAL.append(
            events.ROLLOUT_TRANSITION, frm=frm, to=to,
            **{k: str(v) for k, v in labels.items()},
        )
        log.info("rollout: %s -> %s%s", frm, to,
                 f" {labels}" if labels else "")

    # -- the cycle -----------------------------------------------------------

    def _pick_target(self):
        """Least-loaded drainable replica -- ONLY when at least one other
        replica keeps serving (the loop never trades availability for
        freshness)."""
        candidates = [t for t in self.targets
                      if not getattr(t, "draining", False)]
        if len(candidates) < 2:
            return None
        return min(candidates, key=lambda t: t.active_streams)

    def _wait(self, stage: str, deadline: float, done: Callable[[], bool],
              what: str) -> None:
        while not done():
            if self._clock() >= deadline:
                raise StageTimeout(stage, f"{stage}: timed out waiting "
                                          f"for {what}")
            self._sleep(0.05)

    def _retrain(self, target) -> object:
        """Run the training function bounded by the stage timeout. The
        thread cannot be killed mid-train; on timeout the cooperative
        cancel flag is set -- the retraining pipeline checks it at stage
        boundaries and exits early instead of burning a full training
        run whose candidate the cycle has already discarded."""
        result_box: list = []
        cancel = threading.Event()

        def run():
            try:
                result_box.append(self._train(target, cancel))
            except Exception as exc:  # noqa: BLE001 - surfaced below
                result_box.append(exc)

        t = threading.Thread(target=run, name="rollout-retrain",
                             daemon=True)
        t.start()
        deadline = self._clock() + self.cfg.retrain_timeout_s
        while t.is_alive():
            if self._clock() >= deadline:
                cancel.set()
                obs.ROLLOUT_RETRAIN_CANCELS.inc()
                journal_lib.JOURNAL.append(
                    events.ROLLOUT_RETRAIN_CANCEL,
                    timeout_s=self.cfg.retrain_timeout_s,
                )
                raise StageTimeout(
                    RETRAINING,
                    f"retraining exceeded {self.cfg.retrain_timeout_s:.0f}s"
                    "; candidate (if any) is discarded and the pipeline "
                    "is asked to stop at its next stage boundary")
            t.join(timeout=0.05)
            if t.is_alive():
                # the injectable sleep is what advances a fake clock --
                # join() alone would spin a fake-clock test forever
                self._sleep(0.05)
        if not result_box:
            raise StageError(RETRAINING, "retraining returned nothing")
        result = result_box[0]
        if isinstance(result, BaseException):
            raise StageError(
                RETRAINING,
                f"retraining raised {type(result).__name__}: {result}")
        return result

    def _train(self, target, cancel: threading.Event | None = None):
        if self._train_fn is not None:
            # legacy train_fns take only the target; pass the cancel
            # flag to any that declare a second parameter for it
            try:
                params = inspect.signature(self._train_fn).parameters
                takes_cancel = ("cancel" in params
                                or len(params) >= 2)
            except (TypeError, ValueError):
                takes_cancel = False
            if takes_cancel and cancel is not None:
                return self._train_fn(target, cancel)
            return self._train_fn(target)
        if self._train_cfg is None:
            raise StageError(
                RETRAINING,
                "no train_fn and no train_cfg configured; the rollout "
                "manager cannot launch the retraining pipeline")
        from robotic_discovery_platform_tpu.workflows.retraining import (
            run_retraining_pipeline,
        )

        mesh = target.training_mesh() if hasattr(target, "training_mesh") \
            else None
        kwargs = {"mesh": mesh, "alias": self.cfg.candidate_alias,
                  "cancel": cancel}
        if self._model_cfg is not None:
            kwargs["model_cfg"] = self._model_cfg
        return run_retraining_pipeline(self._train_cfg, **kwargs)

    def _load_candidate(self, version):
        """The candidate analyzer + variables for shadow/fixture runs.
        The weight tree is staged explicitly ONCE (serving/server.py's
        _make_engine policy): a registry-loaded tree is host numpy, and
        passing it raw would re-transfer every weight per mirrored frame
        -- implicitly, which RDP_TRANSFER_GUARD=strict rightly refuses."""
        import jax

        from robotic_discovery_platform_tpu import tracking
        from robotic_discovery_platform_tpu.ops import pipeline

        store = tracking.store_for(self.server_cfg.tracking_uri)
        model, variables = tracking.load_model(
            f"models:/{self.server_cfg.model_name}/{version}", store=store,
        )
        if any(not isinstance(leaf, jax.Array)
               for leaf in jax.tree_util.tree_leaves(variables)):
            variables = jax.device_put(variables)
        analyze = pipeline.make_frame_analyzer(
            model, img_size=self.server_cfg.model_img_size,
            geom_cfg=GeometryConfig(stride=self.server_cfg.geometry_stride),
        )
        return analyze, variables

    #: the fixture scenes' camera geometry -- also the candidate warm
    #: shape (a mirrored frame of the same geometry reuses the compile)
    FIXTURE_H, FIXTURE_W = 120, 160

    def _warm_candidate(self, cand_analyze, cand_variables) -> None:
        """One golden frame through the candidate so its graph is
        compiled before shadow mirroring starts. Best-effort: a failure
        here will resurface as shadow-frame errors the gate sees."""
        import jax
        import numpy as np

        from robotic_discovery_platform_tpu.ops.pallas import quant

        h, w = self.FIXTURE_H, self.FIXTURE_W
        f = 0.94 * w
        k = np.array([[f, 0, w / 2], [0, f, h / 2], [0, 0, 1]], np.float32)
        try:
            rgb, depth = quant.golden_frames(1, h, w)[0]
            inputs = jax.device_put((
                rgb, depth, k,
                np.float32(self.server_cfg.default_depth_scale),
            ))
            cand_analyze(cand_variables, *inputs)
        except Exception as exc:  # noqa: BLE001 - surfaced by the gates
            log.warning("candidate warm-up failed (%s: %s); the shadow "
                        "stage will surface it", type(exc).__name__, exc)

    def _fixture_report(self, reference_analyzer, cand_analyze,
                        cand_variables) -> dict:
        """The PR 8 parity fixtures, candidate vs the live generation:
        deterministic synthetic scenes through both analyzers, scored by
        quant.parity_report."""
        import jax
        import numpy as np

        from robotic_discovery_platform_tpu.ops.pallas import quant

        h, w = self.FIXTURE_H, self.FIXTURE_W
        f = 0.94 * w
        k = np.array([[f, 0, w / 2], [0, f, h / 2], [0, 0, 1]], np.float32)
        scale = np.float32(self.server_cfg.default_depth_scale)
        refs, gots = [], []
        for rgb, depth in quant.golden_frames(
            self.cfg.gate_fixture_frames, h, w
        ):
            # explicit H2D per fixture frame (transfer-guard discipline)
            inputs = jax.device_put((rgb, depth, k, scale))
            refs.append(reference_analyzer(*inputs))
            gots.append(cand_analyze(cand_variables, *inputs))
        return quant.parity_report(refs, gots)

    def _promote(self, cycle: dict, version) -> None:
        """Move the staging alias and drive every replica through its
        hot-reload swap; on partial failure the alias is restored and the
        already-promoted replicas are reloaded back -- fail-closed, the
        fleet converges on ONE generation either way."""
        from robotic_discovery_platform_tpu import tracking

        store = tracking.store_for(self.server_cfg.tracking_uri)
        name = self.server_cfg.model_name
        previous = store.get_alias(name, self.server_cfg.model_alias)
        cycle["previous_version"] = previous
        store.set_alias(name, self.server_cfg.model_alias, int(version))
        try:
            deadline = self._clock() + self.cfg.promote_timeout_s
            for t in self.targets:
                t.promote()
                self._wait(
                    PROMOTING, deadline,
                    lambda t=t: t.current_version == int(version),
                    f"replica {t.name} to adopt version {version}",
                )
        except Exception:
            if previous is not None:
                log.error("promotion failed mid-swap; reverting %s alias "
                          "to version %s", self.server_cfg.model_alias,
                          previous)
                store.set_alias(name, self.server_cfg.model_alias,
                                int(previous))
                for t in self.targets:
                    try:
                        t.promote()
                    except Exception:  # noqa: BLE001 - best-effort revert
                        log.exception("revert reload failed on %s", t.name)
            raise

    def run_cycle(self, rec) -> dict:
        """One full supervised rollout for ``rec``; returns the cycle
        record (also appended to :attr:`history`). Never raises: every
        failure is a recorded rollback landing back in IDLE."""
        t0 = self._clock()
        cycle: dict = {
            "reason": getattr(rec, "reason", str(rec)),
            "signals": list(getattr(rec, "signals", []) or []),
            "started_s": round(t0, 3),
            "stages": [],
            "outcome": None,
            "candidate_version": None,
            "gates": None,
            "shadow": None,
            "fixture": None,
        }
        with self._lock:
            self._current = cycle
        target = self._pick_target()
        if target is None:
            obs.ROLLOUT_SKIPPED.labels(reason="no_spare_replica").inc()
            cycle["outcome"] = "skipped"
            cycle["error"] = ("no spare replica: draining one would leave "
                              "nothing serving")
            log.warning("rollout skipped: %s", cycle["error"])
            self._record_cycle(cycle, t0)
            return cycle
        cycle["replica"] = target.name
        stage = DRAINING
        drained = False
        try:
            # -- DRAINING --------------------------------------------------
            self._transition(DRAINING, cycle, replica=target.name)
            target.set_draining(True)
            drained = True
            self._wait(DRAINING, self._clock() + self.cfg.drain_timeout_s,
                       lambda: target.active_streams == 0,
                       "in-flight streams to finish")

            # -- RETRAINING ------------------------------------------------
            stage = RETRAINING
            self._transition(RETRAINING, cycle, replica=target.name)
            result = self._retrain(target)
            if result is None or not getattr(result, "succeeded", False) \
                    or getattr(result, "version", None) is None:
                raise StageError(
                    RETRAINING,
                    "retraining pipeline failed: "
                    f"{getattr(result, 'message', result)}")
            version = int(result.version)
            cycle["candidate_version"] = version
            cand_analyze, cand_variables = self._load_candidate(version)
            # warm the candidate's graph BEFORE the shadow stage opens
            # (the server's own discipline: compile off the measured
            # path). Without this the first mirrored frame pays the full
            # XLA compilation inside the shadow stage's budget.
            self._warm_candidate(cand_analyze, cand_variables)

            # -- SHADOW ----------------------------------------------------
            stage = SHADOW
            self._transition(SHADOW, cycle, candidate=version)
            runner = ShadowRunner(
                cand_analyze, cand_variables,
                fraction=self.cfg.shadow_fraction,
                max_queue=self.cfg.shadow_queue,
            )
            live_targets = [t for t in self.targets if t is not target]
            for t in live_targets:
                t.set_shadow(runner.hook)
            try:
                deadline = self._clock() + self.cfg.shadow_timeout_s
                while runner.diffed < self.cfg.shadow_min_frames:
                    if self._clock() >= deadline:
                        break
                    if not runner.process_one(timeout_s=0.0):
                        # idle tap: wait through the injectable sleep so
                        # fake-clock tests can expire the stage
                        self._sleep(0.05)
                # drain what was already mirrored before the tap closes
                while runner.process_one(timeout_s=0.0):
                    pass
            finally:
                for t in live_targets:
                    try:
                        t.set_shadow(None)
                    except Exception:  # noqa: BLE001 - replica died
                        log.exception("clearing shadow tap on %s failed",
                                      t.name)
            shadow_report = runner.report()
            cycle["shadow"] = shadow_report

            # -- CANARY ----------------------------------------------------
            stage = CANARY
            self._transition(CANARY, cycle, candidate=version)
            reference = None
            for t in live_targets:
                try:
                    reference = t.reference_analyzer()
                    break
                except Exception:  # noqa: BLE001 - try the next replica
                    log.exception("reference analyzer from %s failed",
                                  t.name)
            if reference is None:
                raise StageError(CANARY, "no live replica could provide "
                                         "the fixture reference analyzer")
            fixture_report = self._fixture_report(
                reference, cand_analyze, cand_variables)
            cycle["fixture"] = fixture_report
            passed, verdicts = evaluate_gates(
                self.cfg, fixture_report, shadow_report)
            cycle["gates"] = verdicts
            if not passed:
                failed = sorted(g for g, v in verdicts.items()
                                if not v["pass"])
                raise StageError(
                    CANARY,
                    f"candidate v{version} rejected by gate(s) "
                    f"{', '.join(failed)}")

            # -- PROMOTING -------------------------------------------------
            stage = PROMOTING
            self._transition(PROMOTING, cycle, candidate=version)
            self._promote(cycle, version)

            # -- REJOINING -------------------------------------------------
            stage = REJOINING
            self._transition(REJOINING, cycle, replica=target.name)
            target.set_draining(False)
            drained = False
            cycle["outcome"] = "promoted"
            obs.ROLLOUT_CYCLES.labels(outcome="promoted").inc()
            log.info("rollout promoted version %s (replica %s rejoining)",
                     version, target.name)
        except Exception as exc:  # noqa: BLE001 - every failure rolls back
            failed_stage = exc.stage if isinstance(exc, StageError) \
                else stage
            cycle["outcome"] = "rolled_back"
            cycle["rolled_back_at"] = failed_stage
            cycle["error"] = f"{type(exc).__name__}: {exc}"
            obs.ROLLOUT_ROLLBACKS.labels(stage=failed_stage).inc()
            obs.ROLLOUT_CYCLES.labels(outcome="rolled_back").inc()
            recorder_lib.RECORDER.pin(recorder_lib.RECORDER.record_event(
                "serving.rollout.rollback", stage=failed_stage,
                error=cycle["error"],
            ))
            log.warning(
                "rollout ROLLBACK at %s: %s -- candidate discarded, fleet "
                "keeps serving the old generation", failed_stage,
                cycle["error"],
            )
            if drained:
                # the replica must never stay stuck draining
                self._transition(REJOINING, cycle, replica=target.name)
                try:
                    target.set_draining(False)
                except Exception:  # noqa: BLE001 - replica died entirely
                    log.exception("un-drain of %s failed; the membership "
                                  "poll owns its fate now", target.name)
        finally:
            self._record_cycle(cycle, t0)
        return cycle

    def _record_cycle(self, cycle: dict, t0: float) -> None:
        cycle["duration_s"] = round(self._clock() - t0, 3)
        with self._lock:
            self._cycles += 1
            self._current = None
            self.history.append(cycle)
            del self.history[:-self.HISTORY]
            already_idle = self._state == IDLE
        if not already_idle:
            self._transition(IDLE)

    # -- /debug/rollout ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "state": self._state,
                "cycles_total": self._cycles,
                "current": dict(self._current) if self._current else None,
                "replicas": [
                    {
                        "name": t.name,
                        "active_streams": t.active_streams,
                        "version": t.current_version,
                    }
                    for t in self.targets
                ],
                "config": {
                    "shadow_fraction": self.cfg.shadow_fraction,
                    "shadow_min_frames": self.cfg.shadow_min_frames,
                    "candidate_alias": self.cfg.candidate_alias,
                    "gates": {
                        "fixture_min_iou": self.cfg.gate_fixture_min_iou,
                        "fixture_max_curv_err":
                            self.cfg.gate_fixture_max_curv_err,
                        "shadow_min_iou": self.cfg.gate_shadow_min_iou,
                        "shadow_max_curv_err":
                            self.cfg.gate_shadow_max_curv_err,
                        "shadow_max_psi": self.cfg.gate_shadow_max_psi,
                    },
                    "timeouts_s": {
                        "drain": self.cfg.drain_timeout_s,
                        "retrain": self.cfg.retrain_timeout_s,
                        "shadow": self.cfg.shadow_timeout_s,
                        "promote": self.cfg.promote_timeout_s,
                    },
                },
                "history": list(self.history),
            }


def attach_rollout(manager: RolloutManager, servicers,
                   names: Sequence[str] | None = None) -> list[RolloutTarget]:
    """Wire in-process replica servicers to one shared manager: each
    becomes a :class:`RolloutTarget`, and each servicer's drift
    recommendations feed :meth:`RolloutManager.on_recommendation`."""
    targets = []
    for i, servicer in enumerate(servicers):
        name = names[i] if names is not None else f"replica-{i}"
        target = RolloutTarget(name, servicer)
        manager.add_target(target)
        servicer.rollout = manager
        targets.append(target)
    return targets
