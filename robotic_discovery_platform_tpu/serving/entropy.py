"""Host half of the split JPEG decode: entropy decode to coefficient blocks.

At fleet scale the wire carries compressed JPEG, but a full host-side
``cv2.imdecode`` pays dequant + IDCT + upsample + color convert per frame on
the CPU -- work that is matmul/gather-shaped and belongs on the accelerator
(ROADMAP "device-side ingest", nvJPEG-style split). This module implements
the half of the decode that genuinely IS host-shaped: the sequential,
branchy baseline-JPEG marker parse + Huffman entropy decode. It stops at
quantized 8x8 coefficient blocks (natural raster order, int16) plus the
quantization tables; everything downstream -- dequant, IDCT (two integer
basis matmuls over the block axis), chroma upsample, YCbCr->RGB -- runs
next to the fused analyzer in one jit graph (ops/pipeline.decode_coef_batch
with the ops/pallas/decode.py kernel under it).

The device path reproduces libjpeg's fixed-point arithmetic exactly
(``jpeg_idct_islow`` is linear between its two DESCALE roundings, so each
pass is one integer matmul), which is what makes the end-to-end split
decode bitwise-comparable against ``cv2.imdecode`` in the golden tests.

Also defined here: the ``Image.format == FORMAT_COEF`` wire payload
(:func:`pack_coefficients` / :func:`unpack_coefficients`) -- a flat header +
quant tables + int16 planes layout whose server-side parse is nothing but
``np.frombuffer`` views, so clients that already hold coefficients (or
transcode once at the edge via ``client.encode_request(fmt="coef")``) skip
the server's entropy stage entirely and the host does byte routing only.

Error contract: every malformed, truncated, or unsupported stream raises
``ValueError``. Inside ``serving.ingest.DecodePool.decode`` that is the
``serving.ingest.decode`` fault site's guarded path, so a corrupt entropy
stream error-completes the one frame and never kills the worker.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

# JPEG natural-order index for each zigzag position: natural[ZIGZAG] = zz.
ZIGZAG = np.array([
     0,  1,  8, 16,  9,  2,  3, 10,
    17, 24, 32, 25, 18, 11,  4,  5,
    12, 19, 26, 33, 40, 48, 41, 34,
    27, 20, 13,  6,  7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36,
    29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46,
    53, 60, 61, 54, 47, 55, 62, 63], dtype=np.int32)

_M_SOI, _M_EOI, _M_SOS = 0xD8, 0xD9, 0xDA
_M_DQT, _M_DHT, _M_DRI, _M_SOF0 = 0xDB, 0xC4, 0xDD, 0xC0
# Non-baseline SOFs (progressive, arithmetic, lossless...): rejected.
_M_SOF_UNSUPPORTED = frozenset(
    (0xC1, 0xC2, 0xC3, 0xC5, 0xC6, 0xC7, 0xC9, 0xCA, 0xCB, 0xCD, 0xCE,
     0xCF)
)

SUBSAMPLINGS = ("444", "420", "422")

# -- coefficient wire format (Image.format == 2) -----------------------------
#
#   offset  size  field
#   0       4     magic b"RDC1"
#   4       1     version (1)
#   5       1     subsampling code (index into SUBSAMPLINGS)
#   6       2     reserved (0)
#   8       2     height (LE u16)
#   10      2     width (LE u16)
#   12      4     reserved (0)
#   16      128   luma quant table, [64] LE u16, natural order
#   144     128   chroma quant table, [64] LE u16, natural order
#   272     ...   Y plane   [by*bx, 64] LE i16, natural order, block raster
#   ...     ...   Cb plane  [cby*cbx, 64] LE i16
#   ...     ...   Cr plane  [cby*cbx, 64] LE i16
#
# Block counts are derived from (height, width, subsampling), never shipped.
# The 16-byte header keeps every plane 2-byte aligned and the first plane
# 16-byte aligned, so unpack is pure np.frombuffer views into the gRPC
# message buffer -- zero copies, zero per-pixel host work.
_COEF_MAGIC = b"RDC1"
_COEF_VERSION = 1
_COEF_HEADER = struct.Struct("<4sBBHHHI")  # 16 bytes


@dataclasses.dataclass(frozen=True)
class CoefficientFrame:
    """Entropy-decoded JPEG: quantized coefficient blocks + quant tables.

    ``y``/``cb``/``cr`` are ``[n_blocks, 64] int16`` QUANTIZED coefficients
    in natural (row-major) order -- the de-zigzag happens at parse time so
    the device half is pure matmuls with no gathers. ``qy``/``qc`` are the
    ``[64] uint16`` quant tables, natural order. Dequantization is
    deliberately NOT applied on the host: it rides fused with the IDCT
    matmuls on the device (ops/pallas/decode.dequant_idct).
    """

    height: int
    width: int
    subsampling: str          # one of SUBSAMPLINGS
    y: np.ndarray             # [y_blocks_h * y_blocks_w, 64] int16
    cb: np.ndarray            # [c_blocks_h * c_blocks_w, 64] int16
    cr: np.ndarray            # [c_blocks_h * c_blocks_w, 64] int16
    qy: np.ndarray            # [64] uint16
    qc: np.ndarray            # [64] uint16

    @property
    def shape(self) -> tuple:
        """(h, w, 3) -- lets frame-shape grouping treat it like an image."""
        return (self.height, self.width, 3)

    @property
    def nbytes(self) -> int:
        return (self.y.nbytes + self.cb.nbytes + self.cr.nbytes
                + self.qy.nbytes + self.qc.nbytes)


def block_grids(height: int, width: int, subsampling: str) -> tuple:
    """((y_bh, y_bw), (c_bh, c_bw)) block-grid dims for a frame geometry."""
    if subsampling not in SUBSAMPLINGS:
        raise ValueError(
            f"unsupported subsampling {subsampling!r} "
            f"(choose from {SUBSAMPLINGS})"
        )
    sh, sv = {"444": (1, 1), "420": (2, 2), "422": (2, 1)}[subsampling]
    mcux = -(-width // (8 * sh))
    mcuy = -(-height // (8 * sv))
    return (mcuy * sv, mcux * sh), (mcuy, mcux)


# -- Huffman + bit reading ----------------------------------------------------


class _HuffTable:
    """Canonical Huffman table: (code length, code) -> symbol."""

    __slots__ = ("lut",)

    def __init__(self, counts, symbols):
        if sum(counts) != len(symbols):
            raise ValueError("DHT counts/symbols mismatch")
        self.lut = {}
        code = 0
        k = 0
        for length in range(1, 17):
            for _ in range(counts[length - 1]):
                self.lut[(length, code)] = symbols[k]
                k += 1
                code += 1
            if code > (1 << length):
                raise ValueError("over-subscribed Huffman table")
            code <<= 1


class _BitReader:
    """MSB-first reader over the entropy-coded segment.

    Handles 0xFF00 byte stuffing; any bare marker or end-of-buffer inside
    the scan raises ValueError (truncated/corrupt entropy stream).
    """

    __slots__ = ("data", "pos", "acc", "nbits")

    def __init__(self, data: bytes, pos: int):
        self.data = data
        self.pos = pos
        self.acc = 0
        self.nbits = 0

    def _fill(self):
        data, pos = self.data, self.pos
        if pos >= len(data):
            raise ValueError("truncated entropy stream: ran out of bytes")
        b = data[pos]
        if b == 0xFF:
            if pos + 1 >= len(data):
                raise ValueError("truncated entropy stream: dangling 0xFF")
            nxt = data[pos + 1]
            if nxt != 0x00:
                raise ValueError(
                    "truncated entropy stream: marker 0x%02X inside scan"
                    % nxt
                )
            self.pos = pos + 2
        else:
            self.pos = pos + 1
        self.acc = ((self.acc << 8) | b) & 0xFFFFFF
        self.nbits += 8

    def bit(self) -> int:
        if self.nbits == 0:
            self._fill()
        self.nbits -= 1
        return (self.acc >> self.nbits) & 1

    def bits(self, n: int) -> int:
        while self.nbits < n:
            self._fill()
        self.nbits -= n
        return (self.acc >> self.nbits) & ((1 << n) - 1)

    def restart(self, idx: int):
        """Byte-align and consume the expected RSTn marker."""
        self.nbits = 0
        self.acc = 0
        data, pos = self.data, self.pos
        if pos + 1 >= len(data) or data[pos] != 0xFF:
            raise ValueError("restart marker missing")
        while data[pos + 1] == 0xFF:  # optional fill bytes
            pos += 1
            if pos + 1 >= len(data):
                raise ValueError("restart marker missing")
        if data[pos + 1] != 0xD0 + (idx & 7):
            raise ValueError(
                "restart marker out of sequence: 0x%02X" % data[pos + 1]
            )
        self.pos = pos + 2

    def decode(self, table: _HuffTable) -> int:
        code = 0
        lut = table.lut
        for length in range(1, 17):
            code = (code << 1) | self.bit()
            sym = lut.get((length, code))
            if sym is not None:
                return sym
        raise ValueError("invalid Huffman code in entropy stream")


def _extend(v: int, t: int) -> int:
    """JPEG EXTEND: map a t-bit magnitude to its signed value."""
    if t and v < (1 << (t - 1)):
        return v - (1 << t) + 1
    return v


# -- marker parse + scan decode ----------------------------------------------


def parse_jpeg(data: bytes) -> CoefficientFrame:
    """Entropy-decode a baseline JPEG to quantized coefficient blocks.

    Supports the camera-wire subset: 8-bit baseline sequential (SOF0),
    3-component YCbCr with 4:4:4 / 4:2:0 / 4:2:2 sampling, restart
    markers, 8- and 16-bit quant tables. Everything else (progressive,
    arithmetic coding, grayscale, CMYK, 12-bit) raises ValueError -- the
    caller's cv2 path stays the fallback for exotic content.
    """
    if len(data) < 4 or data[0] != 0xFF or data[1] != _M_SOI:
        raise ValueError("not a JPEG: missing SOI marker")
    pos = 2
    n = len(data)
    qtables = {}
    dc_tables, ac_tables = {}, {}
    restart_interval = 0
    frame = None
    while pos < n:
        if data[pos] != 0xFF:
            raise ValueError("corrupt JPEG: marker sync lost")
        while pos < n and data[pos] == 0xFF:
            pos += 1
        if pos >= n:
            raise ValueError("truncated JPEG: no SOS before end of data")
        marker = data[pos]
        pos += 1
        if marker == _M_EOI:
            raise ValueError("corrupt JPEG: EOI before SOS")
        if marker == _M_SOI or 0xD0 <= marker <= 0xD7:
            continue
        if marker in _M_SOF_UNSUPPORTED:
            raise ValueError(
                "unsupported JPEG (SOF 0x%02X): baseline sequential only"
                % marker
            )
        if pos + 2 > n:
            raise ValueError("truncated JPEG: segment header cut off")
        seglen = (data[pos] << 8) | data[pos + 1]
        if seglen < 2 or pos + seglen > n:
            raise ValueError("corrupt JPEG: bad segment length")
        seg = data[pos + 2:pos + seglen]
        if marker == _M_DQT:
            _parse_dqt(seg, qtables)
        elif marker == _M_DHT:
            _parse_dht(seg, dc_tables, ac_tables)
        elif marker == _M_DRI:
            if len(seg) < 2:
                raise ValueError("corrupt JPEG: short DRI segment")
            restart_interval = (seg[0] << 8) | seg[1]
        elif marker == _M_SOF0:
            frame = _parse_sof0(seg)
        elif marker == _M_SOS:
            if frame is None:
                raise ValueError("corrupt JPEG: SOS before SOF0")
            scan = _parse_sos(seg, frame)
            return _decode_scan(
                data, pos + seglen, frame, scan, qtables, dc_tables,
                ac_tables, restart_interval,
            )
        pos += seglen
    raise ValueError("truncated JPEG: no SOS marker found")


def _parse_dqt(seg, qtables):
    i = 0
    while i < len(seg):
        pq, tq = seg[i] >> 4, seg[i] & 15
        i += 1
        if pq == 0:
            if i + 64 > len(seg):
                raise ValueError("corrupt JPEG: short DQT segment")
            q = np.frombuffer(seg, np.uint8, 64, i).astype(np.uint16)
            i += 64
        elif pq == 1:
            if i + 128 > len(seg):
                raise ValueError("corrupt JPEG: short DQT segment")
            q = np.frombuffer(seg, ">u2", 64, i).astype(np.uint16)
            i += 128
        else:
            raise ValueError("corrupt JPEG: bad DQT precision")
        qtables[tq] = q  # zigzag order; de-zigzagged at scan end


def _parse_dht(seg, dc_tables, ac_tables):
    i = 0
    while i < len(seg):
        if i + 17 > len(seg):
            raise ValueError("corrupt JPEG: short DHT segment")
        tc, th = seg[i] >> 4, seg[i] & 15
        counts = list(seg[i + 1:i + 17])
        i += 17
        total = sum(counts)
        if i + total > len(seg):
            raise ValueError("corrupt JPEG: short DHT symbol list")
        symbols = list(seg[i:i + total])
        i += total
        if tc not in (0, 1):
            raise ValueError("corrupt JPEG: bad DHT class")
        (dc_tables if tc == 0 else ac_tables)[th] = _HuffTable(
            counts, symbols
        )


def _parse_sof0(seg):
    if len(seg) < 6:
        raise ValueError("corrupt JPEG: short SOF0 segment")
    if seg[0] != 8:
        raise ValueError("unsupported JPEG: only 8-bit precision")
    height = (seg[1] << 8) | seg[2]
    width = (seg[3] << 8) | seg[4]
    ncomp = seg[5]
    if ncomp != 3:
        raise ValueError(
            "unsupported JPEG: %d components (YCbCr only)" % ncomp
        )
    if len(seg) < 6 + 3 * ncomp:
        raise ValueError("corrupt JPEG: short SOF0 component list")
    comps = []
    for c in range(ncomp):
        comps.append({
            "id": seg[6 + 3 * c],
            "h": seg[7 + 3 * c] >> 4,
            "v": seg[7 + 3 * c] & 15,
            "tq": seg[8 + 3 * c],
        })
    y, cb, cr = comps
    key = (y["h"], y["v"], cb["h"], cb["v"], cr["h"], cr["v"])
    subsampling = {
        (1, 1, 1, 1, 1, 1): "444",
        (2, 2, 1, 1, 1, 1): "420",
        (2, 1, 1, 1, 1, 1): "422",
    }.get(key)
    if subsampling is None:
        raise ValueError(
            "unsupported JPEG sampling factors %r (444/420/422 only)"
            % (key,)
        )
    if height == 0 or width == 0:
        raise ValueError("corrupt JPEG: zero image dimension")
    return {"h": height, "w": width, "comps": comps,
            "subsampling": subsampling}


def _parse_sos(seg, frame):
    if len(seg) < 1 or seg[0] != 3:
        raise ValueError("unsupported JPEG scan: interleaved YCbCr only")
    if len(seg) < 1 + 2 * 3:
        raise ValueError("corrupt JPEG: short SOS segment")
    scan = []
    for c in range(3):
        scan.append({
            "id": seg[1 + 2 * c],
            "dc": seg[2 + 2 * c] >> 4,
            "ac": seg[2 + 2 * c] & 15,
        })
    ids = [s["id"] for s in scan]
    if ids != [c["id"] for c in frame["comps"]]:
        raise ValueError("unsupported JPEG scan: component order differs")
    return scan


def _decode_scan(data, pos, frame, scan, qtables, dc_tables, ac_tables,
                 restart_interval):
    height, width = frame["h"], frame["w"]
    comps = frame["comps"]
    hmax = max(c["h"] for c in comps)
    vmax = max(c["v"] for c in comps)
    mcux = -(-width // (8 * hmax))
    mcuy = -(-height // (8 * vmax))
    plan = []
    for comp, sc in zip(comps, scan):
        if sc["dc"] not in dc_tables or sc["ac"] not in ac_tables:
            raise ValueError("corrupt JPEG: scan references missing DHT")
        if comp["tq"] not in qtables:
            raise ValueError("corrupt JPEG: component references missing "
                             "DQT")
        bx = mcux * comp["h"]
        plan.append({
            "h": comp["h"], "v": comp["v"], "bx": bx,
            "dc": dc_tables[sc["dc"]], "ac": ac_tables[sc["ac"]],
            "coef": np.zeros((mcuy * comp["v"] * bx, 64), np.int32),
        })

    reader = _BitReader(data, pos)
    preds = [0, 0, 0]
    n_mcu = mcux * mcuy
    rst_idx = 0
    for mcu in range(n_mcu):
        if restart_interval and mcu and mcu % restart_interval == 0:
            reader.restart(rst_idx)
            rst_idx += 1
            preds = [0, 0, 0]
        my, mx = divmod(mcu, mcux)
        for ci, comp in enumerate(plan):
            for v_in in range(comp["v"]):
                row = (my * comp["v"] + v_in) * comp["bx"] + mx * comp["h"]
                for h_in in range(comp["h"]):
                    preds[ci] = _decode_block(
                        reader, comp["coef"][row + h_in], comp["dc"],
                        comp["ac"], preds[ci],
                    )
    y, cb, cr = plan
    # De-zigzag once per component (one fancy index), clamp to the coded
    # int16 coefficient range, and de-zigzag the quant tables too.
    out = []
    for comp in (y, cb, cr):
        nat = np.zeros_like(comp["coef"], dtype=np.int16)
        nat[:, ZIGZAG] = np.clip(comp["coef"], -32768, 32767)
        out.append(nat)
    qy = np.zeros(64, np.uint16)
    qc = np.zeros(64, np.uint16)
    qy[ZIGZAG] = qtables[comps[0]["tq"]]
    qc[ZIGZAG] = qtables[comps[1]["tq"]]
    if not np.array_equal(
        qtables[comps[1]["tq"]], qtables[comps[2]["tq"]]
    ):
        raise ValueError(
            "unsupported JPEG: Cb/Cr use different quant tables"
        )
    return CoefficientFrame(
        height=height, width=width, subsampling=frame["subsampling"],
        y=out[0], cb=out[1], cr=out[2], qy=qy, qc=qc,
    )


def _decode_block(reader, block, dc_table, ac_table, pred):
    """Decode one 8x8 block (zigzag order) into ``block``; returns the new
    DC predictor."""
    t = reader.decode(dc_table)
    if t > 11:
        raise ValueError("corrupt JPEG: DC magnitude category > 11")
    pred += _extend(reader.bits(t), t) if t else 0
    block[0] = pred
    k = 1
    while k < 64:
        rs = reader.decode(ac_table)
        run, size = rs >> 4, rs & 15
        if size == 0:
            if run == 15:  # ZRL: sixteen zeros
                k += 16
                continue
            break  # EOB
        k += run
        if k > 63:
            raise ValueError("corrupt JPEG: AC index overruns the block")
        block[k] = _extend(reader.bits(size), size)
        k += 1
    return pred


# -- coefficient wire payload -------------------------------------------------


def pack_coefficients(frame: CoefficientFrame) -> bytes:
    """Serialize a CoefficientFrame as the ``Image.format == 2`` payload."""
    if frame.subsampling not in SUBSAMPLINGS:
        raise ValueError(
            f"unsupported subsampling {frame.subsampling!r}"
        )
    (ybh, ybw), (cbh, cbw) = block_grids(
        frame.height, frame.width, frame.subsampling
    )
    for name, arr, blocks in (("y", frame.y, ybh * ybw),
                              ("cb", frame.cb, cbh * cbw),
                              ("cr", frame.cr, cbh * cbw)):
        if arr.shape != (blocks, 64):
            raise ValueError(
                f"{name} plane shape {arr.shape} != ({blocks}, 64)"
            )
    header = _COEF_HEADER.pack(
        _COEF_MAGIC, _COEF_VERSION, SUBSAMPLINGS.index(frame.subsampling),
        0, frame.height, frame.width, 0,
    )
    return b"".join((
        header,
        np.ascontiguousarray(frame.qy, "<u2").tobytes(),
        np.ascontiguousarray(frame.qc, "<u2").tobytes(),
        np.ascontiguousarray(frame.y, "<i2").tobytes(),
        np.ascontiguousarray(frame.cb, "<i2").tobytes(),
        np.ascontiguousarray(frame.cr, "<i2").tobytes(),
    ))


def unpack_coefficients(data: bytes) -> CoefficientFrame:
    """Parse a format=2 payload into zero-copy views of ``data``.

    The hot-path cost is one struct unpack plus five ``np.frombuffer``
    views -- no per-pixel work, which is the entire point of the format:
    the host routes bytes, the device decodes.
    """
    if len(data) < _COEF_HEADER.size:
        raise ValueError("coefficient payload too short for header")
    magic, version, sub_code, _, height, width, _ = _COEF_HEADER.unpack(
        data[:_COEF_HEADER.size]
    )
    if magic != _COEF_MAGIC:
        raise ValueError("coefficient payload: bad magic")
    if version != _COEF_VERSION:
        raise ValueError(
            "coefficient payload: unsupported version %d" % version
        )
    if sub_code >= len(SUBSAMPLINGS):
        raise ValueError("coefficient payload: bad subsampling code")
    if height == 0 or width == 0:
        raise ValueError("coefficient payload: zero image dimension")
    subsampling = SUBSAMPLINGS[sub_code]
    (ybh, ybw), (cbh, cbw) = block_grids(height, width, subsampling)
    ny, nc = ybh * ybw, cbh * cbw
    want = _COEF_HEADER.size + 2 * 128 + 2 * (ny + 2 * nc) * 64
    if len(data) != want:
        raise ValueError(
            "coefficient payload: %d bytes, expected %d for %dx%d %s"
            % (len(data), want, height, width, subsampling)
        )
    off = _COEF_HEADER.size
    qy = np.frombuffer(data, "<u2", 64, off)
    qc = np.frombuffer(data, "<u2", 64, off + 128)
    off += 256
    y = np.frombuffer(data, "<i2", ny * 64, off).reshape(ny, 64)
    off += ny * 128
    cb = np.frombuffer(data, "<i2", nc * 64, off).reshape(nc, 64)
    off += nc * 128
    cr = np.frombuffer(data, "<i2", nc * 64, off).reshape(nc, 64)
    return CoefficientFrame(
        height=height, width=width, subsampling=subsampling,
        y=y, cb=cb, cr=cr, qy=qy, qc=qc,
    )
