"""Capacity planner + drain-driven autoscaler for the elastic fleet.

The manual loop this closes: a human reads LOADBENCH.json (what one
replica sustains inside the SLO), eyeballs ``GET /federate`` (what the
fleet is being asked to do right now), and decides how many replicas to
run. The planner is that arithmetic as code; the autoscaler is the
actuator that carries its recommendation out through machinery every
prior PR already hardened:

- **capacity** comes from the measured load bench
  (:meth:`CapacityModel.from_loadbench`): the best goodput any
  within-violation-budget row sustained, with the row's chips/placement
  and the Pallas bench's precision recommendation riding along -- so a
  plan names the full serving config (replicas, chips, precision,
  dispatch mode, batching window), not just a count;
- **demand** comes from the live ``/federate`` roll-ups the front-end
  already computes (``rdp_fleet_model_arrival_rate`` summed over models,
  ``rdp_fleet_burn{stat="max"}`` as the is-it-already-hurting signal);
- **actions** ride existing paths: scale-up spawns a replica that
  self-registers a membership lease (serving/replica.py spawner +
  serving/fleet.py LeaseClient -- the front-end needs no config edit);
  scale-down sends the Drain RPC to the least-loaded member, which takes
  it out of NEW-stream placement through the exact PR 13
  ``set_draining`` path while its in-flight streams finish;
- **discipline** is the PR 7 controller idiom: a scale signal must hold
  ``sustain_s`` before anything fires, every action is followed by a
  ``cooldown_s`` sleep, and only one action is ever in flight -- the
  fleet steps, it never flaps. Every decision (including the holds) is
  journaled; every ACTION is also counted
  (``rdp_autoscaler_actions_total``) and pinned in the flight recorder,
  so the incident view shows why the fleet changed shape.

Everything is injectable (clock, observe/spawn/drain callables), so the
whole control loop runs against fakes in tests; jax- and grpc-free like
the rest of the front-end plane.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable

from robotic_discovery_platform_tpu.observability import (
    events,
    families,
    instruments as obs,
    journal as journal_lib,
    recorder as recorder_lib,
)
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: default violation-rate ceiling a bench row must beat to count as
#: "sustainable" capacity (matches the load bench's SLO budget)
VIOLATION_BUDGET = 0.05

#: the no-bench fallback: deliberately conservative so a misplaced
#: LOADBENCH.json over-provisions instead of under-provisioning
DEFAULT_GOODPUT_RPS = 20.0

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


# -- capacity ----------------------------------------------------------------


@dataclass(frozen=True)
class CapacityModel:
    """What ONE replica sustains inside the SLO, fit from the benches."""

    goodput_rps: float
    p99_ms: float = 0.0
    slo_ms: float = 0.0
    chips: int = 1
    placement: str = "shared"
    precision: str = "f32"
    source: str = "default"

    @classmethod
    def default(cls) -> "CapacityModel":
        return cls(goodput_rps=DEFAULT_GOODPUT_RPS)

    @classmethod
    def from_loadbench(cls, path: str | Path, *,
                       violation_budget: float = VIOLATION_BUDGET,
                       precision: str = "f32") -> "CapacityModel":
        """The best goodput any within-budget row sustained, with that
        row's chips/placement. Raises on an unreadable/empty bench."""
        data = json.loads(Path(path).read_text())
        best = None
        for row in data.get("rows", []):
            try:
                rate = float(row.get("goodput_rps", 0.0))
                violations = float(row.get("violation_rate", 1.0))
            except (TypeError, ValueError):
                continue
            if violations > violation_budget or rate <= 0.0:
                continue
            if best is None or rate > float(best.get("goodput_rps", 0.0)):
                best = row
        if best is None:
            raise ValueError(
                f"{path}: no row within violation budget "
                f"{violation_budget:g}")
        return cls(
            goodput_rps=float(best["goodput_rps"]),
            p99_ms=float(best.get("p99_ms") or 0.0),
            slo_ms=float(best.get("slo_ms")
                         or data.get("slo_ms") or 0.0),
            chips=int(best.get("chips") or 1),
            placement=str(best.get("placement") or "shared"),
            precision=precision,
            source=str(path),
        )

    @classmethod
    def resolve(cls, configured_path: str = "",
                *, root: str | Path = ".") -> "CapacityModel":
        """The planner's boot-time fit: the configured LOADBENCH path,
        else ``<root>/LOADBENCH.json``, else the conservative default.
        The Pallas bench (``<root>/PALLASBENCH.json``), when present,
        contributes the precision recommendation (a bf16-ingest kernel
        bench means the measured capacity assumed that tier)."""
        precision = "f32"
        pallas = Path(root) / "PALLASBENCH.json"
        try:
            dtype = str(json.loads(pallas.read_text()).get("dtype", ""))
            if "bfloat16" in dtype or "bf16" in dtype:
                precision = "bf16"
        except (OSError, ValueError):
            pass
        candidates = ([configured_path] if configured_path.strip()
                      else []) + [str(Path(root) / "LOADBENCH.json")]
        for candidate in candidates:
            try:
                return cls.from_loadbench(candidate, precision=precision)
            except (OSError, ValueError, KeyError) as exc:
                log.debug("capacity fit from %s failed: %s",
                          candidate, exc)
        return cls(goodput_rps=DEFAULT_GOODPUT_RPS, precision=precision)


# -- demand ------------------------------------------------------------------


def parse_federate_rollups(text: str) -> dict:
    """Pull the planner's demand inputs out of a ``GET /federate``
    exposition payload: summed per-model arrival rate
    (``rdp_fleet_model_arrival_rate``), the max-burn roll-up
    (``rdp_fleet_burn{stat="max"}``), and the live-member gauge. Tolerant
    of missing families (a cold front-end federates before any scrape)."""
    demand = 0.0
    burn_max = 0.0
    live = None
    rates: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        if name == families.FLEET_MODEL_ARRIVAL_RATE:
            model = labels.get("model", "")
            rates[model] = rates.get(model, 0.0) + value
        elif name == families.FLEET_BURN and labels.get("stat") == "max":
            burn_max = max(burn_max, value)
        elif name == families.FLEET_REPLICAS_LIVE and "replica" not in labels:
            live = int(value)
    demand = sum(rates.values())
    return {"demand_rps": demand, "burn_max": burn_max,
            "live": live, "rates": rates}


# -- the plan ----------------------------------------------------------------


@dataclass(frozen=True)
class Plan:
    """One planning verdict: the cheapest config meeting the target SLO
    at the observed demand, and how it compares to what is running."""

    target_replicas: int
    live_replicas: int
    demand_rps: float
    burn_max: float
    per_replica_rps: float
    headroom: float
    chips: int
    precision: str
    dispatch_mode: str
    window_ms: float
    recommendation: str  # "scale_up" | "scale_down" | "hold"
    reason: str

    def to_dict(self) -> dict:
        return {
            "target_replicas": self.target_replicas,
            "live_replicas": self.live_replicas,
            "demand_rps": round(self.demand_rps, 3),
            "burn_max": round(self.burn_max, 3),
            "per_replica_rps": round(self.per_replica_rps, 3),
            "headroom": self.headroom,
            "chips": self.chips,
            "precision": self.precision,
            "dispatch_mode": self.dispatch_mode,
            "window_ms": self.window_ms,
            "recommendation": self.recommendation,
            "reason": self.reason,
        }


def plan(demand_rps: float, live_replicas: int, *,
         capacity: CapacityModel, headroom: float = 0.7,
         burn_max: float = 0.0, min_replicas: int = 1,
         max_replicas: int = 4, window_ms: float = 2.0) -> Plan:
    """The planner's arithmetic, journaled and gauged. ``headroom`` is
    the utilization ceiling: capacity is derated so the plan leaves
    burst room (0.7 = plan to run at 70% of measured goodput). A burning
    fleet (``burn_max >= 1``: the SLO error budget is spent) forces at
    least one replica of growth even when the arrival-rate arithmetic
    says the fleet is big enough -- demand says "fits", the SLO says
    "doesn't", and the SLO is the contract."""
    headroom = min(max(headroom, 0.05), 1.0)
    sustainable = max(capacity.goodput_rps * headroom, 1e-9)
    needed = max(1, math.ceil(demand_rps / sustainable)) if demand_rps > 0 \
        else min_replicas
    reason = (f"demand {demand_rps:.1f} rps / "
              f"({capacity.goodput_rps:.1f} rps x {headroom:g} headroom)")
    if burn_max >= 1.0 and needed <= live_replicas:
        needed = live_replicas + 1
        reason = (f"burn {burn_max:.2f} >= 1: error budget spent, "
                  "growing past the demand fit")
    target = min(max(needed, min_replicas), max_replicas)
    if target != needed:
        reason += f"; clamped to [{min_replicas}, {max_replicas}]"
    if target > live_replicas:
        recommendation = "scale_up"
    elif target < live_replicas:
        recommendation = "scale_down"
    else:
        recommendation = "hold"
    verdict = Plan(
        target_replicas=target,
        live_replicas=live_replicas,
        demand_rps=demand_rps,
        burn_max=burn_max,
        per_replica_rps=capacity.goodput_rps,
        headroom=headroom,
        chips=capacity.chips,
        precision=capacity.precision,
        dispatch_mode=capacity.placement,
        window_ms=window_ms,
        recommendation=recommendation,
        reason=reason,
    )
    obs.PLANNER_PLANS.labels(recommendation=recommendation).inc()
    obs.PLANNER_TARGET_REPLICAS.set(target)
    journal_lib.JOURNAL.append(
        events.PLANNER_PLAN, target=target, live=live_replicas,
        demand_rps=f"{demand_rps:.3f}", burn_max=f"{burn_max:.3f}",
        recommendation=recommendation, reason=reason,
    )
    return verdict


# -- the actuator ------------------------------------------------------------


class Autoscaler:
    """PR 7 hysteresis around the planner's recommendation: a non-hold
    recommendation must hold ``sustain_s`` before it becomes an action,
    and after ANY action the scaler sleeps ``cooldown_s``. Pure
    decision-making (no I/O): :meth:`decide` maps (plan, now) to one of
    ``scale_up`` / ``scale_down`` / ``hold_sustain`` / ``hold_cooldown``
    / ``hold_bounds`` / ``hold``, counting every verdict."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 sustain_s: float = 5.0, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.sustain_s = max(0.0, float(sustain_s))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._clock = clock
        self._pending = ""  # the recommendation being sustained
        self._pending_since = 0.0
        self._last_action_at = -math.inf
        self.actions_total = 0

    def decide(self, verdict: Plan) -> str:
        now = self._clock()
        rec = verdict.recommendation
        action = "hold"
        if rec == "hold":
            self._pending = ""
        elif now - self._last_action_at < self.cooldown_s:
            # post-action quiet period: signals are observed (the
            # pending clock keeps running) but nothing fires
            action = "hold_cooldown"
            if rec != self._pending:
                self._pending = rec
                self._pending_since = now
        elif ((rec == "scale_up"
               and verdict.live_replicas >= self.max_replicas)
              or (rec == "scale_down"
                  and verdict.live_replicas <= self.min_replicas)):
            action = "hold_bounds"
            self._pending = ""
        elif rec != self._pending:
            self._pending = rec
            self._pending_since = now
            action = "hold_sustain"
        elif now - self._pending_since < self.sustain_s:
            action = "hold_sustain"
        else:
            action = rec
            self._pending = ""
            self._last_action_at = now
            self.actions_total += 1
        obs.AUTOSCALER_ACTIONS.labels(action=action).inc()
        return action


class ElasticSupervisor:
    """The loop that closes the plan: observe -> plan -> decide -> act.

    Side effects are injected so the whole loop runs against fakes:

    - ``observe()`` -> dict with ``demand_rps``, ``burn_max``, ``live``
      (the front-end supplies the /federate roll-ups + router live
      count);
    - ``scale_up()`` -> str description (spawn ONE self-registering
      replica; its lease registration is what admits it);
    - ``pick_drain()`` -> endpoint of the least-loaded drainable member
      (None = nothing eligible);
    - ``scale_down(endpoint)`` (send the Drain RPC / retire the
      process once idle).

    Every action is journaled (``autoscaler.action``), counted by the
    :class:`Autoscaler`, and pinned in the flight recorder -- incident
    timelines must show why the fleet changed shape."""

    def __init__(self, *, observe: Callable[[], dict],
                 scale_up: Callable[[], str],
                 scale_down: Callable[[str], None],
                 pick_drain: Callable[[], str | None],
                 capacity: CapacityModel | None = None,
                 autoscaler: Autoscaler | None = None,
                 headroom: float = 0.7, window_ms: float = 2.0,
                 poll_s: float = 1.0,
                 flight_recorder: recorder_lib.FlightRecorder | None = None):
        self._observe = observe
        self._scale_up = scale_up
        self._scale_down = scale_down
        self._pick_drain = pick_drain
        self.capacity = capacity or CapacityModel.default()
        self.autoscaler = autoscaler or Autoscaler()
        self.headroom = headroom
        self.window_ms = window_ms
        self.poll_s = max(0.05, float(poll_s))
        self.recorder = (flight_recorder if flight_recorder is not None
                         else recorder_lib.RECORDER)
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self.last_plan: Plan | None = None
        self.last_action = ""
        self.ticks = 0

    # -- one evaluation -------------------------------------------------------

    def tick(self) -> dict:
        """One observe->plan->decide->act pass (public: tests and the
        smoke tool drive the loop deterministically without the
        thread). Returns the tick's full story."""
        observed = self._observe()
        live = int(observed.get("live") or 0)
        verdict = plan(
            float(observed.get("demand_rps") or 0.0), live,
            capacity=self.capacity, headroom=self.headroom,
            burn_max=float(observed.get("burn_max") or 0.0),
            min_replicas=self.autoscaler.min_replicas,
            max_replicas=self.autoscaler.max_replicas,
            window_ms=self.window_ms,
        )
        action = self.autoscaler.decide(verdict)
        detail = ""
        if action == "scale_up":
            detail = self._act(action, verdict, self._scale_up)
        elif action == "scale_down":
            target = self._pick_drain()
            if target is None:
                action = "hold"
                detail = "no drainable member"
                obs.AUTOSCALER_ACTIONS.labels(action=action).inc()
            else:
                detail = self._act(
                    action, verdict,
                    lambda: (self._scale_down(target), target)[1])
        self.last_plan = verdict
        self.last_action = action
        self.ticks += 1
        return {"plan": verdict.to_dict(), "action": action,
                "detail": detail}

    def _act(self, action: str, verdict: Plan,
             effect: Callable[[], str]) -> str:
        """Run one actuation with full evidence: journal entry, pinned
        flight-recorder timeline, and the failure path journaled too
        (a spawn that dies must be visible, not retried silently)."""
        tl = recorder_lib.Timeline(
            events.AUTOSCALER_ACTION,
            labels={"action": action,
                    "target": str(verdict.target_replicas)})
        start_ns = time.monotonic_ns()
        span = tl.span("autoscale", start_ns=start_ns, action=action,
                       reason=verdict.reason)
        try:
            detail = str(effect() or "")
        except Exception as exc:  # noqa: BLE001 - journal, don't crash
            detail = f"failed: {exc}"
            tl.fail(detail)
            log.exception("autoscaler %s failed", action)
        span.end(time.monotonic_ns())
        self.recorder.pin(self.recorder.record(tl))
        journal_lib.JOURNAL.append(
            events.AUTOSCALER_ACTION, action=action,
            target=str(verdict.target_replicas),
            live=str(verdict.live_replicas), detail=detail,
            reason=verdict.reason,
        )
        log.info("autoscaler: %s (%s) -> %s", action, verdict.reason,
                 detail or "ok")
        return detail

    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "ticks": self.ticks,
            "actions_total": self.autoscaler.actions_total,
            "last_action": self.last_action,
            "last_plan": (self.last_plan.to_dict()
                          if self.last_plan is not None else None),
            "capacity": {
                "goodput_rps": self.capacity.goodput_rps,
                "chips": self.capacity.chips,
                "placement": self.capacity.placement,
                "precision": self.capacity.precision,
                "source": self.capacity.source,
            },
        }

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.tick()
                except Exception:  # pragma: no cover - keep planning
                    log.exception("autoscaler tick failed")

        self._thread = threading.Thread(
            target=loop, name="fleet-autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
