"""Streaming client: FrameSource -> gRPC -> (optional) live overlay UI.

Rebuild of the reference client (reference: services/vision_analysis/
client.py): JPEG-encodes color / PNG-encodes depth (lossy vs lossless, the
reference's deliberate asymmetry, client.py:63-67), streams them over the
bidirectional rpc, smooths curvature over a 10-frame window, and -- when a
display is requested -- alpha-blends the returned mask and reprojects the 3D
spline with the calibrated intrinsics. Headless operation is first-class
(the reference hard-requires a GUI): results are returned as a list so
tests, benches, and batch jobs can consume the same path.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from dataclasses import dataclass

import grpc
import numpy as np

from robotic_discovery_platform_tpu.io.frames import (
    FrameSource,
    SyntheticSource,
    iter_frames,
    load_calibration,
)
from robotic_discovery_platform_tpu.observability import trace
from robotic_discovery_platform_tpu.resilience import RetryPolicy, inject
from robotic_discovery_platform_tpu.resilience import (
    sites as fault_sites,
)
from robotic_discovery_platform_tpu.serving import egress
from robotic_discovery_platform_tpu.serving.proto import vision_grpc, vision_pb2
from robotic_discovery_platform_tpu.utils.config import ClientConfig
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclass
class FrameResult:
    mean_curvature: float
    max_curvature: float
    smoothed_mean: float
    smoothed_max: float
    status: str
    mask_coverage: float
    proc_time_ms: float
    #: the raw response ``mask`` payload (PNG bytes on the legacy wire;
    #: a packed-bits / RLE payload when the request asked for one)
    mask_png: bytes
    spline_points: np.ndarray  # [N, 3]
    frame_bgr: np.ndarray | None = None
    #: the decoded [H, W] uint8 0/1 mask when the response carried a
    #: packed payload (serving/egress.decode_mask_wire) -- the EXACT
    #: mask the analyzer emitted; None on the legacy PNG wire
    mask: np.ndarray | None = None


def encode_request(color_bgr: np.ndarray, depth: np.ndarray,
                   fmt: str = "encoded",
                   model: str = "",
                   mask_format: int = 0) -> vision_pb2.AnalysisRequest:
    """Build one wire request from a BGR frame + z16 depth frame.

    ``fmt="encoded"`` (default) is the historical JPEG/PNG pair (lossy
    color, lossless depth -- the reference's deliberate asymmetry).
    ``fmt="raw"`` sends the fleet-internal fast path instead: raw RGB8 /
    little-endian z16 payloads with ``Image.format = 1``, which the
    server maps as zero-copy views and never runs through ``imdecode``
    (serving/ingest.py) -- more ingress bytes, near-zero server decode.

    ``fmt="coef"`` is the split-decode wire: the client JPEG-encodes the
    color frame once, entropy-decodes it at the edge
    (serving/entropy.py), and ships the quantized coefficient blocks as
    ``Image.format = 2``. The server's whole host-side color decode is
    then ``np.frombuffer`` views, and dequant + IDCT + chroma upsample +
    color convert run fused ahead of the analyzer on the accelerator --
    the decoded image never exists on the server's host. Wire size sits
    between JPEG and raw (coefficients are sparse but uncompressed);
    depth rides raw z16. The decoded pixels are bitwise identical to the
    server decoding the same JPEG with ``cv2.imdecode``.

    ``model`` selects the model-zoo entry by name (serving/zoo.py);
    "" (default) is the server's default model, and serializes to ZERO
    extra wire bytes -- a legacy request is bitwise identical.

    ``mask_format`` selects the RESPONSE mask encoding
    (serving/egress.py): 0 (default) is the historical PNG bytes --
    serializing to zero extra wire bytes, so a legacy request stays
    bitwise identical -- 1 asks for the packed-bits payload and 2 for
    RLE; both decode back to the exact uint8 mask
    (``FrameResult.mask``), and the spline rides ``packed_spline`` as
    f32 triples instead of per-point Point3D messages."""
    import cv2

    h, w = color_bgr.shape[:2]
    if fmt == "coef":
        from robotic_discovery_platform_tpu.serving import entropy, ingest

        ok_c, jpg = cv2.imencode(".jpg", color_bgr)
        if not ok_c:
            raise ValueError("frame encode failed")
        payload = entropy.pack_coefficients(
            entropy.parse_jpeg(jpg.tobytes())
        )
        z16 = np.ascontiguousarray(depth, dtype="<u2")
        return vision_pb2.AnalysisRequest(
            color_image=vision_pb2.Image(
                data=payload, width=w, height=h,
                format=ingest.FORMAT_COEF,
            ),
            depth_image=vision_pb2.Image(
                data=z16.tobytes(), width=w, height=h,
                format=ingest.FORMAT_RAW,
            ),
            model=model,
            mask_format=mask_format,
        )
    if fmt == "raw":
        from robotic_discovery_platform_tpu.serving import ingest

        rgb = cv2.cvtColor(color_bgr, cv2.COLOR_BGR2RGB)
        z16 = np.ascontiguousarray(depth, dtype="<u2")
        return vision_pb2.AnalysisRequest(
            color_image=vision_pb2.Image(
                data=rgb.tobytes(), width=w, height=h,
                format=ingest.FORMAT_RAW,
            ),
            depth_image=vision_pb2.Image(
                data=z16.tobytes(), width=w, height=h,
                format=ingest.FORMAT_RAW,
            ),
            model=model,
            mask_format=mask_format,
        )
    if fmt != "encoded":
        raise ValueError(f"unknown request format {fmt!r}; "
                         "expected 'encoded', 'raw', or 'coef'")
    ok_c, jpg = cv2.imencode(".jpg", color_bgr)
    ok_d, png = cv2.imencode(".png", depth)
    if not (ok_c and ok_d):
        raise ValueError("frame encode failed")
    return vision_pb2.AnalysisRequest(
        color_image=vision_pb2.Image(data=jpg.tobytes(), width=w, height=h),
        depth_image=vision_pb2.Image(data=png.tobytes(), width=w, height=h),
        model=model,
        mask_format=mask_format,
    )


def generate_requests(source: FrameSource, frame_queue: deque,
                      max_frames: int | None = None,
                      mask_format: int = 0):
    for color, depth in iter_frames(source, max_frames):
        frame_queue.append(color)
        yield encode_request(color, depth, mask_format=mask_format)


def overlay(frame_bgr: np.ndarray, result: FrameResult,
            intrinsics: np.ndarray | None, dist: np.ndarray | None) -> np.ndarray:
    """Red mask blend + green reprojected spline + smoothed curvature text
    (reference: client.py:110-136)."""
    import cv2

    vis = frame_bgr.copy()
    if result.mask is not None:
        # packed wire formats arrive pre-decoded as the exact 0/1 mask
        mask = result.mask * np.uint8(255)
        if mask.shape == vis.shape[:2]:
            red = np.zeros_like(vis)
            red[..., 2] = mask
            vis = cv2.addWeighted(vis, 1.0, red, 0.4, 0)
    elif result.mask_png:
        mask = cv2.imdecode(np.frombuffer(result.mask_png, np.uint8),
                            cv2.IMREAD_GRAYSCALE)
        if mask is not None and mask.shape == vis.shape[:2]:
            red = np.zeros_like(vis)
            red[..., 2] = mask
            vis = cv2.addWeighted(vis, 1.0, red, 0.4, 0)
    if intrinsics is not None and len(result.spline_points):
        pts, _ = cv2.projectPoints(
            result.spline_points.astype(np.float64),
            np.zeros(3), np.zeros(3),
            intrinsics, dist if dist is not None else np.zeros(5),
        )
        cv2.polylines(vis, [pts.astype(np.int32).reshape(-1, 1, 2)], False,
                      (0, 255, 0), 2)
    cv2.putText(
        vis,
        f"mean k: {result.smoothed_mean:.3f}  max k: {result.smoothed_max:.3f}",
        (10, 30), cv2.FONT_HERSHEY_SIMPLEX, 0.8, (255, 255, 255), 2,
    )
    return vis


def run_client(
    cfg: ClientConfig = ClientConfig(),
    source: FrameSource | None = None,
    max_frames: int | None = None,
    display: bool = False,
    channel: grpc.Channel | None = None,
    retry: RetryPolicy | None = None,
    mask_format: int = 0,
) -> list[FrameResult]:
    """Stream frames, return per-frame results. ``display=True`` opens the
    live overlay window ('q' quits, reference client.py:138-140).

    ``mask_format`` selects the response mask encoding (0 = legacy PNG,
    1 = packed bits, 2 = RLE; serving/egress.py). Packed responses are
    decoded back to the exact uint8 mask (``FrameResult.mask``) and the
    spline is read off the f32 ``packed_spline`` payload instead of the
    per-point Point3D message loop.

    Stream SETUP rides the shared RetryPolicy: UNAVAILABLE before the
    first response (server restarting, port not up yet) backs off and
    reopens the stream from a reset source. Once any response has
    arrived the stream is stateful (smoothing windows, frame pairing) and
    a failure surfaces to the caller instead of silently re-streaming.
    """
    source = source or SyntheticSource()
    retry = retry or RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                 max_delay_s=2.0)
    intrinsics = dist = None
    try:
        intrinsics, dist, _ = load_calibration(cfg.calibration_path)
    except (FileNotFoundError, KeyError):
        if isinstance(source, SyntheticSource):
            intrinsics = source.intrinsics()
        log.warning("no calibration file at %s", cfg.calibration_path)

    own_channel = channel is None
    if channel is None:
        channel = grpc.insecure_channel(cfg.server_address)
    stub = vision_grpc.VisionAnalysisServiceStub(channel)

    frame_queue: deque = deque(maxlen=cfg.frame_queue_len)
    mean_window: deque = deque(maxlen=cfg.smoothing_window)
    max_window: deque = deque(maxlen=cfg.smoothing_window)
    results: list[FrameResult] = []

    source.start()

    def stream_once():
        inject(fault_sites.CLIENT_STREAM)
        # one stream = one trace: the span's traceparent rides the call
        # metadata, the server adopts it, and both sides' log lines carry
        # the same [trace=...] stamp (a retried stream mints a new trace,
        # so the two attempts are distinguishable in the logs)
        with trace.span("client.stream") as sp:
            log.info("streaming to %s", cfg.server_address)
            responses = stub.AnalyzeActuatorPerformance(
                generate_requests(source, frame_queue, max_frames,
                                  mask_format=mask_format),
                metadata=trace.to_metadata(sp.context),
            )
            for response in responses:
                frame = frame_queue.popleft() if frame_queue else None
                mean_window.append(response.mean_curvature)
                max_window.append(response.max_curvature)
                if response.packed_spline:
                    spline = egress.decode_spline_wire(response.packed_spline)
                else:
                    spline = np.array(
                        [[p.x, p.y, p.z] for p in response.spline_points]
                    ).reshape(-1, 3)
                result = FrameResult(
                    mean_curvature=response.mean_curvature,
                    max_curvature=response.max_curvature,
                    smoothed_mean=float(np.mean(mean_window)),
                    smoothed_max=float(np.mean(max_window)),
                    status=response.status,
                    mask_coverage=response.mask_coverage,
                    proc_time_ms=response.proc_time_ms,
                    mask_png=response.mask,
                    spline_points=spline,
                    frame_bgr=frame,
                    mask=egress.decode_mask_wire(response.mask),
                )
                results.append(result)
                if display and frame is not None:
                    import cv2

                    cv2.imshow("Actuator Analysis (TPU)",
                               overlay(frame, result, intrinsics, dist))
                    if cv2.waitKey(1) & 0xFF == ord("q"):
                        break

    def setup_retryable(exc: BaseException) -> bool:
        # only pre-first-response failures the policy itself would retry
        return not results and retry.retryable(exc)

    def on_retry(attempt: int, exc: BaseException, delay: float) -> None:
        code = exc.code() if hasattr(exc, "code") else exc
        log.warning("stream setup to %s failed (%s); retry %d in %.2fs",
                    cfg.server_address, code, attempt, delay)
        # restart the (deterministic) source and drop stale pairing state
        # so the re-opened stream begins from frame 0 again
        frame_queue.clear()
        mean_window.clear()
        max_window.clear()
        source.start()

    try:
        dataclasses.replace(retry, retryable=setup_retryable).call(
            stream_once, on_retry=on_retry, name="client.stream",
        )
    except grpc.RpcError as exc:
        log.error("rpc failed (%s) -- is the server running at %s?",
                  exc.code() if hasattr(exc, "code") else exc,
                  cfg.server_address)
        raise
    finally:
        source.stop()
        if display:
            import cv2

            cv2.destroyAllWindows()
        if own_channel:
            channel.close()
    return results


if __name__ == "__main__":
    from robotic_discovery_platform_tpu.utils.config import parse_config

    run_client(parse_config().client, display=True)
