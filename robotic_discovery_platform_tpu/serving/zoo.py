"""Model zoo + statistical multiplexing: serve M models over N chips.

The pre-zoo server pairs ONE engine generation with the whole chip mesh.
This module breaks that pairing into two pieces:

- :class:`ModelZoo` -- the served set: M named engine generations
  (models/variants.py catalog), each with its own registry entry,
  precision tier, golden-frame parity gate, drift reference, and SLO
  tracker, all sharing one batch dispatcher and one chip mesh. The
  empty wire ``model`` field resolves to the default entry, so the
  legacy single-model path is a zoo of one -- bitwise identical.

- :class:`ZooPlacer` -- AlpaServe-style placement (PAPERS.md): instead
  of partitioning chips per model, co-locate models whose measured
  arrival-rate peaks ANTI-correlate on shared chips, so each model's
  burst capacity is every chip its quiet neighbors are not using.
  Per-model arrival rates stream into sliding interval windows
  (:class:`RateWindow`); every ``rebalance_s`` the placer recomputes
  pairwise Pearson correlations over the aligned rate series and
  re-places: each model first claims its demand-proportional share of
  chips (preferring chips whose residents' correlated load is lowest --
  anti-correlated residents score negative, so bursty complements
  attract each other), then extends onto every chip whose residents are
  all below the co-location correlation cap. Models with no measured
  correlation yet default to full sharing (pure statistical
  multiplexing until there is evidence of positive correlation);
  ``mode="dedicated"`` pins the static contiguous partition -- the
  comparison leg ``bench_load.py --models`` measures the multiplexing
  win against.

The dispatcher consults ``chips_for(model)`` per launch (one dict read)
and Clockwork's observation (predictable per-model service times) is
what makes the shed/placement decisions sound: the admission estimator
is keyed per (model, bucket) (serving/admission.py), so a cheap aux
ride can never poison the segmenter's service estimate.
"""

from __future__ import annotations

import math
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from robotic_discovery_platform_tpu.models import variants as variants_lib
from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
)
from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

PLACEMENT_MODES = ("shared", "dedicated")

_PLACEMENT_ENV_VAR = "RDP_ZOO_PLACEMENT"


class UnknownModelError(KeyError):
    """A request named a model this zoo does not hold; the server maps
    it to a per-frame ERROR status (the stream stays alive -- a typo'd
    model name is a bad frame, not a dead connection)."""


def resolve_zoo_placement(configured: str) -> str:
    """The effective placement mode: ``RDP_ZOO_PLACEMENT`` when set, else
    ``ServerConfig.zoo_placement``."""
    mode = os.environ.get(_PLACEMENT_ENV_VAR) or configured
    if mode not in PLACEMENT_MODES:
        raise ValueError(
            f"unknown zoo placement {mode!r}; one of {PLACEMENT_MODES}"
        )
    return mode


@dataclass
class ZooEntry:
    """One served zoo model: everything a frame of this model touches,
    plus the bindings the shared dispatcher needs to route to it. The
    DEFAULT entry aliases the server's legacy engine state so the
    single-model path stays byte-for-byte the pre-zoo server."""

    name: str
    variant: variants_lib.ModelVariant
    #: jitted single-frame analyzer (the direct, dispatcher-less path)
    analyze: Any
    variables: Any
    version: int | None
    precision: str = "f32"
    #: pre-transform (f32) pair kept as the parity-gate reference
    pristine: tuple[Any, Any] | None = None
    #: warm-up parity report (None at f32 / pre-warm)
    parity: dict | None = None
    #: per-model drift monitor (monitoring/profile.DriftMonitor) -- the
    #: default entry's monitor is the server's legacy ``self.drift``
    drift: Any = None
    #: per-model SLO tracker (observability/slo.SloTracker) or None
    slo: Any = None
    #: dispatcher bindings: the shared batch analyzer closure plus
    #: optional per-chip / mesh-sharded variants (rebound onto each new
    #: dispatcher generation by the serving layer)
    batch_analyze: Callable | None = None
    per_chip_analyzers: list | None = None
    sharded_analyzer: Callable | None = None
    #: frames served (terminal statuses), for replica stats / planner
    frames_total: int = 0


class ModelZoo:
    """The served model set. Lookup is one dict read; "" resolves to the
    default entry (the legacy wire contract)."""

    def __init__(self, default: str = variants_lib.DEFAULT_MODEL):
        self.default = default
        self._entries: dict[str, ZooEntry] = {}

    def add(self, entry: ZooEntry) -> None:
        self._entries[entry.name] = entry

    def get(self, name: str = "") -> ZooEntry | None:
        return self._entries.get(name or self.default)

    @property
    def default_entry(self) -> ZooEntry | None:
        return self._entries.get(self.default)

    def names(self) -> tuple[str, ...]:
        return tuple(self._entries)

    def extras(self) -> tuple[ZooEntry, ...]:
        """Every entry except the default (the ones the zoo added)."""
        return tuple(e for n, e in self._entries.items()
                     if n != self.default)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, name: str) -> bool:
        return (name or self.default) in self._entries


class RateWindow:
    """Per-model arrival counts over fixed wall-clock intervals: a ring
    of completed-interval counts plus the accumulating current interval.
    NOT thread-safe on its own -- the placer serializes access."""

    def __init__(self, interval_s: float = 1.0, window: int = 60,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = max(1e-3, float(interval_s))
        self.counts: deque[int] = deque(maxlen=max(2, int(window)))
        self._clock = clock
        self._cur = 0
        self._cur_start = clock()

    def _advance(self, now: float) -> None:
        gap = now - self._cur_start
        if gap < self.interval_s:
            return
        steps = int(gap / self.interval_s)
        if steps >= self.counts.maxlen:
            # idle longer than the whole window: it is all zeros now
            self.counts.extend([0] * self.counts.maxlen)
            self._cur = 0
            self._cur_start = now
            return
        self.counts.append(self._cur)
        self._cur = 0
        for _ in range(steps - 1):
            self.counts.append(0)
        self._cur_start += steps * self.interval_s

    def record(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        self._advance(now)
        self._cur += 1

    def series(self, now: float | None = None) -> list[float]:
        """Completed-interval rates (arrivals/sec), oldest first."""
        now = self._clock() if now is None else now
        self._advance(now)
        return [c / self.interval_s for c in self.counts]

    def mean_rate(self, now: float | None = None) -> float:
        s = self.series(now)
        return sum(s) / len(s) if s else 0.0

    def peak_rate(self, now: float | None = None) -> float:
        s = self.series(now)
        return max(s) if s else 0.0


def correlation(a: list[float], b: list[float]) -> float:
    """Pearson correlation over the aligned tails of two rate series
    (0.0 when either is too short or constant -- "no evidence", which
    the placer treats as freely co-locatable)."""
    n = min(len(a), len(b))
    if n < 4:
        return 0.0
    xa, xb = a[-n:], b[-n:]
    ma = sum(xa) / n
    mb = sum(xb) / n
    va = sum((x - ma) ** 2 for x in xa)
    vb = sum((x - mb) ** 2 for x in xb)
    if va <= 0 or vb <= 0:
        return 0.0
    cov = sum((x - ma) * (y - mb) for x, y in zip(xa, xb))
    return cov / math.sqrt(va * vb)


class ZooPlacer:
    """Assign M models to N chips by measured arrival-rate correlation.

    Args:
        models: zoo model names (placement keys).
        chips: mesh width (ring indices 0..chips-1).
        mode: "shared" (correlation-driven co-location) or "dedicated"
            (static contiguous partition -- the comparison baseline).
        interval_s / window: per-model rate-window geometry.
        rebalance_s: how often a recorded arrival may trigger a
            re-placement (0 = every placement is recomputed on demand
            only via :meth:`rebalance`).
        corr_cap: co-location threshold -- a model extends onto a chip
            only when every resident's correlation with it is BELOW this
            (0.25 default: unknown/uncorrelated and anti-correlated
            models share freely; clearly synchronized peaks separate).
        min_share: every model keeps at least this many chips.
        clock: injectable monotonic clock (tests never sleep).
    """

    def __init__(self, models: tuple[str, ...], chips: int, *,
                 mode: str = "shared", interval_s: float = 1.0,
                 window: int = 60, rebalance_s: float = 5.0,
                 corr_cap: float = 0.25, min_share: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if mode not in PLACEMENT_MODES:
            raise ValueError(
                f"unknown zoo placement {mode!r}; one of {PLACEMENT_MODES}"
            )
        self.models = tuple(models)
        self.chips = max(1, int(chips))
        self.mode = mode
        self.corr_cap = float(corr_cap)
        self.min_share = max(1, int(min_share))
        self.rebalance_s = float(rebalance_s)
        self._clock = clock
        self._lock = checked_lock("zoo.placer")
        self._rates = {  # guarded_by: _lock
            m: RateWindow(interval_s, window, clock) for m in self.models
        }
        self._last_rebalance = clock()  # guarded_by: _lock
        self.rebalances = 0  # guarded_by: _lock
        all_chips = tuple(range(self.chips))
        self._placement: dict[str, tuple[int, ...]] = (  # guarded_by: _lock
            self._dedicated() if mode == "dedicated"
            else {m: all_chips for m in self.models}
        )
        self._publish(self._placement)
        obs.ZOO_MODELS.set(len(self.models))

    # -- hot path ------------------------------------------------------------

    def record_arrival(self, model: str) -> None:
        """One arrival for ``model`` (the dispatcher's submit hook): bump
        its rate window and, at most every ``rebalance_s``, recompute the
        placement. O(1) amortized; the rebalance itself is O(M^2 * W)
        over tiny M."""
        now = self._clock()
        placement = None
        with self._lock:
            win = self._rates.get(model)
            if win is None:
                return
            win.record(now)
            if (self.mode == "shared" and self.rebalance_s > 0
                    and now - self._last_rebalance >= self.rebalance_s):
                self._last_rebalance = now
                placement = self._place_locked(now)
        if placement is not None:
            self._adopt(placement)

    def chips_for(self, model: str) -> tuple[int, ...]:
        """The ring indices ``model`` may dispatch to right now (every
        chip for unknown models -- the dispatcher's router still applies
        its own health gating on top)."""
        with self._lock:
            return self._placement.get(model, tuple(range(self.chips)))

    # -- placement -----------------------------------------------------------

    def _dedicated(self) -> dict[str, tuple[int, ...]]:
        """Static contiguous partition: model i gets chips
        [i*N/M, (i+1)*N/M) (at least one each) -- silicon per model, the
        allocation statistical multiplexing beats."""
        n, m = self.chips, max(1, len(self.models))
        out: dict[str, tuple[int, ...]] = {}
        for i, name in enumerate(self.models):
            lo = (i * n) // m
            hi = ((i + 1) * n) // m
            out[name] = tuple(range(lo, max(hi, lo + 1))) or (n - 1,)
        return out

    def correlations(self, now: float | None = None) -> dict[tuple, float]:
        with self._lock:
            return self._correlations_locked(
                self._clock() if now is None else now
            )

    def _correlations_locked(self, now: float) -> dict[tuple, float]:
        series = {m: w.series(now) for m, w in self._rates.items()}
        out: dict[tuple, float] = {}
        names = list(self.models)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                out[(a, b)] = correlation(series[a], series[b])
        return out

    def rebalance(self) -> dict[str, tuple[int, ...]]:
        """Force one re-placement now; returns the adopted placement."""
        with self._lock:
            if self.mode == "dedicated":
                return dict(self._placement)
            self._last_rebalance = self._clock()
            placement = self._place_locked(self._clock())
        self._adopt(placement)
        return placement

    def _place_locked(self, now: float) -> dict[str, tuple[int, ...]]:
        """The AlpaServe-flavored greedy: demand-proportional base shares
        preferring chips whose residents' correlated load is lowest
        (anti-correlation scores negative -- complements attract), then
        free extension onto chips whose residents all sit below the
        co-location cap."""
        corr = self._correlations_locked(now)

        def c(a: str, b: str) -> float:
            return corr.get((a, b), corr.get((b, a), 0.0))

        demand = {m: max(w.mean_rate(now), 1e-9)
                  for m, w in self._rates.items()}
        total = sum(demand.values())
        order = sorted(self.models, key=lambda m: -demand[m])
        residents: list[list[str]] = [[] for _ in range(self.chips)]
        placement: dict[str, tuple[int, ...]] = {}
        for m in order:
            share = max(self.min_share,
                        round(self.chips * demand[m] / total))
            share = min(share, self.chips)
            scored = sorted(
                (sum(c(m, r) * demand[r] for r in residents[i]),
                 len(residents[i]), i)
                for i in range(self.chips)
            )
            take = [i for _, _, i in scored[:share]]
            take += [
                i for _, _, i in scored[share:]
                if all(c(m, r) < self.corr_cap for r in residents[i])
            ]
            for i in take:
                residents[i].append(m)
            placement[m] = tuple(sorted(take))
        return placement

    def _adopt(self, placement: dict[str, tuple[int, ...]]) -> None:
        with self._lock:
            changed = placement != self._placement
            self._placement = placement
            if changed:
                self.rebalances += 1
                n = self.rebalances
        if changed:
            obs.ZOO_REBALANCES.inc()
            journal_lib.JOURNAL.append(
                events.ZOO_REBALANCE, rebalance=n,
                placement=";".join(
                    f"{m}:{','.join(map(str, cs))}"
                    for m, cs in sorted(placement.items())),
            )
            log.info("zoo placement #%d: %s", n,
                     {m: list(cs) for m, cs in placement.items()})
        self._publish(placement)

    def _publish(self, placement: dict[str, tuple[int, ...]]) -> None:
        now = self._clock()
        for m in self.models:
            obs.MODEL_CHIPS.labels(model=m).set(
                len(placement.get(m, ())))
            with self._lock:
                rate = self._rates[m].mean_rate(now)
            obs.MODEL_ARRIVAL_RATE.labels(model=m).set(rate)

    # -- introspection -------------------------------------------------------

    def rates(self) -> dict[str, float]:
        """Per-model mean arrival rate over the window (the capacity
        planner's per-model input, exported on the replica stats RPC)."""
        now = self._clock()
        with self._lock:
            return {m: w.mean_rate(now) for m, w in self._rates.items()}

    def snapshot(self) -> dict:
        """The ``GET /debug/zoo`` placement block."""
        now = self._clock()
        with self._lock:
            placement = {m: list(cs) for m, cs in self._placement.items()}
            rates = {m: round(w.mean_rate(now), 3)
                     for m, w in self._rates.items()}
            peaks = {m: round(w.peak_rate(now), 3)
                     for m, w in self._rates.items()}
            corr = {f"{a}/{b}": round(v, 3)
                    for (a, b), v in self._correlations_locked(now).items()}
            rebalances = self.rebalances
        return {
            "mode": self.mode,
            "chips": self.chips,
            "placement": placement,
            "mean_rate": rates,
            "peak_rate": peaks,
            "correlation": corr,
            "rebalances": rebalances,
            "corr_cap": self.corr_cap,
        }
