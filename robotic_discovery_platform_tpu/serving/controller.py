"""Reactive SLO controller: the platform's overload-control brain.

PR 6 finished the measurement layer (streaming p50-p99.9 summaries, the
``rdp_slo_error_budget_burn`` gauge, per-dispatch span timelines, the
open-loop ``bench_load.py`` harness); ROADMAP's verdict was "the
measurement layer is done; what remains is the controller itself". This
module is that controller, in the InferLine mold: a *planner* chose the
static config (``ServerConfig``), and this *reactive tuner* perturbs it
online from the live signals, never waiting for a redeploy:

- **AIMD in-flight window**: when burn is comfortably low and the backlog
  shows unmet demand, ``max_inflight`` steps up by one (additive
  increase) toward ``inflight_cap``; a sustained burn > ``burn_high``
  halves it (multiplicative decrease) as part of brownout entry -- the
  TCP-shaped response that converges instead of oscillating.
- **Brownout ladder** (entered on sustained burn > ``burn_high``, exited
  symmetrically on sustained burn < ``burn_low``):

  1. shrink the batch window (cut coalescing delay) and halve the
     in-flight window (cut queueing on the device);
  2. shed earlier at admission (raise the dispatcher's
     ``deadline_safety`` so the collector drops frames whose deadline is
     merely *at risk*, not only the doomed ones);
  3. refuse new streams (UNAVAILABLE at stream entry: clients fail over
     to another replica instead of piling onto a breached objective).
     The servicer duty-cycles the refusal (every other stream) so the
     SLO signal keeps flowing and the symmetric exit stays reachable --
     refusing everything would starve the burn gauge at its peak and
     freeze the ladder at the top rung.

- **Bucket-floor tuning**: a deep backlog raises the padded-bucket floor
  (bigger dispatches amortize per-launch overhead when there is always
  work waiting); an empty one lowers it back (no padding tax at low
  load).
- **round_robin vs sharded** (the AlpaServe tradeoff): when the recent
  dispatch occupancy fills the mesh (EWMA batch >= chips), one big
  sharded dispatch beats N small ones; when occupancy collapses below
  half the mesh, per-chip round_robin wins. Only wired when the router
  was built mode-switchable.

Every decision passes **hysteresis** (the burn signal must hold beyond
its threshold for ``sustain_s``; the band between ``burn_low`` and
``burn_high`` is dead) and a **cooldown** (at most one action per
``cooldown_s``), so the controller cannot flap: a single slow frame
moves nothing, and an overload is answered by one rung at a time.

Like resilience/, the controller is deterministic under test: ``clock``
is injectable and ``tick()`` is the whole control law -- fake-clock
units never sleep. ``start()`` runs ticks on a daemon thread for
production. The controller only ever touches host-side scheduling knobs
(it holds no device state), so enabled-but-idle it changes nothing:
serial depth-1 parity stays bitwise.

Concurrency discipline (rdp-racecheck): the controller holds NO locks of
its own -- every mutable field (``level``, the hysteresis timers, the
captured base knobs) is written exclusively by the tick thread
(single-writer; ``tick()`` is also what tests call directly, never
concurrently with ``start()``), and every actuation goes through the
dispatcher's ``set_*`` mutators, which take the dispatcher's own locks.
That keeps the controller out of the lock-order graph entirely: it can
never deadlock against the collector/completer/watchdog, only call into
them.

``ServerConfig.controller_enabled`` / ``RDP_CONTROLLER`` turn it on;
serving/server.py wires the live signals (SLO tracker burn, dispatcher
backlog) and actuators (the dispatcher's ``set_*`` surface plus the
servicer's refuse-streams flag).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
)
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

_CONTROLLER_ENV_VAR = "RDP_CONTROLLER"

#: brownout ladder depth (level 0 = normal operation)
MAX_LEVEL = 3


def resolve_controller_enabled(configured: bool) -> bool:
    """The effective controller switch: ``RDP_CONTROLLER`` (1/true/on)
    when set, else the configured value."""
    raw = os.environ.get(_CONTROLLER_ENV_VAR, "").strip().lower()
    if raw:
        return raw in ("1", "true", "yes", "on")
    return bool(configured)


class ReactiveController:
    """One control loop over one dispatcher.

    Args:
        dispatcher: zero-arg callable returning the live
            :class:`~.batching.BatchDispatcher` (or None while the engine
            swaps) -- an indirection, because hot-reload replaces the
            dispatcher under a running controller.
        burn: zero-arg callable returning the current error-budget burn
            (``SloTracker.burn``; > 1 means the objective is breached).
        refuse_streams: called with True/False when the brownout ladder
            reaches/leaves its top rung; None leaves rung 3 unused.
        interval_s: tick period for the background thread.
        burn_high / burn_low: hysteresis thresholds around burn = 1.
        sustain_s: how long burn must hold beyond a threshold to count.
        cooldown_s: minimum spacing between actions.
        inflight_cap: AIMD ceiling on max_inflight.
        samples: zero-arg callable returning how many frames the SLO
            tracker has observed; until it reaches ``min_samples`` the
            burn signal is treated as a dead band (one slow warm-up
            frame in a near-empty sliding window reads as a huge burn
            -- acting on it would brown out an idle server).
        clock: injectable monotonic clock (fake-clock tests drive
            ``tick()`` directly and never sleep).
    """

    def __init__(self, dispatcher: Callable[[], Any],
                 burn: Callable[[], float],
                 refuse_streams: Callable[[bool], None] | None = None,
                 *, interval_s: float = 0.5,
                 burn_high: float = 1.0, burn_low: float = 0.5,
                 sustain_s: float = 1.0, cooldown_s: float = 2.0,
                 inflight_cap: int = 8,
                 samples: Callable[[], int] | None = None,
                 min_samples: int = 32,
                 clock: Callable[[], float] = time.monotonic):
        if burn_low > burn_high:
            raise ValueError(
                f"burn_low ({burn_low}) must not exceed burn_high "
                f"({burn_high}): the dead band between them is the "
                "hysteresis"
            )
        self._dispatcher = dispatcher
        self._burn = burn
        self._refuse_streams = refuse_streams
        self.interval_s = float(interval_s)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.inflight_cap = max(1, int(inflight_cap))
        self._samples = samples
        self.min_samples = int(min_samples)
        self._clock = clock
        #: brownout ladder position (0 = normal)
        self.level = 0
        self.actions_total = 0
        self._high_since: float | None = None
        self._low_since: float | None = None
        self._last_action = float("-inf")
        # the pre-brownout knob values, captured on first escalation so a
        # symmetric exit restores exactly what load found
        self._base_window_ms: float | None = None
        self._base_inflight: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        obs.CONTROLLER_LEVEL.set(0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="slo-controller", daemon=True
        )
        self._thread.start()
        log.info(
            "reactive SLO controller started (tick %.2fs, burn "
            "thresholds %.2f/%.2f, cooldown %.1fs)",
            self.interval_s, self.burn_low, self.burn_high, self.cooldown_s,
        )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a control bug must never kill the loop
                log.exception("controller tick failed; continuing")

    # -- the control law -----------------------------------------------------

    def tick(self) -> str | None:
        """One control evaluation; returns the action taken (for tests
        and logs) or None."""
        now = self._clock()
        d = self._dispatcher()
        burn = self._burn()
        if (self._samples is not None
                and self._samples() < self.min_samples):
            # the sliding window is not statistically filled yet: one
            # slow frame among a handful reads as an enormous burn
            burn = float("nan")  # lands in the dead band below
        # hysteresis bookkeeping: the dead band clears both timers
        if burn > self.burn_high:
            self._low_since = None
            if self._high_since is None:
                self._high_since = now
        elif burn < self.burn_low:
            self._high_since = None
            if self._low_since is None:
                self._low_since = now
        else:
            self._high_since = self._low_since = None
        action = None
        if d is not None and now - self._last_action >= self.cooldown_s:
            sustained_high = (self._high_since is not None
                              and now - self._high_since >= self.sustain_s)
            sustained_low = (self._low_since is not None
                             and now - self._low_since >= self.sustain_s)
            if sustained_high and self.level < MAX_LEVEL:
                action = self._escalate(d)
            elif sustained_low and self.level > 0:
                action = self._deescalate(d)
            elif sustained_low:
                action = self._tune_steady(d)
            if action is not None:
                self._last_action = now
                self.actions_total += 1
                # a rung (or tune) answered this excursion; the signal
                # must re-sustain before the next action
                self._high_since = self._low_since = None
                obs.CONTROLLER_ACTIONS.labels(action=action).inc()
                journal_lib.JOURNAL.append(
                    events.CONTROLLER_ACTION, action=action,
                    level=self.level, burn=round(burn, 3),
                )
                log.info("controller action: %s (burn %.2f, level %d)",
                         action, burn, self.level)
        if d is not None:
            obs.CONTROLLER_INFLIGHT.set(d.max_inflight)
            obs.CONTROLLER_WINDOW_MS.set(d.window_ms)
        obs.CONTROLLER_LEVEL.set(self.level)
        return action

    def _set_level(self, new: int) -> None:
        """Every rung change is a control-plane transition: publish the
        gauge and journal the move at the mutation site (not only once
        per tick), so an incident reconstruction sees exactly when the
        ladder moved and from where."""
        old, self.level = self.level, new
        obs.CONTROLLER_LEVEL.set(new)
        journal_lib.JOURNAL.append(events.CONTROLLER_LEVEL, frm=old, to=new)

    def _escalate(self, d) -> str:
        self._set_level(self.level + 1)
        if self.level == 1:
            self._base_window_ms = d.window_ms
            self._base_inflight = d.max_inflight
            d.set_window_ms(max(0.5, d.window_ms / 2))
            d.set_max_inflight(max(1, d.max_inflight // 2))
            return "window_down"
        if self.level == 2:
            d.set_deadline_safety(2.0)
            return "admission_tighten"
        if self._refuse_streams is not None:
            self._refuse_streams(True)
            return "refuse_streams"
        # no stream-refusal hook: rung 3 degenerates to holding rung 2
        self._set_level(2)
        d.set_deadline_safety(3.0)
        return "admission_tighten"

    def _deescalate(self, d) -> str:
        if self.level == 3:
            self._set_level(2)
            if self._refuse_streams is not None:
                self._refuse_streams(False)
            return "accept_streams"
        if self.level == 2:
            self._set_level(1)
            d.set_deadline_safety(1.0)
            return "admission_relax"
        self._set_level(0)
        if self._base_window_ms is not None:
            d.set_window_ms(self._base_window_ms)
        if self._base_inflight is not None:
            d.set_max_inflight(self._base_inflight)
        return "window_up"

    def _tune_steady(self, d) -> str | None:
        """Level-0 optimization under a healthy burn signal: grow
        throughput where the backlog shows demand, give back padding and
        parallelism where it does not."""
        backlog = d.backlog()
        if backlog > 0 and d.max_inflight < self.inflight_cap:
            d.set_max_inflight(d.max_inflight + 1)
            return "inflight_up"
        mode_action = self._tune_mode(d)
        if mode_action is not None:
            return mode_action
        if backlog >= 2 * d.bucket_floor and backlog >= 2:
            floor = min(d.bucket_floor * 2, d._max_batch)
            if floor != d.bucket_floor:
                d.set_bucket_floor(floor)
                return "floor_up"
        if backlog == 0 and d.bucket_floor > 1:
            d.set_bucket_floor(d.bucket_floor // 2)
            return "floor_down"
        return None

    def _tune_mode(self, d) -> str | None:
        r = d.router
        if r is None or not r.can_switch_modes:
            return None
        # occupancy hysteresis: full-mesh batches justify one sharded
        # dispatch; below half the mesh, per-chip windows win
        if r.mode == "round_robin" and d.recent_batch >= r.chips:
            r.set_mode("sharded")
            return "mode_sharded"
        if r.mode == "sharded" and d.recent_batch <= r.chips / 2:
            r.set_mode("round_robin")
            return "mode_round_robin"
        return None
