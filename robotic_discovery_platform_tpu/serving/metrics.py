"""Per-frame metrics CSV -- the monitoring data contract.

Schema is byte-compatible with the reference
(``timestamp,mean_curvature,max_curvature,mask_coverage_percent``,
reference: services/vision_analysis/server.py:68-72,146-150): the drift
detector consumes exactly these columns. Two reference defects fixed
(SURVEY.md section 5.2): the reference re-opens the file for every frame and
interleaves appends from up to 10 gRPC worker threads with no lock; here a
single writer object owns the handle, buffers rows, and flushes under a lock.

A third defect fixed here (ISSUE 9 satellite): an invalid frame's
``nan``/``inf`` curvature used to be appended verbatim, poisoning the CSV
the drift detector consumes (its column means went NaN). Non-finite rows
are now skipped with a warning and counted
(``rdp_metrics_rows_skipped_total``); ``skipped_rows`` exposes the count.
"""

from __future__ import annotations

import atexit
import math
import threading
import time
from datetime import datetime, timezone
from pathlib import Path

from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

HEADER = "timestamp,mean_curvature,max_curvature,mask_coverage_percent"


class MetricsWriter:
    def __init__(self, path: str | Path, flush_every: int = 32,
                 flush_interval_s: float = 2.0):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.flush_every = max(1, flush_every)
        self.flush_interval_s = flush_interval_s
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._last_flush = time.monotonic()
        # The drift detector consumes this CSV: rows buffered between
        # interval flushes must survive a server exit, so the tail is
        # flushed at interpreter shutdown unless close() already ran.
        self._closed = False
        self.skipped_rows = 0
        atexit.register(self._flush_at_exit)
        if not self.path.exists():
            self.path.write_text(HEADER + "\n")

    def append(self, mean_curvature: float, max_curvature: float,
               mask_coverage_percent: float, timestamp: str | None = None) -> None:
        values = (mean_curvature, max_curvature, mask_coverage_percent)
        if not all(math.isfinite(float(v)) for v in values):
            # an invalid frame's nan/inf must never reach the CSV the
            # drift detector consumes; count it instead of writing it
            with self._lock:
                self.skipped_rows += 1
            from robotic_discovery_platform_tpu.observability import (
                instruments as obs,
            )

            obs.METRICS_ROWS_SKIPPED.inc()
            log.warning(
                "skipping non-finite metrics row "
                "(mean_curvature=%s, max_curvature=%s, coverage=%s); "
                "%d skipped so far", *values, self.skipped_rows,
            )
            return
        ts = timestamp or datetime.now(timezone.utc).strftime(
            "%Y-%m-%d %H:%M:%S.%f"
        )
        row = f"{ts},{mean_curvature},{max_curvature},{mask_coverage_percent}"
        with self._lock:
            self._buf.append(row)
            due = (
                len(self._buf) >= self.flush_every
                or time.monotonic() - self._last_flush > self.flush_interval_s
            )
            if due:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        with open(self.path, "a") as f:
            f.write("\n".join(self._buf) + "\n")
        self._buf.clear()
        self._last_flush = time.monotonic()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_at_exit(self) -> None:
        if not self._closed:
            self.flush()

    def close(self) -> None:
        """Flush the buffered tail and drop the atexit registration (a
        closed writer must not be kept alive, or re-flushed, by interpreter
        shutdown). Idempotent; the writer stays usable after close -- a
        late append just buffers and flushes normally."""
        self.flush()
        with self._lock:
            if self._closed:
                return
            self._closed = True
        atexit.unregister(self._flush_at_exit)
