"""Per-frame metrics CSV -- the monitoring data contract.

Schema is byte-compatible with the reference
(``timestamp,mean_curvature,max_curvature,mask_coverage_percent``,
reference: services/vision_analysis/server.py:68-72,146-150): the drift
detector consumes exactly these columns. Two reference defects fixed
(SURVEY.md section 5.2): the reference re-opens the file for every frame and
interleaves appends from up to 10 gRPC worker threads with no lock; here a
single writer object owns the handle, buffers rows, and flushes under a lock.
"""

from __future__ import annotations

import atexit
import threading
import time
from datetime import datetime, timezone
from pathlib import Path

HEADER = "timestamp,mean_curvature,max_curvature,mask_coverage_percent"


class MetricsWriter:
    def __init__(self, path: str | Path, flush_every: int = 32,
                 flush_interval_s: float = 2.0):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.flush_every = max(1, flush_every)
        self.flush_interval_s = flush_interval_s
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._last_flush = time.monotonic()
        # The drift detector consumes this CSV: rows buffered between
        # interval flushes must survive a server exit, so the tail is
        # flushed at interpreter shutdown unless close() already ran.
        self._closed = False
        atexit.register(self._flush_at_exit)
        if not self.path.exists():
            self.path.write_text(HEADER + "\n")

    def append(self, mean_curvature: float, max_curvature: float,
               mask_coverage_percent: float, timestamp: str | None = None) -> None:
        ts = timestamp or datetime.now(timezone.utc).strftime(
            "%Y-%m-%d %H:%M:%S.%f"
        )
        row = f"{ts},{mean_curvature},{max_curvature},{mask_coverage_percent}"
        with self._lock:
            self._buf.append(row)
            due = (
                len(self._buf) >= self.flush_every
                or time.monotonic() - self._last_flush > self.flush_interval_s
            )
            if due:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        with open(self.path, "a") as f:
            f.write("\n".join(self._buf) + "\n")
        self._buf.clear()
        self._last_flush = time.monotonic()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_at_exit(self) -> None:
        if not self._closed:
            self.flush()

    def close(self) -> None:
        """Flush the buffered tail and drop the atexit registration (a
        closed writer must not be kept alive, or re-flushed, by interpreter
        shutdown). Idempotent; the writer stays usable after close -- a
        late append just buffers and flushes normally."""
        self.flush()
        with self._lock:
            if self._closed:
                return
            self._closed = True
        atexit.unregister(self._flush_at_exit)
