"""Cross-stream micro-batching for the analysis server, pipelined.

The reference serves strictly one frame per request, sequentially per stream
(reference: services/vision_analysis/server.py:116): with 10 worker threads
the GPU sees batch-1 forwards regardless of load. On TPU the model forward
is where the MXU time goes and batch-1 leaves the chip mostly idle, so this
module coalesces frames from *concurrent gRPC streams* into one batched
dispatch (SURVEY.md section 5.7b calls this the single biggest
serving-throughput lever).

Design: a three-stage pipeline that exploits JAX async dispatch, so the
device never idles while the host stages or fans out (the classic
serving-pipeline stall that Clipper-style async dispatch pipelines
eliminate):

1. **Collector/stager** -- stream handler threads ``submit()`` a frame and
   block on a per-request event; the collector drains the queue, waits at
   most ``window_ms`` for co-arriving frames, groups them by (H, W) camera
   geometry, pads each group up to the next power-of-two bucket into a
   *preallocated, pooled* host buffer (no fresh ``np.stack`` copies per
   dispatch), stages it onto the device (``ops.pipeline.stage_batch``),
   and launches the jitted analyzer WITHOUT waiting for the result --
   the jit call returns as soon as the computation is enqueued.
2. **Bounded in-flight window** -- at most ``max_inflight`` dispatches may
   be launched-but-not-completed at once (``ServerConfig.
   max_inflight_dispatches``, default 2; ``RDP_INFLIGHT`` overrides), so
   device memory stays capped while batch N+1's staging and compute
   overlap batch N's completion. ``max_inflight=1`` is the serial mode:
   bit-identical results, no overlap.
3. **Completer** -- a second thread drains finished dispatches in launch
   order, performs the single blocking D2H (``np.asarray``) off the
   collector's critical path, and fans results back to the per-stream
   events. Padding frames are replicas of the first frame and their
   results are dropped.

Resilience (resilience/ package):

- the queue is *bounded*: a submit arriving with ``max_backlog`` frames
  already waiting fast-fails with :class:`OverloadedError` (the server maps
  it to RESOURCE_EXHAUSTED) instead of growing latency without bound;
- every submit carries a deadline (``submit_timeout_s``, or the caller's
  tighter one) instead of the old unbounded ``done.wait()`` -- a handler
  thread can no longer be parked forever;
- a watchdog notices a collector OR completer thread that died outside its
  per-dispatch guard, error-completes the frames stranded in EITHER queue
  (submit backlog and in-flight completions alike), resets the in-flight
  window, and restarts the dead stage;
- ``stop()`` error-completes frames stranded in either queue; no submitter
  is ever left blocked.

Fault-injection sites (resilience/faults.py): ``serving.batch.collect``
fires in the collector loop outside the dispatch guard (chaos tests kill the
collector here), ``serving.batch.dispatch`` fires inside the launch guard
(failed / slow staging+launch), ``serving.batch.complete`` fires inside the
completer's guard (failed / slow D2H: the dispatch's frames error-complete,
the completer keeps draining).

Observability (observability/ package): queue depth gauge
(``rdp_batch_queue_depth``), per-dispatch batch-size histogram,
in-flight-dispatch gauge (``rdp_batch_inflight_dispatches``), per-dispatch
overlap histogram (``rdp_batch_overlap_seconds``: how long a completing
dispatch overlapped the next one's staging/compute), stage-split latency
(``rdp_batch_stage_seconds``: stage / launch / complete), watchdog restart
counter; each submit carries its stream's span context across the
collector-thread hop so dispatch failures can name the traces they hit.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.observability import (
    instruments as obs,
    trace,
)
from robotic_discovery_platform_tpu.ops import pipeline as pipeline_lib
from robotic_discovery_platform_tpu.resilience import DeadlineExceeded, inject
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

_INFLIGHT_ENV_VAR = "RDP_INFLIGHT"


def resolve_max_inflight(configured: int) -> int:
    """The effective in-flight-dispatch cap: ``RDP_INFLIGHT`` when set,
    else the configured value; never below 1 (1 = serial dispatch)."""
    raw = os.environ.get(_INFLIGHT_ENV_VAR)
    value = int(raw) if raw else int(configured)
    return max(1, value)


class OverloadedError(RuntimeError):
    """The dispatcher's backlog cap was hit; the frame was shed, not
    queued. Retryable by the client (the server surfaces it as
    RESOURCE_EXHAUSTED)."""


@dataclass(eq=False)  # identity semantics: instances live in _pending sets
class _Pending:
    frame_rgb: np.ndarray
    depth: np.ndarray
    intrinsics: np.ndarray
    depth_scale: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: BaseException | None = None
    # the submitting stream's span context, carried across the thread hop
    # (contextvars do not flow into the collector thread) so dispatch-side
    # logs can name the traces of the frames they affected
    trace_ctx: Any = None


class _BucketBuffers:
    """One reusable set of host staging arrays for a (geometry, bucket)
    key: the collector fills rows in place instead of building fresh
    ``np.stack`` copies per dispatch. A buffer set is exclusive to one
    in-flight dispatch (the completer returns it to the pool only after
    the dispatch's device work is done), so refilling can never race a
    zero-copy ``device_put`` of a still-executing batch."""

    __slots__ = ("key", "frames", "depths", "intr", "scales")

    def __init__(self, key: tuple, template: _Pending, b: int):
        h, w = template.frame_rgb.shape[:2]
        self.key = key
        self.frames = np.empty((b, h, w, 3), template.frame_rgb.dtype)
        self.depths = np.empty((b, h, w), template.depth.dtype)
        self.intr = np.empty((b, 3, 3), np.float32)
        self.scales = np.empty((b,), np.float32)


@dataclass(eq=False)
class _Dispatch:
    """A launched-but-not-completed batch riding the completion queue."""

    group: list[_Pending]
    out: Any  # the analyzer's (possibly still-computing) output tree
    bufs: _BucketBuffers | None
    # the in-flight slot this dispatch holds; released by the completer.
    # Carried per-dispatch so a watchdog window reset can never double-free
    # a fresh semaphore.
    slot: threading.Semaphore
    launch_t: float


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class BatchDispatcher:
    """Coalesce concurrent frame analyses into pipelined batched dispatches.

    Args:
        analyze_batch: ``(frames [B,H,W,3] u8 RGB, depths [B,H,W] u16,
            intrinsics [B,3,3], scales [B]) -> FrameAnalysis`` with leading
            batch dim on every output (ops/pipeline.make_batch_analyzer,
            already closed over the model variables). Receives pre-staged
            device arrays; must not block on its own result (jit async
            dispatch).
        window_ms: how long to hold the first frame of a batch waiting for
            co-arriving frames. The reference's dead ``batch_window_ms`` knob
            (round-1 review) is live here.
        max_batch: hard cap per dispatch.
        max_backlog: queued-frame cap; submits beyond it shed load
            (:class:`OverloadedError`) instead of queuing.
        submit_timeout_s: default per-submit deadline; ``submit`` raises
            ``DeadlineExceeded`` when the result is not back in time.
        watchdog_interval_s: how often the watchdog checks collector +
            completer liveness (<= 0 disables the watchdog).
        max_inflight: bounded in-flight window -- how many dispatches may
            be launched but not yet completed at once. 1 = serial (launch
            N+1 only after N's results are on the host); 2 (default)
            overlaps batch N+1's staging/compute with batch N's D2H.
    """

    def __init__(self, analyze_batch: Callable, window_ms: float = 2.0,
                 max_batch: int = 8, max_backlog: int = 64,
                 submit_timeout_s: float = 30.0,
                 watchdog_interval_s: float = 1.0,
                 max_inflight: int = 2):
        self._analyze = analyze_batch
        self._window_s = window_ms / 1e3
        self._max_batch = max_batch
        self._max_backlog = max_backlog
        self._submit_timeout_s = submit_timeout_s
        self._max_inflight = max(1, int(max_inflight))
        self._q: queue.Queue[_Pending | None] = queue.Queue()
        self._cq: queue.Queue[_Dispatch | None] = queue.Queue()
        self._inflight = threading.Semaphore(self._max_inflight)
        self._inflight_lock = threading.Lock()
        self._inflight_count = 0
        #: high-water mark of concurrently in-flight dispatches; never
        #: exceeds ``max_inflight`` (tests and the bench assert on this)
        self.inflight_high_water = 0
        #: total seconds completed dispatches overlapped the next launch
        #: (0.0 in serial mode); written only by the completer thread
        self.overlap_s_total = 0.0
        self._last_done_t = 0.0
        # pooled host staging buffers, keyed by (bucket, frame shape/dtype,
        # depth dtype); free-list only -- buffers in use ride the dispatch
        self._pool: dict[tuple, list[_BucketBuffers]] = {}
        self._pool_lock = threading.Lock()
        self._stopped = threading.Event()
        self._submit_lock = threading.Lock()
        # every not-yet-completed submit, whether still queued, staged, or
        # in flight on the device: the watchdog error-completes exactly
        # this set when a pipeline stage dies, so a frame caught between
        # queues is covered too
        self._pending: set[_Pending] = set()
        self._pending_lock = threading.Lock()
        self.collector_restarts = 0
        self.completer_restarts = 0
        self._completer = self._start_completer()
        self._thread = self._start_collector()
        self._watchdog: threading.Thread | None = None
        if watchdog_interval_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, args=(watchdog_interval_s,),
                name="batch-dispatcher-watchdog", daemon=True,
            )
            self._watchdog.start()

    def _start_collector(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop, name="batch-dispatcher", daemon=True
        )
        t.start()
        return t

    def _start_completer(self) -> threading.Thread:
        t = threading.Thread(
            target=self._complete_loop, name="batch-completer", daemon=True
        )
        t.start()
        return t

    # -- caller side --------------------------------------------------------

    @shape_contract(frame_rgb=("h w 3", "uint8"), depth="h w",
                    intrinsics="3 3")
    def submit(self, frame_rgb, depth, intrinsics, depth_scale,
               timeout_s: float | None = None):
        """Block until this frame's analysis is available; returns the
        unbatched FrameAnalysis slice (host numpy leaves).

        Raises :class:`OverloadedError` when the backlog cap is hit and
        ``DeadlineExceeded`` when the result misses the submit deadline
        (``timeout_s`` if given and tighter, else ``submit_timeout_s``).
        """
        p = _Pending(frame_rgb, depth, np.asarray(intrinsics, np.float32),
                     float(depth_scale), trace_ctx=trace.current())
        # enqueue under the lock stop() drains under: a submit either lands
        # BEFORE the drain (and is error-completed by it) or observes
        # stopped and raises -- it can never enqueue after the drain and
        # block forever on done.wait()
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("dispatcher stopped")
            if self._q.qsize() >= self._max_backlog:
                raise OverloadedError(
                    f"dispatcher backlog at cap ({self._max_backlog} "
                    "frames queued); shedding load"
                )
            with self._pending_lock:
                self._pending.add(p)
            self._q.put(p)
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
        timeout = self._submit_timeout_s
        if timeout_s is not None:
            timeout = min(timeout, timeout_s)
        try:
            if not p.done.wait(timeout):
                raise DeadlineExceeded(
                    f"batched analysis not ready within {timeout:.2f}s "
                    "(per-submit deadline)"
                )
        finally:
            with self._pending_lock:
                self._pending.discard(p)
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        """Idempotent. Every pending or racing submit is completed: frames
        already launched drain through the completer with real results when
        it is healthy, frames stranded in either queue get a 'dispatcher
        stopped' error. No caller is left blocked."""
        with self._submit_lock:
            self._stopped.set()
            self._q.put(None)
        self._thread.join(timeout=5)
        # the completer first drains every dispatch launched before the
        # sentinel (delivering their real results), then exits
        self._cq.put(None)
        self._completer.join(timeout=5)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
        # error-complete anything either queue still holds
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.done.is_set():
                item.error = RuntimeError("dispatcher stopped")
                item.done.set()
        while True:
            try:
                d = self._cq.get_nowait()
            except queue.Empty:
                break
            if d is None:
                continue
            self._pool_put(d.bufs)
            for p in d.group:
                if not p.done.is_set():
                    p.error = RuntimeError("dispatcher stopped")
                    p.done.set()
        self._fail_pending(RuntimeError("dispatcher stopped"))

    def _fail_pending(self, exc: BaseException) -> None:
        with self._pending_lock:
            stranded = [p for p in self._pending if not p.done.is_set()]
        for p in stranded:
            p.error = exc
            p.done.set()

    # -- watchdog ------------------------------------------------------------

    def _watch(self, interval_s: float) -> None:
        """Error-complete and restart if the collector or completer ever
        dies outside its per-dispatch guard (e.g. an exception in the
        grouping / collection / queue code itself): without this, every
        in-flight submitter of that era would wait out its full deadline
        for nothing, and all later submits would queue into a threadless
        pipeline stage."""
        while not self._stopped.wait(interval_s):
            collector_dead = not self._thread.is_alive()
            completer_dead = not self._completer.is_alive()
            if not (collector_dead or completer_dead):
                continue
            with self._submit_lock:
                if self._stopped.is_set():
                    return
                dead = ("collector" if collector_dead else "completer")
                if collector_dead:
                    self.collector_restarts += 1
                if completer_dead:
                    self.completer_restarts += 1
                obs.WATCHDOG_RESTARTS.inc()
                log.error(
                    "batch %s thread died unexpectedly; failing %d "
                    "pending frame(s) and restarting (restart #%d)",
                    dead, len(self._pending),
                    self.collector_restarts + self.completer_restarts,
                )
                # drain BOTH queues (the restarted stages start from an
                # empty pipeline; stranded submitters get an error now,
                # not a deadline timeout later), returning pooled buffers
                # from abandoned in-flight dispatches
                while True:
                    try:
                        self._q.get_nowait()
                    except queue.Empty:
                        break
                while True:
                    try:
                        d = self._cq.get_nowait()
                    except queue.Empty:
                        break
                    if d is not None:
                        self._pool_put(d.bufs)
                # fresh in-flight window: slots held by dispatches lost
                # with the dead stage can never be released (a dispatch
                # still riding a live completer releases its OWN slot
                # object, never this new one)
                self._inflight = threading.Semaphore(self._max_inflight)
                with self._inflight_lock:
                    self._inflight_count = 0
                    obs.INFLIGHT_DISPATCHES.set(0)
                self._fail_pending(RuntimeError(
                    f"batch {dead} died; frame dropped"
                ))
                if collector_dead:
                    self._thread = self._start_collector()
                if completer_dead:
                    self._completer = self._start_completer()

    # -- collector / stager side --------------------------------------------

    def _collect(self) -> list[_Pending]:
        first = self._q.get()
        if first is None:
            return []
        batch = [first]
        deadline = time.monotonic() + self._window_s
        while len(batch) < self._max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                break
            batch.append(item)
        return batch

    def _loop(self) -> None:
        while not self._stopped.is_set():
            batch = self._collect()
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
            if not batch:
                continue
            # deliberately OUTSIDE the launch guard: an injected fault
            # here kills the collector thread itself, which is exactly the
            # failure mode the watchdog exists for
            inject("serving.batch.collect")
            by_shape: dict[tuple, list[_Pending]] = {}
            for p in batch:
                by_shape.setdefault(p.frame_rgb.shape[:2], []).append(p)
            for group in by_shape.values():
                self._launch_group(group)

    def _pool_take(self, key: tuple, template: _Pending) -> _BucketBuffers:
        with self._pool_lock:
            free = self._pool.get(key)
            if free:
                return free.pop()
        return _BucketBuffers(key, template, key[0])

    def _pool_put(self, bufs: _BucketBuffers | None) -> None:
        if bufs is None:
            return
        with self._pool_lock:
            self._pool.setdefault(bufs.key, []).append(bufs)

    def _stage_group(self, group: list[_Pending], b: int):
        """Host-side staging: the padded [b, ...] batch arrays for a group.

        Returns ``(bufs, frames, depths, intr, scales)`` where ``bufs`` is
        the pooled buffer set to return after the dispatch completes (None
        for the b == 1 fast path, which returns zero-copy ``[None]`` views
        of the submitted arrays -- no stack, no pad, no copy). For b > 1
        the group's rows are filled into a pooled buffer; padding rows
        (replicas of frame 0) are written only when the bucket is not
        full -- a full bucket skips the pad work entirely."""
        n = len(group)
        first = group[0]
        if b == 1:
            return (None, first.frame_rgb[None], first.depth[None],
                    first.intrinsics[None],
                    np.asarray([first.depth_scale], np.float32))
        key = (b, first.frame_rgb.shape, first.frame_rgb.dtype.str,
               first.depth.dtype.str)
        bufs = self._pool_take(key, first)
        for i, p in enumerate(group):
            bufs.frames[i] = p.frame_rgb
            bufs.depths[i] = p.depth
            bufs.intr[i] = p.intrinsics
            bufs.scales[i] = p.depth_scale
        if n < b:
            bufs.frames[n:] = bufs.frames[0]
            bufs.depths[n:] = bufs.depths[0]
            bufs.intr[n:] = bufs.intr[0]
            bufs.scales[n:] = bufs.scales[0]
        return bufs, bufs.frames, bufs.depths, bufs.intr, bufs.scales

    def _launch_group(self, group: list[_Pending]) -> None:
        """Stage + H2D + async launch of one geometry group, then hand the
        in-flight dispatch to the completer. Never blocks on the result."""
        # bounded in-flight window: dispatch N+1 may not launch until a
        # slot frees (i.e. at most max_inflight batches hold device memory)
        slot = self._inflight
        while not slot.acquire(timeout=0.05):
            if self._stopped.is_set():
                self._fail_group(
                    group, RuntimeError("dispatcher stopped"), log_it=False
                )
                return
        bufs = None
        launched = False
        try:
            inject("serving.batch.dispatch")
            n = len(group)
            obs.BATCH_SIZE.observe(n)
            b = _bucket(n, self._max_batch)
            t0 = time.monotonic()
            bufs, frames, depths, intr, scales = self._stage_group(group, b)
            staged = pipeline_lib.stage_batch(frames, depths, intr, scales)
            t1 = time.monotonic()
            # jit async dispatch: returns once the computation is enqueued
            out = self._analyze(*staged)
            t2 = time.monotonic()
            obs.BATCH_STAGE_LATENCY.labels(stage="stage").observe(t1 - t0)
            obs.BATCH_STAGE_LATENCY.labels(stage="launch").observe(t2 - t1)
            with self._inflight_lock:
                self._inflight_count += 1
                self.inflight_high_water = max(
                    self.inflight_high_water, self._inflight_count
                )
                obs.INFLIGHT_DISPATCHES.set(self._inflight_count)
            self._cq.put(_Dispatch(group, out, bufs, slot, t2))
            launched = True
        except BaseException as exc:  # deliver, don't kill the collector
            self._fail_group(group, exc)
            self._pool_put(bufs)
        finally:
            if not launched:
                slot.release()

    # -- completer side -----------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            d = self._cq.get()
            if d is None:
                return
            t_pop = time.monotonic()
            try:
                inject("serving.batch.complete")
                # the ONE blocking host fetch, off the collector's critical
                # path: batch N+1 is already staging/computing while this
                # D2H + fan-out runs
                host = jax.tree.map(np.asarray, d.out)
                for i, p in enumerate(d.group):
                    p.result = jax.tree.map(lambda a, _i=i: a[_i], host)
                    p.done.set()
            except BaseException as exc:  # deliver, keep draining
                self._fail_group(d.group, exc)
            finally:
                done_t = time.monotonic()
                # overlap: how long this dispatch's predecessor was still
                # completing after this one had already launched. Serial
                # mode (max_inflight=1) launches only after the previous
                # completion, so this is identically 0 there.
                overlap = max(0.0, self._last_done_t - d.launch_t)
                self._last_done_t = done_t
                self.overlap_s_total += overlap
                obs.DISPATCH_OVERLAP.observe(overlap)
                obs.BATCH_STAGE_LATENCY.labels(stage="complete").observe(
                    done_t - t_pop
                )
                self._pool_put(d.bufs)
                with self._inflight_lock:
                    self._inflight_count = max(0, self._inflight_count - 1)
                    obs.INFLIGHT_DISPATCHES.set(self._inflight_count)
                d.slot.release()

    def _fail_group(self, group: list[_Pending], exc: BaseException,
                    log_it: bool = True) -> None:
        if log_it:
            log.exception(
                "batched dispatch failed (affected traces: %s)",
                ",".join(
                    p.trace_ctx.trace_id if p.trace_ctx is not None else "-"
                    for p in group
                ),
            )
        for p in group:
            if not p.done.is_set():
                p.error = exc
                p.done.set()
