"""Cross-stream micro-batching for the analysis server.

The reference serves strictly one frame per request, sequentially per stream
(reference: services/vision_analysis/server.py:116): with 10 worker threads
the GPU sees batch-1 forwards regardless of load. On TPU the model forward
is where the MXU time goes and batch-1 leaves the chip mostly idle, so this
module coalesces frames from *concurrent gRPC streams* into one batched
dispatch (SURVEY.md section 5.7b calls this the single biggest
serving-throughput lever).

Design: stream handler threads ``submit()`` a frame and block on a
per-request event; a single collector thread drains the queue, waits at most
``window_ms`` for co-arriving frames, groups them by (H, W) camera geometry,
pads each group up to the next power-of-two bucket (so XLA compiles a handful
of batch shapes, not one per group size), runs the batched fused graph, and
fans results back out. Padding frames are replicas of the first frame and
their results are dropped.

Resilience (resilience/ package):

- the queue is *bounded*: a submit arriving with ``max_backlog`` frames
  already waiting fast-fails with :class:`OverloadedError` (the server maps
  it to RESOURCE_EXHAUSTED) instead of growing latency without bound;
- every submit carries a deadline (``submit_timeout_s``, or the caller's
  tighter one) instead of the old unbounded ``done.wait()`` -- a handler
  thread can no longer be parked forever;
- a watchdog notices a collector thread that died *outside* ``_run_group``'s
  guard (the one hole in the old design: pending events were never set and
  every submitter hung), error-completes the stranded frames, and restarts
  the collector.

Fault-injection sites (resilience/faults.py): ``serving.batch.collect``
fires in the collector loop outside the dispatch guard (chaos tests kill the
collector here), ``serving.batch.dispatch`` fires inside the guard (failed /
slow batched dispatches).

Observability (observability/ package): queue depth gauge
(``rdp_batch_queue_depth``), per-dispatch batch-size histogram, watchdog
restart counter; each submit carries its stream's span context across the
collector-thread hop so dispatch failures can name the traces they hit.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.observability import (
    instruments as obs,
    trace,
)
from robotic_discovery_platform_tpu.resilience import DeadlineExceeded, inject
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)


class OverloadedError(RuntimeError):
    """The dispatcher's backlog cap was hit; the frame was shed, not
    queued. Retryable by the client (the server surfaces it as
    RESOURCE_EXHAUSTED)."""


@dataclass(eq=False)  # identity semantics: instances live in _pending sets
class _Pending:
    frame_rgb: np.ndarray
    depth: np.ndarray
    intrinsics: np.ndarray
    depth_scale: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: BaseException | None = None
    # the submitting stream's span context, carried across the thread hop
    # (contextvars do not flow into the collector thread) so dispatch-side
    # logs can name the traces of the frames they affected
    trace_ctx: Any = None


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class BatchDispatcher:
    """Coalesce concurrent frame analyses into batched dispatches.

    Args:
        analyze_batch: ``(frames [B,H,W,3] u8 RGB, depths [B,H,W] u16,
            intrinsics [B,3,3], scales [B]) -> FrameAnalysis`` with leading
            batch dim on every output (ops/pipeline.make_batch_analyzer,
            already closed over the model variables).
        window_ms: how long to hold the first frame of a batch waiting for
            co-arriving frames. The reference's dead ``batch_window_ms`` knob
            (round-1 review) is live here.
        max_batch: hard cap per dispatch.
        max_backlog: queued-frame cap; submits beyond it shed load
            (:class:`OverloadedError`) instead of queuing.
        submit_timeout_s: default per-submit deadline; ``submit`` raises
            ``DeadlineExceeded`` when the result is not back in time.
        watchdog_interval_s: how often the watchdog checks collector
            liveness (<= 0 disables the watchdog).
    """

    def __init__(self, analyze_batch: Callable, window_ms: float = 2.0,
                 max_batch: int = 8, max_backlog: int = 64,
                 submit_timeout_s: float = 30.0,
                 watchdog_interval_s: float = 1.0):
        self._analyze = analyze_batch
        self._window_s = window_ms / 1e3
        self._max_batch = max_batch
        self._max_backlog = max_backlog
        self._submit_timeout_s = submit_timeout_s
        self._q: queue.Queue[_Pending | None] = queue.Queue()
        self._stopped = threading.Event()
        self._submit_lock = threading.Lock()
        # every not-yet-completed submit, whether still queued or already
        # popped by the collector: the watchdog error-completes exactly this
        # set when the collector dies, so a frame caught between _collect()
        # and _run_group() is covered too
        self._pending: set[_Pending] = set()
        self._pending_lock = threading.Lock()
        self.collector_restarts = 0
        self._thread = self._start_collector()
        self._watchdog: threading.Thread | None = None
        if watchdog_interval_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, args=(watchdog_interval_s,),
                name="batch-dispatcher-watchdog", daemon=True,
            )
            self._watchdog.start()

    def _start_collector(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop, name="batch-dispatcher", daemon=True
        )
        t.start()
        return t

    # -- caller side --------------------------------------------------------

    @shape_contract(frame_rgb=("h w 3", "uint8"), depth="h w",
                    intrinsics="3 3")
    def submit(self, frame_rgb, depth, intrinsics, depth_scale,
               timeout_s: float | None = None):
        """Block until this frame's analysis is available; returns the
        unbatched FrameAnalysis slice (host numpy leaves).

        Raises :class:`OverloadedError` when the backlog cap is hit and
        ``DeadlineExceeded`` when the result misses the submit deadline
        (``timeout_s`` if given and tighter, else ``submit_timeout_s``).
        """
        p = _Pending(frame_rgb, depth, np.asarray(intrinsics, np.float32),
                     float(depth_scale), trace_ctx=trace.current())
        # enqueue under the lock stop() drains under: a submit either lands
        # BEFORE the drain (and is error-completed by it) or observes
        # stopped and raises -- it can never enqueue after the drain and
        # block forever on done.wait()
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("dispatcher stopped")
            if self._q.qsize() >= self._max_backlog:
                raise OverloadedError(
                    f"dispatcher backlog at cap ({self._max_backlog} "
                    "frames queued); shedding load"
                )
            with self._pending_lock:
                self._pending.add(p)
            self._q.put(p)
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
        timeout = self._submit_timeout_s
        if timeout_s is not None:
            timeout = min(timeout, timeout_s)
        try:
            if not p.done.wait(timeout):
                raise DeadlineExceeded(
                    f"batched analysis not ready within {timeout:.2f}s "
                    "(per-submit deadline)"
                )
        finally:
            with self._pending_lock:
                self._pending.discard(p)
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        """Idempotent. Every pending or racing submit is completed (with a
        'dispatcher stopped' error if its frame was never dispatched);
        no caller is left blocked."""
        with self._submit_lock:
            self._stopped.set()
            self._q.put(None)
        self._thread.join(timeout=5)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
        # error-complete anything the collector left behind
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.done.is_set():
                item.error = RuntimeError("dispatcher stopped")
                item.done.set()
        self._fail_pending(RuntimeError("dispatcher stopped"))

    def _fail_pending(self, exc: BaseException) -> None:
        with self._pending_lock:
            stranded = [p for p in self._pending if not p.done.is_set()]
        for p in stranded:
            p.error = exc
            p.done.set()

    # -- watchdog ------------------------------------------------------------

    def _watch(self, interval_s: float) -> None:
        """Error-complete and restart if the collector ever dies outside
        ``_run_group``'s guard (e.g. an exception in the grouping /
        collection code itself): without this, every in-flight submitter
        of that era would wait out its full deadline for nothing, and all
        later submits would queue into a threadless dispatcher."""
        while not self._stopped.wait(interval_s):
            if self._thread.is_alive():
                continue
            with self._submit_lock:
                if self._stopped.is_set():
                    return
                self.collector_restarts += 1
                obs.WATCHDOG_RESTARTS.inc()
                log.error(
                    "batch collector thread died unexpectedly; failing %d "
                    "pending frame(s) and restarting (restart #%d)",
                    len(self._pending), self.collector_restarts,
                )
                # drain whatever is queued (the restarted collector starts
                # from an empty backlog; stranded submitters get an error
                # now, not a deadline timeout later)
                while True:
                    try:
                        self._q.get_nowait()
                    except queue.Empty:
                        break
                self._fail_pending(
                    RuntimeError("batch collector died; frame dropped")
                )
                self._thread = self._start_collector()

    # -- collector side -----------------------------------------------------

    def _collect(self) -> list[_Pending]:
        first = self._q.get()
        if first is None:
            return []
        batch = [first]
        deadline = _now() + self._window_s
        while len(batch) < self._max_batch:
            remaining = deadline - _now()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                break
            batch.append(item)
        return batch

    def _loop(self) -> None:
        while not self._stopped.is_set():
            batch = self._collect()
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
            if not batch:
                continue
            # deliberately OUTSIDE _run_group's guard: an injected fault
            # here kills the collector thread itself, which is exactly the
            # failure mode the watchdog exists for
            inject("serving.batch.collect")
            by_shape: dict[tuple, list[_Pending]] = {}
            for p in batch:
                by_shape.setdefault(p.frame_rgb.shape[:2], []).append(p)
            for group in by_shape.values():
                self._run_group(group)

    def _run_group(self, group: list[_Pending]) -> None:
        try:
            inject("serving.batch.dispatch")
            n = len(group)
            obs.BATCH_SIZE.observe(n)
            b = _bucket(n, self._max_batch)
            pad = b - n
            frames = np.stack(
                [p.frame_rgb for p in group] + [group[0].frame_rgb] * pad
            )
            depths = np.stack(
                [p.depth for p in group] + [group[0].depth] * pad
            )
            intr = np.stack(
                [p.intrinsics for p in group] + [group[0].intrinsics] * pad
            )
            scales = np.asarray(
                [p.depth_scale for p in group]
                + [group[0].depth_scale] * pad, np.float32,
            )
            out = self._analyze(frames, depths, intr, scales)
            import jax

            host = jax.tree.map(np.asarray, out)
            for i, p in enumerate(group):
                p.result = jax.tree.map(lambda a, _i=i: a[_i], host)
                p.done.set()
        except BaseException as exc:  # deliver, don't kill the collector
            log.exception(
                "batched dispatch failed (affected traces: %s)",
                ",".join(
                    p.trace_ctx.trace_id if p.trace_ctx is not None else "-"
                    for p in group
                ),
            )
            for p in group:
                if not p.done.is_set():
                    p.error = exc
                    p.done.set()


def _now() -> float:
    import time

    return time.monotonic()
