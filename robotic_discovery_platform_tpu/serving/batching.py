"""Cross-stream micro-batching for the analysis server.

The reference serves strictly one frame per request, sequentially per stream
(reference: services/vision_analysis/server.py:116): with 10 worker threads
the GPU sees batch-1 forwards regardless of load. On TPU the model forward
is where the MXU time goes and batch-1 leaves the chip mostly idle, so this
module coalesces frames from *concurrent gRPC streams* into one batched
dispatch (SURVEY.md section 5.7b calls this the single biggest
serving-throughput lever).

Design: stream handler threads ``submit()`` a frame and block on a
per-request event; a single collector thread drains the queue, waits at most
``window_ms`` for co-arriving frames, groups them by (H, W) camera geometry,
pads each group up to the next power-of-two bucket (so XLA compiles a handful
of batch shapes, not one per group size), runs the batched fused graph, and
fans results back out. Padding frames are replicas of the first frame and
their results are dropped.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclass
class _Pending:
    frame_rgb: np.ndarray
    depth: np.ndarray
    intrinsics: np.ndarray
    depth_scale: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: BaseException | None = None


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class BatchDispatcher:
    """Coalesce concurrent frame analyses into batched dispatches.

    Args:
        analyze_batch: ``(frames [B,H,W,3] u8 RGB, depths [B,H,W] u16,
            intrinsics [B,3,3], scales [B]) -> FrameAnalysis`` with leading
            batch dim on every output (ops/pipeline.make_batch_analyzer,
            already closed over the model variables).
        window_ms: how long to hold the first frame of a batch waiting for
            co-arriving frames. The reference's dead ``batch_window_ms`` knob
            (round-1 review) is live here.
        max_batch: hard cap per dispatch.
    """

    def __init__(self, analyze_batch: Callable, window_ms: float = 2.0,
                 max_batch: int = 8):
        self._analyze = analyze_batch
        self._window_s = window_ms / 1e3
        self._max_batch = max_batch
        self._q: queue.Queue[_Pending | None] = queue.Queue()
        self._stopped = threading.Event()
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="batch-dispatcher", daemon=True
        )
        self._thread.start()

    # -- caller side --------------------------------------------------------

    @shape_contract(frame_rgb=("h w 3", "uint8"), depth="h w",
                    intrinsics="3 3")
    def submit(self, frame_rgb, depth, intrinsics, depth_scale):
        """Block until this frame's analysis is available; returns the
        unbatched FrameAnalysis slice (host numpy leaves)."""
        p = _Pending(frame_rgb, depth, np.asarray(intrinsics, np.float32),
                     float(depth_scale))
        # enqueue under the lock stop() drains under: a submit either lands
        # BEFORE the drain (and is error-completed by it) or observes
        # stopped and raises -- it can never enqueue after the drain and
        # block forever on done.wait()
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("dispatcher stopped")
            self._q.put(p)
        p.done.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        """Idempotent. Every pending or racing submit is completed (with a
        'dispatcher stopped' error if its frame was never dispatched);
        no caller is left blocked."""
        with self._submit_lock:
            self._stopped.set()
            self._q.put(None)
        self._thread.join(timeout=5)
        # error-complete anything the collector left behind
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.done.is_set():
                item.error = RuntimeError("dispatcher stopped")
                item.done.set()

    # -- collector side -----------------------------------------------------

    def _collect(self) -> list[_Pending]:
        first = self._q.get()
        if first is None:
            return []
        batch = [first]
        deadline = _now() + self._window_s
        while len(batch) < self._max_batch:
            remaining = deadline - _now()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                break
            batch.append(item)
        return batch

    def _loop(self) -> None:
        while not self._stopped.is_set():
            batch = self._collect()
            if not batch:
                continue
            by_shape: dict[tuple, list[_Pending]] = {}
            for p in batch:
                by_shape.setdefault(p.frame_rgb.shape[:2], []).append(p)
            for group in by_shape.values():
                self._run_group(group)

    def _run_group(self, group: list[_Pending]) -> None:
        try:
            n = len(group)
            b = _bucket(n, self._max_batch)
            pad = b - n
            frames = np.stack(
                [p.frame_rgb for p in group] + [group[0].frame_rgb] * pad
            )
            depths = np.stack(
                [p.depth for p in group] + [group[0].depth] * pad
            )
            intr = np.stack(
                [p.intrinsics for p in group] + [group[0].intrinsics] * pad
            )
            scales = np.asarray(
                [p.depth_scale for p in group]
                + [group[0].depth_scale] * pad, np.float32,
            )
            out = self._analyze(frames, depths, intr, scales)
            import jax

            host = jax.tree.map(np.asarray, out)
            for i, p in enumerate(group):
                p.result = jax.tree.map(lambda a, _i=i: a[_i], host)
                p.done.set()
        except BaseException as exc:  # deliver, don't kill the collector
            log.exception("batched dispatch failed")
            for p in group:
                if not p.done.is_set():
                    p.error = exc
                    p.done.set()


def _now() -> float:
    import time

    return time.monotonic()
