"""Cross-stream micro-batching for the analysis server, pipelined.

The reference serves strictly one frame per request, sequentially per stream
(reference: services/vision_analysis/server.py:116): with 10 worker threads
the GPU sees batch-1 forwards regardless of load. On TPU the model forward
is where the MXU time goes and batch-1 leaves the chip mostly idle, so this
module coalesces frames from *concurrent gRPC streams* into one batched
dispatch (SURVEY.md section 5.7b calls this the single biggest
serving-throughput lever).

Design: a three-stage pipeline that exploits JAX async dispatch, so the
device never idles while the host stages or fans out (the classic
serving-pipeline stall that Clipper-style async dispatch pipelines
eliminate):

1. **Collector/stager** -- stream handler threads ``submit()`` a frame and
   block on a per-request event; the collector drains the queue, waits at
   most ``window_ms`` for co-arriving frames, groups them by (H, W) camera
   geometry, pads each group up to the next power-of-two bucket into a
   *preallocated, pooled* host buffer (no fresh ``np.stack`` copies per
   dispatch), stages it onto the device (``ops.pipeline.stage_batch``),
   and launches the jitted analyzer WITHOUT waiting for the result --
   the jit call returns as soon as the computation is enqueued.
2. **Bounded in-flight window** -- at most ``max_inflight`` dispatches may
   be launched-but-not-completed at once (``ServerConfig.
   max_inflight_dispatches``, default 2; ``RDP_INFLIGHT`` overrides), so
   device memory stays capped while batch N+1's staging and compute
   overlap batch N's completion. ``max_inflight=1`` is the serial mode:
   bit-identical results, no overlap.
3. **Completer** -- a second thread drains finished dispatches in launch
   order, performs the single blocking D2H (``np.asarray``) off the
   collector's critical path, and fans results back to the per-stream
   events. Padding frames are replicas of the first frame and their
   results are dropped.

Multi-chip routing (:class:`DeviceRouter`, over a ``parallel/mesh``
"data"-axis mesh): without a router every dispatch lands on ONE chip and
the rest of the mesh idles. A router spreads the in-flight window across
the mesh in one of two modes:

- **round_robin** -- each launched bucket is staged whole
  (``ops/pipeline.stage_batch`` with a per-chip ``device_put``) onto the
  least-loaded chip (ties walk the ring), giving N independent in-flight
  windows of ``max_inflight`` each; the ONE shared completer still drains
  in global launch order, so per-stream result order is unchanged.
  Aggregate FPS scales with chips for single-frame buckets.
- **sharded** -- one large padded bucket is placed with
  ``NamedSharding(P("data"))`` so a single dispatch splits over the mesh
  "data" axis (per-shard H2D straight from the pooled staging buffers);
  the in-flight window stays global.

``max_inflight=1`` on a single-device mesh (or no router at all) is the
serial mode: bit-identical results, no overlap. A dead stage's watchdog
recovery and ``stop()``'s drain guarantees hold per chip -- the window
reset rebuilds EVERY chip's semaphore, and pooled buffers ride their
dispatch regardless of which chip ran it.

Resilience (resilience/ package):

- the queue is *bounded*: a submit arriving with ``max_backlog`` frames
  already waiting fast-fails with :class:`OverloadedError` (the server maps
  it to RESOURCE_EXHAUSTED) instead of growing latency without bound;
- every submit carries a deadline (``submit_timeout_s``, or the caller's
  tighter one) instead of the old unbounded ``done.wait()`` -- a handler
  thread can no longer be parked forever;
- a watchdog notices a collector OR completer thread that died outside its
  per-dispatch guard, error-completes the frames stranded in EITHER queue
  (submit backlog and in-flight completions alike), resets the in-flight
  window, and restarts the dead stage;
- ``stop()`` error-completes frames stranded in either queue; no submitter
  is ever left blocked.

Fault-injection sites (resilience/faults.py): ``serving.batch.collect``
fires in the collector loop outside the dispatch guard (chaos tests kill the
collector here), ``serving.batch.dispatch`` fires inside the launch guard
(failed / slow staging+launch), ``serving.batch.complete`` fires inside the
completer's guard (failed / slow D2H: the dispatch's frames error-complete,
the completer keeps draining).

Observability (observability/ package): queue depth gauge
(``rdp_batch_queue_depth``), per-dispatch batch-size histogram,
in-flight-dispatch gauge (``rdp_batch_inflight_dispatches``), per-dispatch
overlap histogram (``rdp_batch_overlap_seconds``: how long a completing
dispatch overlapped the next one's staging/compute), stage-split latency
(``rdp_batch_stage_seconds``: stage / launch / complete), watchdog restart
counter; each submit carries its stream's span context across the
collector-thread hop so dispatch failures can name the traces they hit.

Flight recorder (observability/recorder.py): every dispatch additionally
records one span **timeline** -- per-frame ``submit`` spans (queue +
window wait, carrying each frame's trace ID), ``collect``, ``stage``
(host fill + H2D), ``launch`` (async jit dispatch), and ``complete``
(blocking D2H + fan-out), all children of one ``dispatch`` root labeled
with the routed ``chip``, padded ``bucket``, and dispatch ``mode`` --
into the bounded ring behind ``GET /debug/spans``. Failed dispatches and
watchdog restarts are pinned so post-mortems never race the ring. The
recorder only ever touches host-side ``monotonic_ns`` stamps: serial-mode
(depth-1, 1-chip) results stay bit-identical with it enabled.

Overload control (serving/admission.py + serving/controller.py):

- the backlog is a :class:`~.admission.DeadlineQueue`: every submit
  carries its absolute deadline into the queue, a put at the cap evicts
  the queued frame with the least remaining headroom instead of blindly
  rejecting the newcomer (``admission="fifo"`` restores position-based
  shedding), and the collector drops frames whose deadline is already
  unmeetable given the EWMA per-frame service-time estimate -- BEFORE
  paying staging/H2D/device time (``rdp_shed_by_deadline_total``);
- a submit that times out marks its frame *abandoned*; the collector
  skips abandoned frames instead of staging device work for a caller
  that already gave up (the PR 7 satellite bugfix);
- the reactive controller (serving/controller.py) retunes
  ``max_inflight``/``window_ms``/``bucket_floor``/dispatch mode online
  through the ``set_*`` mutators below; every knob is read per dispatch,
  so a change applies from the next launch with no restart. With the
  controller enabled but idle (no actions), serial depth-1 results stay
  bitwise identical -- every mutator is host-side scheduling state.

Chip quarantine (:class:`DeviceRouter` with ``breaker_failures > 0``):
each ring chip runs a per-chip :class:`~resilience.CircuitBreaker` over
its dispatch outcomes. A chip whose breaker opens is *quarantined*:
removed from the routing ring (``rdp_quarantined_chips``), its health
entry flipped NOT_SERVING via ``on_health``, and its in-flight frames
failed over to healthy chips (requeued at the queue front, bounded per
frame) -- zero lost frames when the mesh has a healthy chip left. The
last healthy chip is never quarantined. After ``breaker_reset_s`` the
half-open breaker admits ONE probe dispatch; a completed probe closes
the breaker and reinstates the chip (health back to SERVING). The
per-chip fault sites ``serving.chip.<i>.dispatch`` (kinds exc/slow)
make all of this drivable from ``RDP_FAULTS`` without code changes.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np

from robotic_discovery_platform_tpu.analysis.contracts import shape_contract
from robotic_discovery_platform_tpu.observability import (
    events,
    instruments as obs,
    journal as journal_lib,
    recorder as recorder_lib,
    trace,
)
from robotic_discovery_platform_tpu.ops import pipeline as pipeline_lib
from robotic_discovery_platform_tpu.parallel import mesh as mesh_lib
from robotic_discovery_platform_tpu.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    inject,
)
from robotic_discovery_platform_tpu.resilience import (
    sites as fault_sites,
)
from robotic_discovery_platform_tpu.serving import egress as egress_lib
from robotic_discovery_platform_tpu.serving import entropy
from robotic_discovery_platform_tpu.serving.admission import (
    DeadlineQueue,
    OverloadedError,
    ServiceTimeEstimator,
)
from robotic_discovery_platform_tpu.utils.lockcheck import checked_lock
from robotic_discovery_platform_tpu.utils.logging import get_logger

log = get_logger(__name__)

_INFLIGHT_ENV_VAR = "RDP_INFLIGHT"
_CHIPS_ENV_VAR = "RDP_SERVING_CHIPS"
_MODE_ENV_VAR = "RDP_DISPATCH_MODE"

DISPATCH_MODES = ("round_robin", "sharded")


def resolve_max_inflight(configured: int) -> int:
    """The effective in-flight-dispatch cap: ``RDP_INFLIGHT`` when set,
    else the configured value; never below 1 (1 = serial dispatch)."""
    raw = os.environ.get(_INFLIGHT_ENV_VAR)
    value = int(raw) if raw else int(configured)
    return max(1, value)


def resolve_serving_chips(configured: int) -> int:
    """The effective serving-mesh chip count: ``RDP_SERVING_CHIPS`` when
    set, else ``ServerConfig.serving_mesh``. Negative = every available
    device (resolved at mesh build, not here); 0 clamps to 1 (single-chip
    dispatch, exactly the router-less behavior)."""
    raw = os.environ.get(_CHIPS_ENV_VAR)
    value = int(raw) if raw else int(configured)
    if value < 0:
        return len(jax.devices())
    return max(1, value)


def resolve_dispatch_mode(configured: str) -> str:
    """The effective dispatch mode: ``RDP_DISPATCH_MODE`` when set, else
    ``ServerConfig.dispatch_mode``; dashes normalize to underscores."""
    mode = (os.environ.get(_MODE_ENV_VAR) or configured).replace("-", "_")
    if mode not in DISPATCH_MODES:
        raise ValueError(
            f"unknown dispatch mode {mode!r}; expected one of "
            f"{DISPATCH_MODES}"
        )
    return mode


def resolve_precision(configured: str) -> str:
    """The effective serving precision tier: ``RDP_PRECISION`` when set,
    else ``ServerConfig.precision`` (same env-knob convention as the
    resolvers above; the validation lives with the quantizer)."""
    from robotic_discovery_platform_tpu.ops.pallas import quant

    return quant.resolve_precision(configured)


class DeviceRouter:
    """Placement policy for the dispatcher's in-flight window over a
    serving mesh (``parallel.mesh.make_serving_mesh``).

    Args:
        mesh: a Mesh whose data-major device ring the router spreads
            dispatches over (serving only uses the "data" axis).
        mode: "round_robin" (whole buckets onto the least-loaded chip) or
            "sharded" (each bucket split over the "data" axis).
        analyzers: optional per-chip analyzer callables, same signature as
            ``BatchDispatcher``'s ``analyze_batch``. The serving layer
            passes closures over per-chip replicated model variables here
            (round_robin: one per ring position; sharded: a single entry
            closed over mesh-replicated variables) -- without them the
            dispatcher's shared analyzer is used on every chip, which is
            correct but re-transfers uncommitted weights per dispatch.
        sharded_analyzer: optional mesh-replicated analyzer alongside
            per-chip ``analyzers``: a router constructed round_robin
            with this set can flip modes ONLINE (``set_mode``), which is
            how the reactive controller picks round_robin vs sharded per
            load level (the AlpaServe tradeoff).
        breaker_failures / breaker_reset_s: per-chip quarantine circuit
            breakers (0 disables quarantine -- the default, so direct
            constructions keep PR 5 semantics). Only meaningful for
            round_robin routing over > 1 chip; the sharded window spans
            every chip in one dispatch and has no per-chip failure
            domain.
        on_health: ``(chip_index, serving: bool)`` callback invoked on
            quarantine/reinstatement -- the serving layer flips the
            ``rdp.serving.chip.<i>`` grpc.health.v1 entry here.
        clock: injectable monotonic clock for the breakers (fake-clock
            quarantine tests never sleep through reset timeouts).
    """

    def __init__(self, mesh, mode: str = "round_robin", analyzers=None, *,
                 sharded_analyzer=None, breaker_failures: int = 0,
                 breaker_reset_s: float = 30.0, on_health=None,
                 clock=time.monotonic):
        if mode not in DISPATCH_MODES:
            raise ValueError(
                f"unknown dispatch mode {mode!r}; expected one of "
                f"{DISPATCH_MODES}"
            )
        self.mesh = mesh
        self.mode = mode
        self.ring = mesh_lib.device_ring(mesh)
        self.analyzers = list(analyzers) if analyzers is not None else None
        self.sharded_analyzer = sharded_analyzer
        if self.analyzers is not None and mode == "sharded":
            # legacy shape: a sharded router takes its one mesh-replicated
            # analyzer as a single-entry list
            if len(self.analyzers) != 1:
                raise ValueError(
                    f"sharded router over {len(self.ring)} chips expected "
                    f"1 analyzer(s), got {len(self.analyzers)}"
                )
            self.sharded_analyzer = self.analyzers[0]
            self.analyzers = None
        if self.analyzers is not None and len(self.analyzers) != len(self.ring):
            raise ValueError(
                f"{mode} router over {len(self.ring)} chips expected "
                f"{len(self.ring)} analyzer(s), got {len(self.analyzers)}"
            )
        # built whenever the sharded layout is reachable (constructed
        # sharded, or mode-switchable round_robin)
        self.sharding = (
            mesh_lib.batch_sharding(mesh)
            if mode == "sharded" or sharded_analyzer is not None
            else None
        )
        # -- chip quarantine state ------------------------------------------
        self.quarantine_enabled = (
            breaker_failures > 0 and mode == "round_robin"
            and len(self.ring) > 1
        )
        self.on_health = on_health
        self._qlock = checked_lock("batching.router.quarantine")
        self._quarantined: set[int] = set()  # guarded_by: _qlock
        # distinct models whose dispatches failed on each chip since its
        # last success: a single model failing deterministically is a
        # MODEL bug (its frames fail over / error), not a chip fault --
        # only failures spanning >= 2 models (or a single-model
        # dispatcher's failures) feed the quarantine breaker, so one bad
        # zoo model can never quarantine healthy silicon out from under
        # its neighbors
        self._fail_models: dict[int, set[str]] = {}  # guarded_by: _qlock
        #: chips quarantined since construction (monotone; the gauge is
        #: the live set size)
        self.quarantines_total = 0  # guarded_by: _qlock
        self.breakers: list[CircuitBreaker] = []
        if self.quarantine_enabled:
            self.breakers = [
                CircuitBreaker(
                    failure_threshold=breaker_failures,
                    reset_timeout_s=breaker_reset_s,
                    name=f"serving.chip.{i}", clock=clock,
                )
                for i in range(len(self.ring))
            ]

    @property
    def chips(self) -> int:
        return len(self.ring)

    @property
    def can_switch_modes(self) -> bool:
        """True when the controller may retarget round_robin vs sharded
        online: requires the per-chip windows of a round_robin
        construction plus a staged sharded layout."""
        return self.sharding is not None and self.sharded_analyzer is not None

    def set_mode(self, mode: str) -> None:
        """Online dispatch-mode switch (controller actuator). Reads of
        ``self.mode`` are per-dispatch, so the change applies from the
        next launch; in-flight dispatches finish under their era's
        placement."""
        if mode not in DISPATCH_MODES:
            raise ValueError(
                f"unknown dispatch mode {mode!r}; expected one of "
                f"{DISPATCH_MODES}"
            )
        if mode == self.mode:
            return
        if not self.can_switch_modes:
            raise ValueError(
                "router was not built mode-switchable (needs round_robin "
                "construction with a sharded_analyzer)"
            )
        log.info("dispatch mode: %s -> %s", self.mode, mode)
        self.mode = mode

    # -- quarantine ----------------------------------------------------------

    @property
    def quarantined(self) -> frozenset[int]:
        with self._qlock:
            return frozenset(self._quarantined)

    def healthy_chips(self) -> tuple[int, ...]:
        with self._qlock:
            return tuple(i for i in range(len(self.ring))
                         if i not in self._quarantined)

    def probe_candidate(self) -> int | None:
        """A quarantined chip whose half-open breaker admits a probe NOW,
        else None. The breaker holds the probe slot until the dispatch's
        outcome is recorded, so at most one probe rides each chip."""
        if not self.quarantine_enabled:
            return None
        with self._qlock:
            quarantined = sorted(self._quarantined)
        for i in quarantined:
            if self.breakers[i].allow():
                return i
        return None

    def failure_confined(self, chip: int, model: str) -> bool:
        """True when every recorded failure on ``chip`` since its last
        success came from ``model`` alone -- the signature of a broken
        MODEL rather than a broken chip. The dispatcher uses this to cut
        the failover budget to one attempt: ricocheting a deterministic
        model error around the whole ring starves the healthy models'
        frames behind it in the queue for nothing."""
        with self._qlock:
            fails = self._fail_models.get(chip)
            return fails is not None and fails == {model}

    def record_result(self, chip: int, ok: bool,
                      exc: BaseException | None = None,
                      model: str = "", multi_model: bool = False) -> None:
        """Feed one dispatch outcome on ``chip`` into its breaker and
        apply the quarantine/reinstatement transition it implies.

        ``model``/``multi_model``: under a model zoo, a failure only
        counts toward the CHIP breaker when failures on that chip span
        more than one model (or the dispatcher serves a single model --
        the pre-zoo semantics): a chip that fails model A's dispatches
        while completing model B's is running a broken MODEL, and
        quarantining it would amplify one tenant's bug into mesh-wide
        capacity loss for every other tenant."""
        if not self.quarantine_enabled or not (0 <= chip < len(self.ring)):
            return
        breaker = self.breakers[chip]
        if ok:
            with self._qlock:
                self._fail_models.pop(chip, None)
            breaker.record_success()
            with self._qlock:
                reinstated = chip in self._quarantined
                self._quarantined.discard(chip)
                live = len(self._quarantined)
            if reinstated:
                obs.QUARANTINED_CHIPS.set(live)
                journal_lib.JOURNAL.append(
                    events.CHIP_REINSTATE, chip=chip, quarantined=live)
                log.info("chip %d reinstated after successful probe "
                         "dispatch", chip)
                if self.on_health is not None:
                    self.on_health(chip, True)
            return
        with self._qlock:
            fails = self._fail_models.setdefault(chip, set())
            fails.add(model)
            chip_level = not multi_model or len(fails) >= 2
            already = chip in self._quarantined
            last_healthy = (not already
                            and len(self._quarantined) >= len(self.ring) - 1)
        if not chip_level:
            # one model failing alone on this chip: its frames fail over
            # or error (the caller handles that); the chip's breaker
            # never hears about it
            return
        if last_healthy:
            # never quarantine the last chip: a degraded mesh still
            # serves; breaker state is left untouched so a recovered
            # sibling's failure history cannot strand the ring empty
            log.warning(
                "chip %d dispatch failed (%s) but it is the last healthy "
                "chip; not quarantining", chip,
                exc if exc is not None else "unknown error",
            )
            return
        breaker.record_failure(exc)
        if breaker.state != "open":
            return
        with self._qlock:
            newly = chip not in self._quarantined
            self._quarantined.add(chip)
            if newly:
                self.quarantines_total += 1
            live = len(self._quarantined)
        if newly:
            obs.QUARANTINED_CHIPS.set(live)
            obs.CHIP_QUARANTINES.labels(chip=str(chip)).inc()
            journal_lib.JOURNAL.append(
                events.CHIP_QUARANTINE, chip=chip, quarantined=live,
                error=str(exc) if exc is not None else "unknown",
            )
            log.error(
                "chip %d quarantined after repeated dispatch failures "
                "(%s); failing its in-flight frames over to %d healthy "
                "chip(s)", chip,
                exc if exc is not None else "unknown error",
                len(self.ring) - live,
            )
            if self.on_health is not None:
                self.on_health(chip, False)


@dataclass(eq=False)  # identity semantics: instances live in _pending sets
class _Pending:
    #: pixels -- or the coefficient half of a split decode, in which case
    #: this frame rides the dispatcher's coefficient lane (grouped by
    #: (model, "coef", geometry, subsampling); the device decodes)
    frame_rgb: np.ndarray | entropy.CoefficientFrame
    depth: np.ndarray
    intrinsics: np.ndarray
    depth_scale: float
    #: zoo model key this frame rides ("" = the default model; the
    #: collector groups by (model, geometry), so one dispatch only ever
    #: carries one model's frames -- per-model fault isolation is
    #: structural, not checked)
    model: str = ""
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: BaseException | None = None
    # the submitting stream's span context, carried across the thread hop
    # (contextvars do not flow into the collector thread) so dispatch-side
    # logs can name the traces of the frames they affected
    trace_ctx: Any = None
    # when the frame entered the queue; the flight recorder's per-frame
    # "submit" span (queue + window wait) starts here
    submit_ns: int = field(default_factory=time.monotonic_ns)
    # absolute monotonic deadline (submit timeout); admission orders
    # evictions by remaining headroom against this, and the collector
    # sheds the frame outright once it is unmeetable
    deadline_t: float | None = None
    # set by a submitter whose wait timed out: the caller is gone, so the
    # collector must not stage device work for this frame
    abandoned: bool = False
    # times this frame was failed over to another chip after a dispatch
    # failure (bounded per frame so a deterministic compute error cannot
    # ricochet around the ring forever)
    failovers: int = 0


#: host staging alignment (bytes). 64 covers a cache line and the widest
#: vector loads the runtime's H2D memcpy uses; np.empty only guarantees
#: 16, so pooled buffers over-allocate and slice to a 64-byte boundary.
_STAGE_ALIGN = 64


def _aligned_empty(shape: tuple, dtype) -> np.ndarray:
    """``np.empty`` whose first byte sits on a ``_STAGE_ALIGN`` boundary.

    Over-allocates by one alignment unit and views in at the aligned
    offset -- the portable way to pin staging-buffer alignment without a
    real pinned-memory API. The base allocation stays referenced through
    the view, and pooled retention (``_pool_take``/``_pool_put``) is what
    keeps the pages resident: each (geometry, bucket) key settles on a
    few long-lived buffer sets that every H2D transfer reads from, so
    the runtime's staging copies always start cache-line-aligned."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape)) * dtype.itemsize
    raw = np.empty(nbytes + _STAGE_ALIGN, np.uint8)
    offset = (-raw.ctypes.data) % _STAGE_ALIGN
    return raw[offset:offset + nbytes].view(dtype).reshape(shape)


class _BucketBuffers:
    """One reusable set of host staging arrays for a (geometry, bucket)
    key: the collector fills rows in place instead of building fresh
    ``np.stack`` copies per dispatch. A buffer set is exclusive to one
    in-flight dispatch (the completer returns it to the pool only after
    the dispatch's device work is done), so refilling can never race a
    zero-copy ``device_put`` of a still-executing batch.

    Every array is allocated 64-byte-aligned (:func:`_aligned_empty`) and
    pinned by pool retention, so the runtime's H2D staging copy always
    streams from an aligned, resident host buffer.

    Fill-in-place contract (:meth:`fill` / :meth:`pad`): a frame's row is
    written straight from the pending frame's arrays into the slot this
    dispatch checked out. For raw-format wire payloads
    (serving/ingest.py) ``frame_rgb``/``depth`` are zero-copy
    ``np.frombuffer`` views of the gRPC message buffer, so the wire
    bytes land in the pooled slot with NO intermediate frame copy -- and
    ``ops/pipeline.stage_batch``'s ``device_put`` then reads each chip's
    H2D transfer straight out of these buffers."""

    __slots__ = ("key", "frames", "depths", "intr", "scales")

    def __init__(self, key: tuple, template: _Pending, b: int):
        h, w = template.frame_rgb.shape[:2]
        self.key = key
        self.frames = _aligned_empty((b, h, w, 3), template.frame_rgb.dtype)
        self.depths = _aligned_empty((b, h, w), template.depth.dtype)
        self.intr = _aligned_empty((b, 3, 3), np.float32)
        self.scales = _aligned_empty((b,), np.float32)

    def fill(self, i: int, p: _Pending) -> None:
        """Write frame ``p`` into row ``i`` in place (the ONE host copy a
        b > 1 frame pays between the wire and the device)."""
        self.frames[i] = p.frame_rgb
        self.depths[i] = p.depth
        self.intr[i] = p.intrinsics
        self.scales[i] = p.depth_scale

    def pad(self, n: int) -> None:
        """Replicate row 0 into the padding rows past ``n`` (skipped
        entirely for full buckets)."""
        if n < len(self.frames):
            self.frames[n:] = self.frames[0]
            self.depths[n:] = self.depths[0]
            self.intr[n:] = self.intr[0]
            self.scales[n:] = self.scales[0]


class _CoefBucketBuffers:
    """The coefficient-lane counterpart of :class:`_BucketBuffers`: pooled,
    64-byte-aligned host staging for frames whose color half is an
    entropy-decoded :class:`~serving.entropy.CoefficientFrame` (wire
    ``format = 2``, or the on-chip reference decode). The staged payload
    is the three quantized int16 coefficient planes plus the per-frame
    quant tables -- ``ops/pipeline.stage_coef_batch`` device_puts these
    buffers directly and the pixels first exist on the device."""

    __slots__ = ("key", "y", "cb", "cr", "qy", "qc",
                 "depths", "intr", "scales")

    def __init__(self, key: tuple, template: _Pending, b: int):
        cf = template.frame_rgb
        (ybh, ybw), (cbh, cbw) = entropy.block_grids(
            cf.height, cf.width, cf.subsampling
        )
        ny, nc = ybh * ybw, cbh * cbw
        dh, dw = template.depth.shape
        self.key = key
        self.y = _aligned_empty((b, ny, 64), np.int16)
        self.cb = _aligned_empty((b, nc, 64), np.int16)
        self.cr = _aligned_empty((b, nc, 64), np.int16)
        self.qy = _aligned_empty((b, 64), np.uint16)
        self.qc = _aligned_empty((b, 64), np.uint16)
        self.depths = _aligned_empty((b, dh, dw), template.depth.dtype)
        self.intr = _aligned_empty((b, 3, 3), np.float32)
        self.scales = _aligned_empty((b,), np.float32)

    def fill(self, i: int, p: _Pending) -> None:
        cf = p.frame_rgb
        self.y[i] = cf.y
        self.cb[i] = cf.cb
        self.cr[i] = cf.cr
        self.qy[i] = cf.qy
        self.qc[i] = cf.qc
        self.depths[i] = p.depth
        self.intr[i] = p.intrinsics
        self.scales[i] = p.depth_scale

    def pad(self, n: int) -> None:
        if n < len(self.y):
            self.y[n:] = self.y[0]
            self.cb[n:] = self.cb[0]
            self.cr[n:] = self.cr[0]
            self.qy[n:] = self.qy[0]
            self.qc[n:] = self.qc[0]
            self.depths[n:] = self.depths[0]
            self.intr[n:] = self.intr[0]
            self.scales[n:] = self.scales[0]


class _EgressStaging:
    """One packed dispatch's pooled host landing buffer, refcounted.

    The completer copies its single D2H fetch (the ``[B, P]`` uint8
    packed payload) into a pooled :func:`_aligned_empty` buffer and
    hands each live frame a zero-copy row view
    (serving/egress.PackedResult) plus this object's ``release_one`` as
    the release callback; the LAST release returns the buffer to the
    dispatcher's egress pool. Completing on behalf of frames whose
    waiter already gave up keeps the count exact in the common case; a
    release lost to the abandon race costs the pool one buffer, never
    correctness -- the buffer is plain GC'd memory and is only re-pooled
    once every row view's holder has called release."""

    __slots__ = ("buf", "_remaining", "_lock", "_pool_put")

    def __init__(self, buf: np.ndarray, n: int,
                 pool_put: Callable[[np.ndarray], None]):
        self.buf = buf
        self._remaining = n  # guarded_by: _lock
        self._lock = threading.Lock()
        self._pool_put = pool_put

    def release_one(self) -> None:
        with self._lock:
            self._remaining -= 1
            last = self._remaining == 0
        if last:
            self._pool_put(self.buf)


@dataclass(eq=False)
class _Dispatch:
    """A launched-but-not-completed batch riding the completion queue."""

    group: list[_Pending]
    out: Any  # the analyzer's (possibly still-computing) output tree
    bufs: _BucketBuffers | _CoefBucketBuffers | None
    # the in-flight slot this dispatch holds; released by the completer.
    # Carried per-dispatch so a watchdog window reset can never double-free
    # a fresh semaphore.
    slot: threading.Semaphore
    launch_t: float
    # which routed chip (ring index) launched this dispatch; 0 for the
    # single-device and data-sharded windows
    chip: int = 0
    # the dispatch mode at launch time ("single" without a router): mode
    # switches mid-flight must not misattribute a sharded dispatch's
    # outcome to chip 0's quarantine breaker
    mode: str = "single"
    # which zoo model this dispatch carries ("" = default) and the padded
    # bucket it launched as: the completer's service-time sample is keyed
    # per (model, bucket) so models never poison each other's estimates
    model: str = ""
    bucket: int = 0
    # when host staging began (seconds); the completer derives the
    # per-frame service-time estimate from staged_t -> completion
    staged_t: float = 0.0
    # this dispatch's flight-recorder timeline + its root span; the
    # completer closes the root and records the timeline
    timeline: Any = None
    root: Any = None


def _intrinsics_f32(intrinsics) -> np.ndarray:
    """Intrinsics as float32 [3,3], converting ONLY when needed: the
    serving layer hands in the geometry cache's float32 array
    (serving/ingest.GeometryCache) and must not pay a per-frame re-wrap;
    direct dispatcher users passing lists / float64 still convert."""
    if (isinstance(intrinsics, np.ndarray)
            and intrinsics.dtype == np.float32
            and intrinsics.shape == (3, 3)):
        return intrinsics
    return np.asarray(intrinsics, np.float32)


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


def _group_key(p: _Pending) -> tuple:
    """The collector's dispatch-group key: frames only ever batch with
    same-model, same-geometry co-arrivals -- and coefficient-lane frames
    additionally split by subsampling (the decode graph's shapes depend
    on it), never mixing with pixel frames."""
    f = p.frame_rgb
    if isinstance(f, entropy.CoefficientFrame):
        return (p.model, "coef", f.subsampling, f.height, f.width)
    return (p.model, f.shape[:2])


@dataclass(eq=False)
class _ModelBinding:
    """How the dispatcher reaches one non-default zoo model: the shared
    batched analyzer (already closed over that model's variables), plus
    optional per-chip replicas / a mesh-sharded variant mirroring the
    default model's DeviceRouter bindings."""

    analyze_batch: Callable
    per_chip: list | None = None
    sharded: Callable | None = None


class BatchDispatcher:
    """Coalesce concurrent frame analyses into pipelined batched dispatches.

    Args:
        analyze_batch: ``(frames [B,H,W,3] u8 RGB, depths [B,H,W] u16,
            intrinsics [B,3,3], scales [B]) -> FrameAnalysis`` with leading
            batch dim on every output (ops/pipeline.make_batch_analyzer,
            already closed over the model variables). Receives pre-staged
            device arrays; must not block on its own result (jit async
            dispatch).
        window_ms: how long to hold the first frame of a batch waiting for
            co-arriving frames. The reference's dead ``batch_window_ms`` knob
            (round-1 review) is live here.
        max_batch: hard cap per dispatch.
        max_backlog: queued-frame cap; submits beyond it shed load
            (:class:`OverloadedError`) instead of queuing.
        submit_timeout_s: default per-submit deadline; ``submit`` raises
            ``DeadlineExceeded`` when the result is not back in time.
        watchdog_interval_s: how often the watchdog checks collector +
            completer liveness (<= 0 disables the watchdog).
        max_inflight: bounded in-flight window -- how many dispatches may
            be launched but not yet completed at once. 1 = serial (launch
            N+1 only after N's results are on the host); 2 (default)
            overlaps batch N+1's staging/compute with batch N's D2H.
            Under a round_robin router the cap is PER CHIP (N independent
            windows); under a sharded router (and without a router) it is
            the one global window.
        router: optional :class:`DeviceRouter` spreading dispatches across
            a serving mesh. None (default) keeps today's single-device
            dispatch exactly.
        admission: backlog overflow policy -- "deadline" (default: evict
            the least-headroom queued frame at the cap, shed unmeetable
            frames before staging) or "fifo" (PR 2's position-based
            shedding, the overload-control-off comparison leg).
        flight_recorder: where per-dispatch span timelines are recorded
            (observability/recorder.py); defaults to the process-global
            ``RECORDER`` behind ``GET /debug/spans``. Tests inject a
            private one.
        placer: optional :class:`~robotic_discovery_platform_tpu.serving.
            zoo.ZooPlacer`; when set, each model's dispatches are
            restricted to its placed chips (``chips_for``) and every
            submit records an arrival into the placer's per-model rate
            windows. None (default) keeps the placement-free routing.
        model_label: display name of the DEFAULT model ("" key) in fault
            sites / metrics / placer keys -- the zoo's default entry
            name ("seg"); "default" when unset.
        coef_analyzer_factory: optional ``(model, height, width,
            subsampling) -> Callable`` building the batched decode+analyze
            graph for coefficient-lane frames
            (ops/pipeline.make_coef_batch_analyzer closed over the
            model's variables). Lazily invoked + memoized per key on the
            first coef dispatch of that geometry; None (default) rejects
            ``submit_coef`` dispatches.
        clock: injectable monotonic clock for every deadline decision
            (submit deadline, unmeetable-deadline shed, coalescing
            window) and the admission queue's headroom ordering -- one
            time source end to end, so fake-clock tests and the sim
            twin see the same deadlines the queue orders by. Profiling
            timestamps stay on wall time.
    """

    def __init__(self, analyze_batch: Callable, window_ms: float = 2.0,
                 max_batch: int = 8, max_backlog: int = 64,
                 submit_timeout_s: float = 30.0,
                 watchdog_interval_s: float = 1.0,
                 max_inflight: int = 2,
                 router: DeviceRouter | None = None,
                 admission: str = "deadline",
                 flight_recorder: recorder_lib.FlightRecorder | None = None,
                 placer=None, model_label: str = "default",
                 coef_analyzer_factory: Callable | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self._analyze = analyze_batch
        # coefficient lane (split JPEG decode): ``(model, height, width,
        # subsampling) -> batched decode+analyze callable`` (the serving
        # engine wires ops/pipeline.make_coef_batch_analyzer). Analyzers
        # are memoized per key on first dispatch; None fails coef
        # submissions with a clear error instead of a shape mismatch.
        self._coef_factory = coef_analyzer_factory  # guarded_by: _coef_lock
        self._coef_analyzers: dict[tuple, Callable] = {}  # guarded_by: _coef_lock
        self._coef_lock = checked_lock("batching.coef")
        # one time source for every CONTROL decision (submit deadlines,
        # unmeetable-deadline sheds, the coalescing window) AND the
        # admission queue's headroom ordering. The queue always took an
        # injectable clock; the dispatcher used to hardcode
        # time.monotonic() around it, so an injected (fake/sim) clock
        # skewed deadline_t against the queue's margin arithmetic.
        # Profiling spans (submit_ns & friends) deliberately stay on
        # wall time -- they measure the host, not the control plane.
        self._clock = clock
        self._recorder = (flight_recorder if flight_recorder is not None
                          else recorder_lib.RECORDER)
        self._placer = placer
        self._model_label = model_label or "default"
        # per-model dispatch bindings beyond the default ("" rides the
        # legacy analyzer/router construction untouched): name ->
        # _ModelBinding, bound by the serving layer per zoo generation.
        # Written only before serving starts (bind_model) -- reads on the
        # collector hot path are lock-free dict lookups.
        self._bindings: dict[str, _ModelBinding] = {}
        # (model, placement, bucket) combos whose batched graph has been
        # compiled (by eager warm-up OR the first lazy dispatch):
        # warming M x chips x buckets eagerly would explode startup, so
        # the serving layer eagerly warms a capped subset and everything
        # else compiles on first use -- this set is how tests and
        # /debug/zoo see which is which
        self.warmed: set[tuple] = set()  # guarded_by: _warm_lock
        self._warm_lock = checked_lock("batching.warmset")
        self._window_s = window_ms / 1e3
        self._max_batch = max_batch
        self._max_backlog = max_backlog
        self._submit_timeout_s = submit_timeout_s
        self._max_inflight = max(1, int(max_inflight))
        self._router = router
        #: best-case per-frame service time (staging -> completed D2H)
        #: over a sliding window; the collector's unmeetable-deadline
        #: shed consults this
        self.service_estimate = ServiceTimeEstimator()
        # liveness valve for the stale shed: after this many CONSECUTIVE
        # stale sheds with no completed dispatch in between, the next
        # frame is admitted regardless, so a stale estimate (or a pile
        # of doomed frames) can never starve the signal that refreshes
        # the estimate. Collector increments, completer resets: two
        # threads, so the counter rides the inflight lock (racecheck
        # RC002 surfaced the bare read-modify-write here). Keyed per
        # model alongside the estimator keys: model A shedding must not
        # burn (or reset) model B's probe budget.
        self._sheds_since_complete: dict[str, int] = {}  # guarded_by: _inflight_lock
        #: multiplier on the service estimate when deciding a deadline is
        #: unmeetable; the controller's brownout ladder raises it to shed
        #: earlier at admission (level 2), 1.0 = only shed truly doomed
        self.deadline_safety = 1.0
        #: controller-tunable floor on the padded bucket size (1 = off);
        #: see bucket_for
        self.bucket_floor = 1
        #: EWMA of recent dispatch sizes (frames per launch); the
        #: controller's round_robin-vs-sharded choice keys off occupancy
        self.recent_batch = 0.0
        if router is not None and router.sharding is not None:
            # the sharded layout is reachable (constructed sharded, or
            # mode-switchable): its geometry must hold up front
            chips = router.chips
            if chips & (chips - 1):
                raise ValueError(
                    f"sharded dispatch needs a power-of-two chip count "
                    f"(buckets are powers of two); got {chips}"
                )
            if max_batch < chips or max_batch % chips:
                raise ValueError(
                    f"sharded dispatch over {chips} chips needs max_batch "
                    f"to be a multiple of the chip count; got {max_batch}"
                )
        # the independent launch windows: one per ring chip under a
        # round_robin router, otherwise the single global window (the
        # sharded mode's one dispatch already spans every chip)
        if router is not None and router.mode == "round_robin":
            self._n_windows = router.chips
        else:
            self._n_windows = 1
        self._q = DeadlineQueue(max_backlog, policy=admission,
                                on_evict=self._on_evicted, clock=clock)
        self._cq: queue.Queue[_Dispatch | None] = queue.Queue()
        self._chip_slots = [
            threading.Semaphore(self._max_inflight)
            for _ in range(self._n_windows)
        ]
        self._inflight_lock = checked_lock("batching.inflight")
        self._inflight_count = 0  # guarded_by: _inflight_lock
        self._chip_inflight = [0] * self._n_windows  # guarded_by: _inflight_lock
        # least-loaded tie-break cursor (ring order)
        self._rr_next = 0  # guarded_by: _inflight_lock
        #: per-chip launched-dispatch / carried-frame totals (padding rows
        #: excluded); the bench derives per-chip FPS and balance from these
        self.chip_dispatches = [0] * self._n_windows  # guarded_by: _inflight_lock
        self.chip_frames = [0] * self._n_windows  # guarded_by: _inflight_lock
        self.chip_inflight_high_water = [0] * self._n_windows  # guarded_by: _inflight_lock
        #: high-water mark of concurrently in-flight dispatches; never
        #: exceeds ``max_inflight`` per window (tests and the bench assert
        #: on this)
        self.inflight_high_water = 0  # guarded_by: _inflight_lock
        #: total seconds completed dispatches overlapped the next launch
        #: (0.0 in serial mode); written only by the completer thread
        self.overlap_s_total = 0.0
        self._last_done_t = 0.0
        # pooled host staging buffers, keyed by (bucket, frame shape/dtype,
        # depth dtype); free-list only -- buffers in use ride the dispatch.
        # Capped per key at one buffer set per possible in-flight dispatch
        # plus the one being staged: anything beyond that is a leak, so
        # _pool_put drops extras instead of growing without bound.
        self._pool: dict[tuple, list[_BucketBuffers]] = {}  # guarded_by: _pool_lock
        self._pool_cap = self._max_inflight * self._n_windows + 1
        self._pool_lock = checked_lock("batching.pool")
        # pooled 64-byte-aligned landing buffers for packed egress
        # payloads, keyed by the fetched [B, P] shape: the completer's
        # single D2H per packed dispatch copies in here and stream
        # handlers read zero-copy row views until the refcounted release
        # (_EgressStaging) returns the buffer. Shares _pool_lock and the
        # _pool_cap leak bound.
        self._egress_pool: dict[tuple, list[np.ndarray]] = {}  # guarded_by: _pool_lock
        obs.SERVING_CHIPS.set(router.chips if router is not None else 1)
        self._stopped = threading.Event()
        self._submit_lock = checked_lock("batching.submit")
        # every not-yet-completed submit, whether still queued, staged, or
        # in flight on the device: the watchdog error-completes exactly
        # this set when a pipeline stage dies, so a frame caught between
        # queues is covered too
        self._pending: set[_Pending] = set()  # guarded_by: _pending_lock
        self._pending_lock = checked_lock("batching.pending")
        self.collector_restarts = 0
        self.completer_restarts = 0
        self._completer = self._start_completer()
        self._thread = self._start_collector()
        self._watchdog: threading.Thread | None = None
        if watchdog_interval_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, args=(watchdog_interval_s,),
                name="batch-dispatcher-watchdog", daemon=True,
            )
            self._watchdog.start()

    def _start_collector(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop, name="batch-dispatcher", daemon=True
        )
        t.start()
        return t

    def _start_completer(self) -> threading.Thread:
        t = threading.Thread(
            target=self._complete_loop, name="batch-completer", daemon=True
        )
        t.start()
        return t

    # -- caller side --------------------------------------------------------

    def bind_model(self, name: str, analyze_batch: Callable,
                   per_chip_analyzers=None, sharded_analyzer=None) -> None:
        """Register one non-default zoo model's batched analyzers so
        ``submit(model=name)`` can route to it. Call before serving that
        model (the serving layer binds the whole zoo at engine build)."""
        if not name:
            raise ValueError("the default model is bound at construction")
        self._bindings[name] = _ModelBinding(
            analyze_batch,
            per_chip=(list(per_chip_analyzers)
                      if per_chip_analyzers is not None else None),
            sharded=sharded_analyzer,
        )

    def bound_models(self) -> tuple[str, ...]:
        """Every model key this dispatcher routes ("" = default)."""
        return ("", *self._bindings)

    def set_coef_analyzer_factory(self, factory: Callable | None) -> None:
        """(Re)bind the coefficient-lane analyzer factory and drop the
        memoized graphs -- hot reload swaps model variables, so stale
        closures must not outlive the generation that built them."""
        with self._coef_lock:
            self._coef_factory = factory
            self._coef_analyzers.clear()

    def _display_model(self, model: str) -> str:
        return model or self._model_label

    @shape_contract(frame_rgb=("h w 3", "uint8"), depth="h w",
                    intrinsics="3 3")
    def submit(self, frame_rgb, depth, intrinsics, depth_scale,
               timeout_s: float | None = None, model: str = ""):
        """Block until this frame's analysis is available; returns the
        unbatched FrameAnalysis slice (host numpy leaves).

        ``model`` selects a bound zoo model ("" = the default engine,
        the pre-zoo contract); frames only ever batch with their own
        model's co-arrivals.

        Raises :class:`OverloadedError` when the backlog cap is hit (or
        this frame was evicted at the cap by a newer frame with more
        deadline headroom) and ``DeadlineExceeded`` when the result
        misses the submit deadline (``timeout_s`` if given and tighter,
        else ``submit_timeout_s``).
        """
        return self._submit_frame(frame_rgb, depth, intrinsics,
                                  depth_scale, timeout_s, model)

    def submit_coef(self, frame: entropy.CoefficientFrame, depth,
                    intrinsics, depth_scale,
                    timeout_s: float | None = None, model: str = ""):
        """Coefficient-lane :meth:`submit`: the color half is an
        entropy-decoded :class:`~serving.entropy.CoefficientFrame`
        (``format = 2`` wire payloads or the on-chip reference decode)
        and the pixels first exist on the device, decoded fused ahead of
        the analyzer. Batching, admission, deadlines, routing, and the
        result contract are identical to :meth:`submit`; frames group by
        (model, geometry, subsampling) and never mix with pixel
        frames."""
        if not isinstance(frame, entropy.CoefficientFrame):
            raise TypeError(
                f"submit_coef wants a CoefficientFrame, got "
                f"{type(frame).__name__}; pixel arrays ride submit()"
            )
        depth = np.asarray(depth)
        if depth.shape != (frame.height, frame.width):
            raise ValueError(
                f"depth shape {depth.shape} != frame geometry "
                f"({frame.height}, {frame.width})"
            )
        return self._submit_frame(frame, depth, intrinsics, depth_scale,
                                  timeout_s, model)

    def _submit_frame(self, frame_rgb, depth, intrinsics, depth_scale,
                      timeout_s: float | None, model: str):
        if model and model not in self._bindings:
            raise ValueError(
                f"unknown model {model!r}; bound: {self.bound_models()}"
            )
        if self._placer is not None:
            self._placer.record_arrival(self._display_model(model))
        timeout = self._submit_timeout_s
        if timeout_s is not None:
            timeout = min(timeout, timeout_s)
        p = _Pending(frame_rgb, depth, _intrinsics_f32(intrinsics),
                     float(depth_scale), model=model,
                     trace_ctx=trace.current(),
                     deadline_t=self._clock() + timeout)
        # enqueue under the lock stop() drains under: a submit either lands
        # BEFORE the drain (and is error-completed by it) or observes
        # stopped and raises -- it can never enqueue after the drain and
        # block forever on done.wait()
        with self._submit_lock:
            if self._stopped.is_set():
                raise RuntimeError("dispatcher stopped")
            with self._pending_lock:
                self._pending.add(p)
            try:
                self._q.put(
                    p, margin_s=(self.service_estimate.s_for(model)
                                 * self.deadline_safety)
                )
            except OverloadedError:
                with self._pending_lock:
                    self._pending.discard(p)
                raise
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
        try:
            if not p.done.wait(timeout):
                # the caller is giving up: flag the frame so the collector
                # never stages device work for it (it may already be in
                # flight, in which case its result is simply dropped)
                p.abandoned = True
                raise DeadlineExceeded(
                    f"batched analysis not ready within {timeout:.2f}s "
                    "(per-submit deadline)"
                )
        finally:
            with self._pending_lock:
                self._pending.discard(p)
        if p.error is not None:
            raise p.error
        return p.result

    def _on_evicted(self, p: _Pending) -> None:
        """DeadlineQueue eviction callback: error-complete the queued
        frame that lost its slot to a newer frame with more headroom.
        Runs under the queue lock -- only completes and counts."""
        p.error = OverloadedError(
            "frame evicted at the backlog cap: least remaining deadline "
            "headroom; shedding load"
        )
        p.done.set()
        obs.SHED_BY_DEADLINE.labels(point="evicted").inc()
        with self._pending_lock:
            self._pending.discard(p)

    def stop(self) -> None:
        """Idempotent. Every pending or racing submit is completed: frames
        already launched drain through the completer with real results when
        it is healthy, frames stranded in either queue get a 'dispatcher
        stopped' error. No caller is left blocked."""
        with self._submit_lock:
            self._stopped.set()
            self._q.put(None)
        self._thread.join(timeout=5)
        # the completer first drains every dispatch launched before the
        # sentinel (delivering their real results), then exits
        self._cq.put(None)
        self._completer.join(timeout=5)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
        # error-complete anything either queue still holds
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.done.is_set():
                item.error = RuntimeError("dispatcher stopped")
                item.done.set()
        while True:
            try:
                d = self._cq.get_nowait()
            except queue.Empty:
                break
            if d is None:
                continue
            self._pool_put(d.bufs)
            for p in d.group:
                if not p.done.is_set():
                    p.error = RuntimeError("dispatcher stopped")
                    p.done.set()
        self._fail_pending(RuntimeError("dispatcher stopped"))

    def _fail_pending(self, exc: BaseException) -> None:
        with self._pending_lock:
            stranded = [p for p in self._pending if not p.done.is_set()]
        for p in stranded:
            p.error = exc
            p.done.set()

    # -- controller actuators ------------------------------------------------
    # Every knob here is host-side scheduling state read per dispatch, so
    # an online retune applies from the next launch and an idle controller
    # changes nothing -- serial depth-1 parity stays bitwise.

    @property
    def router(self) -> DeviceRouter | None:
        return self._router

    @property
    def max_inflight(self) -> int:
        return self._max_inflight

    def set_max_inflight(self, n: int) -> None:
        """Online in-flight-window retune (the controller's AIMD knob).
        Rebuilds the per-window semaphores like a watchdog reset does;
        dispatches already in flight hold (and release) their own slot
        objects, so a shrink is honored from the next launch and the
        window converges as the old era drains."""
        n = max(1, int(n))
        with self._inflight_lock:
            if n == self._max_inflight:
                return
            old = self._max_inflight
            self._max_inflight = n
            self._pool_cap = n * self._n_windows + 1
            # deliberate epoch reset: in-flight dispatches hold their own
            # slot objects, so re-binding starts a fresh window rather
            # than splitting waiters
            self._chip_slots = [  # jaxlint: disable=JL013
                threading.Semaphore(n) for _ in range(self._n_windows)
            ]
        log.info("max_inflight retuned: %d -> %d", old, n)

    @property
    def window_ms(self) -> float:
        return self._window_s * 1e3

    def set_window_ms(self, window_ms: float) -> None:
        """Online batch-window retune; read once per collect cycle."""
        self._window_s = max(0.0, float(window_ms)) / 1e3

    def set_bucket_floor(self, floor: int) -> None:
        """Online bucket-floor retune: pad dispatches up to at least this
        bucket (amortizes per-dispatch overhead when the backlog is deep;
        1 = off). Clamped to max_batch by bucket_for."""
        self.bucket_floor = max(1, int(floor))

    def set_deadline_safety(self, factor: float) -> None:
        """How conservatively the collector sheds against the service
        estimate (brownout level 2 raises this to shed earlier)."""
        self.deadline_safety = max(1.0, float(factor))

    def backlog(self) -> int:
        """Frames currently queued for the collector."""
        return self._q.qsize()

    # -- watchdog ------------------------------------------------------------

    def _watch(self, interval_s: float) -> None:
        """Error-complete and restart if the collector or completer ever
        dies outside its per-dispatch guard (e.g. an exception in the
        grouping / collection / queue code itself): without this, every
        in-flight submitter of that era would wait out its full deadline
        for nothing, and all later submits would queue into a threadless
        pipeline stage."""
        while not self._stopped.wait(interval_s):
            collector_dead = not self._thread.is_alive()
            completer_dead = not self._completer.is_alive()
            if not (collector_dead or completer_dead):
                continue
            with self._submit_lock:
                if self._stopped.is_set():
                    return
                dead = ("collector" if collector_dead else "completer")
                if collector_dead:
                    self.collector_restarts += 1
                if completer_dead:
                    self.completer_restarts += 1
                obs.WATCHDOG_RESTARTS.inc()
                # pinned restart event: the post-mortem evidence must not
                # be overwritten by the healthy traffic that follows
                self._recorder.record_event(
                    "watchdog_restart", stage=dead,
                    error=f"batch {dead} thread died; "
                          f"{len(self._pending)} pending frame(s) failed",
                )
                journal_lib.JOURNAL.append(
                    events.WATCHDOG_RESTART, stage=dead,
                    pending=len(self._pending),
                )
                log.error(
                    "batch %s thread died unexpectedly; failing %d "
                    "pending frame(s) and restarting (restart #%d)",
                    dead, len(self._pending),
                    self.collector_restarts + self.completer_restarts,
                )
                # drain BOTH queues (the restarted stages start from an
                # empty pipeline; stranded submitters get an error now,
                # not a deadline timeout later), returning pooled buffers
                # from abandoned in-flight dispatches
                while True:
                    try:
                        self._q.get_nowait()
                    except queue.Empty:
                        break
                while True:
                    try:
                        d = self._cq.get_nowait()
                    except queue.Empty:
                        break
                    if d is not None:
                        self._pool_put(d.bufs)
                # fresh in-flight windows ON EVERY CHIP: slots held by
                # dispatches lost with the dead stage can never be
                # released (a dispatch still riding a live completer
                # releases its OWN slot object, never these new ones) --
                # the same deliberate epoch reset as set_max_inflight
                self._chip_slots = [  # jaxlint: disable=JL013
                    threading.Semaphore(self._max_inflight)
                    for _ in range(self._n_windows)
                ]
                with self._inflight_lock:
                    self._inflight_count = 0
                    self._chip_inflight = [0] * self._n_windows
                    self._sheds_since_complete.clear()
                    obs.INFLIGHT_DISPATCHES.set(0)
                    for chip in range(self._n_windows):
                        obs.CHIP_INFLIGHT.labels(chip=str(chip)).set(0)
                self._fail_pending(RuntimeError(
                    f"batch {dead} died; frame dropped"
                ))
                if collector_dead:
                    self._thread = self._start_collector()
                if completer_dead:
                    self._completer = self._start_completer()

    # -- collector / stager side --------------------------------------------

    def _admit(self, p: _Pending) -> bool:
        """Whether a dequeued frame is still worth staging. Abandoned
        frames (their submitter already timed out) are dropped silently;
        frames whose deadline is unmeetable given the current per-frame
        service-time estimate are error-completed NOW -- shed work is
        work never staged, and the device time goes to a frame that can
        still make it."""
        if p.abandoned:
            obs.SHED_BY_DEADLINE.labels(point="abandoned").inc()
            with self._pending_lock:
                self._pending.discard(p)
            return False
        if p.deadline_t is not None and self._q.policy == "deadline":
            # per-model estimate (admission.py): a cheap aux ride cannot
            # make the segmenter's deadlines look meetable, nor the
            # reverse -- each model sheds on its own history only
            est = self.service_estimate.s_for(p.model) * self.deadline_safety
            slack = p.deadline_t - self._clock()
            if est > 0 and slack < est:
                with self._inflight_lock:
                    if self._sheds_since_complete.get(p.model, 0) >= 8:
                        # probe-through: admit this frame despite the
                        # verdict so its ride refreshes the service
                        # estimate (the completer resets the counter);
                        # the valve is per model, like the estimate it
                        # exists to refresh
                        return True
                    self._sheds_since_complete[p.model] = (
                        self._sheds_since_complete.get(p.model, 0) + 1
                    )
                obs.SHED_BY_DEADLINE.labels(point="stale").inc()
                self._fail_group([p], DeadlineExceeded(
                    f"deadline unmeetable: ~{est * 1e3:.0f}ms estimated "
                    f"service vs {slack * 1e3:.0f}ms headroom; shed "
                    "before staging"
                ), log_it=False)
                with self._pending_lock:
                    self._pending.discard(p)
                return False
        return True

    def _collect(self) -> list[_Pending]:
        while True:
            first = self._q.get()
            if first is None:
                return []
            if self._admit(first):
                break
        batch = [first]
        deadline = self._clock() + self._window_s
        while len(batch) < self._max_batch:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                break
            if not self._admit(item):
                continue
            batch.append(item)
        return batch

    def _loop(self) -> None:
        while not self._stopped.is_set():
            batch = self._collect()
            obs.BATCH_QUEUE_DEPTH.set(self._q.qsize())
            if not batch:
                continue
            # deliberately OUTSIDE the launch guard: an injected fault
            # here kills the collector thread itself, which is exactly the
            # failure mode the watchdog exists for
            inject(fault_sites.SERVING_BATCH_COLLECT)
            collected_ns = time.monotonic_ns()
            # group by (model, geometry): a dispatch is single-model by
            # construction, so one model's chip fault can only ever fail
            # its own frames (per-model fault isolation)
            by_key: dict[tuple, list[_Pending]] = {}
            for p in batch:
                by_key.setdefault(_group_key(p), []).append(p)
            for group in by_key.values():
                self._launch_group(group, collected_ns)

    def _pool_take(self, key: tuple, template: _Pending):
        with self._pool_lock:
            free = self._pool.get(key)
            if free:
                bufs = free.pop()
                obs.BATCH_POOL_SIZE.set(
                    sum(len(v) for v in self._pool.values())
                )
                return bufs
        cls = (_CoefBucketBuffers
               if isinstance(template.frame_rgb, entropy.CoefficientFrame)
               else _BucketBuffers)
        return cls(key, template, key[0])

    def _pool_put(self, bufs: _BucketBuffers | None) -> None:
        if bufs is None:
            return
        with self._pool_lock:
            free = self._pool.setdefault(bufs.key, [])
            # capped free list: at most one buffer set per possible
            # in-flight dispatch plus the one being staged can ever be
            # legitimately out at once, so a longer free list is growth
            # from a leak path (e.g. repeated watchdog drains) -- drop the
            # extra and let the gauge make any further growth visible
            if len(free) < self._pool_cap:
                free.append(bufs)
            obs.BATCH_POOL_SIZE.set(sum(len(v) for v in self._pool.values()))

    def _egress_take(self, shape: tuple) -> np.ndarray:
        """A pooled aligned landing buffer for one packed dispatch's
        single D2H fetch (``[B, P]`` uint8)."""
        with self._pool_lock:
            free = self._egress_pool.get(shape)
            if free:
                buf = free.pop()
                obs.EGRESS_POOL_SIZE.set(
                    sum(len(v) for v in self._egress_pool.values())
                )
                return buf
        return _aligned_empty(shape, np.uint8)

    def _egress_put(self, buf: np.ndarray) -> None:
        """Return a fully released egress staging buffer to the free
        list (called by the LAST frame's ``PackedResult.release``,
        usually from a stream-handler thread)."""
        with self._pool_lock:
            free = self._egress_pool.setdefault(buf.shape, [])
            # same leak bound as _pool_put: beyond one buffer per
            # possible in-flight dispatch (plus one), growth means lost
            # releases -- drop and let the gauge show it
            if len(free) < self._pool_cap:
                free.append(buf)
            obs.EGRESS_POOL_SIZE.set(
                sum(len(v) for v in self._egress_pool.values())
            )

    # -- mesh routing --------------------------------------------------------

    def _allowed_chips(self, model: str) -> set[int] | None:
        """The placer's chip set for ``model`` (None = unrestricted).
        An empty/exhausted placement falls back to unrestricted: a
        placement is a throughput preference, never an availability
        constraint."""
        if self._placer is None:
            return None
        allowed = set(self._placer.chips_for(self._display_model(model)))
        allowed &= set(range(self._n_windows))
        return allowed or None

    def _pick_chip(self, model: str = "") -> int:
        """The ring index the next dispatch launches on: the least-loaded
        HEALTHY chip -- within the model's placed set when a ZooPlacer is
        wired -- by current in-flight count, ties walking the ring from
        the cursor (so an idle mesh round-robins and a skewed one heals).
        A quarantined chip whose half-open breaker admits a probe takes
        the dispatch instead -- that dispatch IS the probe, and its
        outcome decides reinstatement. Sharded dispatches always ride
        window 0 (one window spanning every chip)."""
        r = self._router
        if r is not None and r.mode == "sharded":
            return 0
        if self._n_windows == 1:
            return 0
        allowed = self._allowed_chips(model)
        if r is not None and r.quarantine_enabled:
            probe = r.probe_candidate()
            if probe is not None and (allowed is None or probe in allowed):
                log.info("routing probe dispatch to quarantined chip %d",
                         probe)
                return probe
            healthy = set(r.healthy_chips())
            placeable = (healthy if allowed is None
                         else (healthy & allowed) or healthy)
            with self._inflight_lock:
                loads = [
                    self._chip_inflight[i] if i in placeable
                    else float("inf")
                    for i in range(self._n_windows)
                ]
                chip = mesh_lib.least_loaded(loads, self._rr_next)
                self._rr_next = (chip + 1) % self._n_windows
            return chip
        with self._inflight_lock:
            if allowed is None:
                loads = self._chip_inflight
            else:
                loads = [
                    self._chip_inflight[i] if i in allowed
                    else float("inf")
                    for i in range(self._n_windows)
                ]
            chip = mesh_lib.least_loaded(loads, self._rr_next)
            self._rr_next = (chip + 1) % self._n_windows
        return chip

    def _placement(self, chip: int):
        """What ``stage_batch`` should place this dispatch with: the routed
        chip's device, the mesh-wide data sharding, or None (default
        device, router-less -- today's behavior exactly)."""
        if self._router is None:
            return None
        if self._router.mode == "sharded":
            return self._router.sharding
        return self._router.ring[chip]

    def _analyze_for(self, chip: int, model: str = "") -> Callable:
        if model:
            # non-default zoo model: its binding mirrors the default
            # model's router layout (per-chip replicas / sharded copy),
            # falling back to the shared closure when a layout was not
            # bound
            b = self._bindings[model]
            r = self._router
            if r is not None and r.mode == "sharded":
                return (b.sharded if b.sharded is not None
                        else b.analyze_batch)
            a = b.per_chip
            return a[min(chip, len(a) - 1)] if a else b.analyze_batch
        r = self._router
        if r is None:
            return self._analyze
        if r.mode == "sharded":
            return (r.sharded_analyzer if r.sharded_analyzer is not None
                    else self._analyze)
        a = r.analyzers
        return a[min(chip, len(a) - 1)] if a else self._analyze

    def bucket_for(self, n: int) -> int:
        """The padded bucket a group of ``n`` frames dispatches as, never
        below the controller's ``bucket_floor``. Sharded routing raises
        the floor to the chip count so every chip gets at least one row
        (the constructor validated divisibility)."""
        b = _bucket(max(n, min(self.bucket_floor, self._max_batch)),
                    self._max_batch)
        if self._router is not None and self._router.mode == "sharded":
            b = min(max(b, self._router.chips), self._max_batch)
        return b

    def warm(self, frames, depths, intrinsics, scales,
             model: str = "", chips=None) -> None:
        """Compile + run ``model``'s analyzer for this batch shape,
        blocking until done: warm-up and hot-reload pre-compilation
        route through here so a warmed (model, placement, bucket) never
        pays XLA compilation on a live frame.

        ``chips=None`` warms EVERY routed placement (the default model's
        historical eager warm; a mode-switchable router warms BOTH
        layouts so a controller mode flip mid-burst never stalls on a
        compile). An explicit chip list is the zoo's CAPPED eager warm:
        extra models warm one home placement each and everything else
        compiles lazily on its first dispatch -- eagerly warming
        M x chips x buckets would explode startup."""
        r = self._router
        b = len(frames)
        placements: list[tuple[Any, Callable, Any]] = []
        if r is not None and r.mode == "sharded":
            placements.append((r.sharding, self._analyze_for(0, model),
                               None))
        else:
            for chip in (range(self._n_windows) if chips is None
                         else chips):
                placements.append(
                    (self._placement(chip),
                     self._analyze_for(chip, model), chip)
                )
        if (chips is None and r is not None and r.can_switch_modes
                and len(frames) % r.chips == 0):
            if r.mode == "round_robin":
                other = (r.sharded_analyzer if not model
                         else self._bindings[model].sharded)
                if other is not None:
                    placements.append((r.sharding, other, None))
        for device, analyze, key in placements:
            staged = pipeline_lib.stage_batch(
                frames, depths, intrinsics, scales, device=device
            )
            jax.block_until_ready(analyze(*staged))
            with self._warm_lock:
                self.warmed.add((model, key, b))

    def warm_coef(self, frame, depths, intrinsics, scales,
                  model: str = "", chips=None) -> None:
        """Coefficient-lane counterpart of :meth:`warm`: compile + run the
        fused decode+analyze graph for ``frame``'s (geometry, subsampling)
        at batch ``len(depths)`` on every routed placement (or an explicit
        chip list), so a coefficient-wire burst's first dispatch never pays
        XLA compilation inside a frame deadline.

        ``frame`` is a single :class:`entropy.CoefficientFrame`; its planes
        are replicated across the batch rows (pixel content is irrelevant
        to compilation -- only shapes, dtypes, and the subsampling layout
        key the jit cache). The decode+analyze closure itself comes from
        the same ``_coef_analyze_for`` memo live dispatches use, so the
        warmed compilation is exactly the one a live frame would hit."""
        r = self._router
        b = len(depths)
        probe = _Pending(frame, np.asarray(depths[0]),
                         np.asarray(intrinsics[0], np.float32),
                         float(scales[0]), model=model)
        analyze = self._coef_analyze_for(probe, model)

        def _rep(a):
            return np.repeat(np.asarray(a)[None], b, axis=0)

        arrays = (_rep(frame.y), _rep(frame.cb), _rep(frame.cr),
                  _rep(frame.qy), _rep(frame.qc),
                  np.asarray(depths),
                  np.asarray(intrinsics, np.float32),
                  np.asarray(scales, np.float32))
        if r is not None and r.mode == "sharded":
            placements: list[tuple[Any, Any]] = [(r.sharding, None)]
        else:
            placements = [(self._placement(chip), chip)
                          for chip in (range(self._n_windows)
                                       if chips is None else chips)]
        for device, key in placements:
            staged = pipeline_lib.stage_coef_batch(*arrays, device=device)
            jax.block_until_ready(analyze(*staged))
            with self._warm_lock:
                self.warmed.add((model, key, ("coef", b)))

    def _stage_group(self, group: list[_Pending], b: int):
        """Host-side staging: the padded [b, ...] batch arrays for a group.

        Returns ``(bufs, frames, depths, intr, scales)`` where ``bufs`` is
        the pooled buffer set to return after the dispatch completes (None
        for the b == 1 fast path, which returns zero-copy ``[None]`` views
        of the submitted arrays -- no stack, no pad, no copy). For b > 1
        the group's rows are filled into a pooled buffer; padding rows
        (replicas of frame 0) are written only when the bucket is not
        full -- a full bucket skips the pad work entirely."""
        n = len(group)
        first = group[0]
        if b == 1:
            return (None, first.frame_rgb[None], first.depth[None],
                    first.intrinsics[None],
                    np.asarray([first.depth_scale], np.float32))
        key = (b, first.frame_rgb.shape, first.frame_rgb.dtype.str,
               first.depth.dtype.str)
        bufs = self._pool_take(key, first)
        for i, p in enumerate(group):
            bufs.fill(i, p)
        bufs.pad(n)
        return bufs, bufs.frames, bufs.depths, bufs.intr, bufs.scales

    def _stage_coef_group(self, group: list[_Pending], b: int):
        """Coefficient-lane staging: the padded batch of quantized
        coefficient planes + quant tables + depth/geometry for one group.

        Returns ``(bufs, arrays)`` where ``arrays`` is the 8-tuple
        ``ops/pipeline.stage_coef_batch`` stages. The b == 1 fast path
        device_puts ``[None]`` views of the unpacked wire payload itself
        (for ``format = 2`` those are ``np.frombuffer`` views of the gRPC
        message buffer -- the wire bytes ARE the H2D source); b > 1 rides
        pooled 64-byte-aligned buffers like the pixel lane."""
        n = len(group)
        first = group[0]
        cf = first.frame_rgb
        if b == 1:
            return (None, (cf.y[None], cf.cb[None], cf.cr[None],
                           cf.qy[None], cf.qc[None], first.depth[None],
                           first.intrinsics[None],
                           np.asarray([first.depth_scale], np.float32)))
        key = (b, "coef", cf.subsampling, cf.height, cf.width,
               first.depth.shape, first.depth.dtype.str)
        bufs = self._pool_take(key, first)
        for i, p in enumerate(group):
            bufs.fill(i, p)
        bufs.pad(n)
        return bufs, (bufs.y, bufs.cb, bufs.cr, bufs.qy, bufs.qc,
                      bufs.depths, bufs.intr, bufs.scales)

    def _coef_analyze_for(self, p: _Pending, model: str) -> Callable:
        """The memoized decode+analyze graph for a coefficient-lane
        frame's (model, geometry, subsampling). Built lazily through the
        serving layer's ``coef_analyzer_factory`` on first dispatch (the
        capped-warmup contract: eagerly compiling every combination
        would explode startup)."""
        cf = p.frame_rgb
        key = (model, cf.height, cf.width, cf.subsampling)
        with self._coef_lock:
            factory = self._coef_factory
            analyze = self._coef_analyzers.get(key)
        if factory is None:
            raise ValueError(
                "coefficient-lane frame dispatched but no "
                "coef_analyzer_factory is bound (the serving engine "
                "wires ops/pipeline.make_coef_batch_analyzer here)"
            )
        if analyze is None:
            analyze = factory(model, cf.height, cf.width, cf.subsampling)
            with self._coef_lock:
                analyze = self._coef_analyzers.setdefault(key, analyze)
        return analyze

    def _launch_group(self, group: list[_Pending],
                      collected_ns: int | None = None) -> None:
        """Stage + H2D + async launch of one geometry group onto the routed
        chip, then hand the in-flight dispatch to the completer. Never
        blocks on the result."""
        if collected_ns is None:
            collected_ns = time.monotonic_ns()
        model = group[0].model
        # bounded in-flight window, per routed chip: dispatch N+1 on a chip
        # may not launch until one of THAT chip's slots frees (at most
        # max_inflight batches hold each chip's device memory). The pick is
        # least-loaded within the model's placed chips, so blocking here
        # means every chip this model may use has a full window.
        chip = self._pick_chip(model)
        slot = self._chip_slots[chip]
        while not slot.acquire(timeout=0.05):
            if self._stopped.is_set():
                self._fail_group(
                    group, RuntimeError("dispatcher stopped"), log_it=False
                )
                return
        # the flight-recorder timeline for this dispatch: the root opens
        # at the earliest member frame's submit, per-frame "submit" spans
        # cover queue + window wait and carry each frame's trace ID
        first_submit_ns = min(p.submit_ns for p in group)
        # mode snapshot: an online set_mode between launch and completion
        # must not misattribute this dispatch's outcome
        mode = self._router.mode if self._router is not None else "single"
        tl = recorder_lib.Timeline("dispatch", labels={
            "chip": str(chip),
            "mode": mode,
            "model": self._display_model(model),
        })
        root = tl.span("dispatch", start_ns=first_submit_ns)
        tl.span("collect", start_ns=first_submit_ns, end_ns=collected_ns,
                parent=root, frames=len(group))
        for p in group:
            tl.span(
                "submit", start_ns=p.submit_ns, end_ns=collected_ns,
                parent=root,
                trace_id=(p.trace_ctx.trace_id
                          if p.trace_ctx is not None else None),
            )
        bufs = None
        launched = False
        try:
            inject(fault_sites.SERVING_BATCH_DISPATCH)
            # per-chip fault site: RDP_FAULTS="serving.chip.1.dispatch:
            # exc:-1" (or the serving.chip.*.dispatch wildcard) kills or
            # slows exactly one chip's dispatches -- the quarantine and
            # failover drill, no code changes needed
            inject(fault_sites.chip_dispatch(chip))
            # per-model fault site: kills exactly one zoo model's
            # dispatches (groups are single-model, so another model's
            # frames can never ride -- and never fail -- this launch);
            # the multimodel-smoke cross-model-isolation drill
            inject(fault_sites.model_dispatch(self._display_model(model)))
            n = len(group)
            obs.BATCH_SIZE.observe(n)
            self.recent_batch += 0.25 * (n - self.recent_batch)
            b = self.bucket_for(n)
            tl.labels["bucket"] = str(b)
            # per-frame admission wait (submit -> collected): the host
            # split's "admit" column
            for p in group:
                obs.HOST_STAGE_SPLIT.labels(stage="admit").observe(
                    max(0, collected_ns - p.submit_ns) / 1e9)
            coef = isinstance(group[0].frame_rgb, entropy.CoefficientFrame)
            t0 = time.monotonic_ns()
            if coef:
                bufs, arrays = self._stage_coef_group(group, b)
                t_fill = time.monotonic_ns()
                staged = pipeline_lib.stage_coef_batch(
                    *arrays, device=self._placement(chip)
                )
                analyze = self._coef_analyze_for(group[0], model)
            else:
                bufs, frames, depths, intr, scales = self._stage_group(
                    group, b
                )
                t_fill = time.monotonic_ns()
                staged = pipeline_lib.stage_batch(
                    frames, depths, intr, scales,
                    device=self._placement(chip)
                )
                analyze = self._analyze_for(chip, model)
            t1 = time.monotonic_ns()
            # jit async dispatch: returns once the computation is enqueued
            # (an unwarmed (model, chip, bucket) pays its XLA compile
            # here -- the capped-warmup contract: lazy by default)
            out = analyze(*staged)
            t2 = time.monotonic_ns()
            warm_key = (model, None if mode == "sharded" else chip,
                        ("coef", b) if coef else b)
            with self._warm_lock:
                self.warmed.add(warm_key)
            tl.span("stage", start_ns=t0, end_ns=t1, parent=root)
            tl.span("launch", start_ns=t1, end_ns=t2, parent=root)
            obs.BATCH_STAGE_LATENCY.labels(stage="stage").observe(
                (t1 - t0) / 1e9)
            obs.BATCH_STAGE_LATENCY.labels(stage="launch").observe(
                (t2 - t1) / 1e9)
            # host/device split (bench_load --host-profile): pooled-buffer
            # fill vs the explicit device_put enqueue vs the async launch
            obs.HOST_STAGE_SPLIT.labels(stage="stage_host").observe(
                (t_fill - t0) / 1e9)
            obs.HOST_STAGE_SPLIT.labels(stage="h2d").observe(
                (t1 - t_fill) / 1e9)
            obs.HOST_STAGE_SPLIT.labels(stage="launch").observe(
                (t2 - t1) / 1e9)
            with self._inflight_lock:
                self._inflight_count += 1
                self.inflight_high_water = max(
                    self.inflight_high_water, self._inflight_count
                )
                self._chip_inflight[chip] += 1
                self.chip_inflight_high_water[chip] = max(
                    self.chip_inflight_high_water[chip],
                    self._chip_inflight[chip],
                )
                self.chip_dispatches[chip] += 1
                self.chip_frames[chip] += n
                obs.INFLIGHT_DISPATCHES.set(self._inflight_count)
                obs.CHIP_INFLIGHT.labels(chip=str(chip)).set(
                    self._chip_inflight[chip]
                )
            obs.CHIP_DISPATCHES.labels(chip=str(chip)).inc()
            obs.CHIP_FRAMES.labels(chip=str(chip)).inc(n)
            obs.MODEL_DISPATCHES.labels(
                model=self._display_model(model)).inc()
            self._cq.put(_Dispatch(group, out, bufs, slot, t2 / 1e9, chip,
                                   mode=mode, model=model, bucket=b,
                                   staged_t=t0 / 1e9,
                                   timeline=tl, root=root))
            launched = True
        except BaseException as exc:  # deliver, don't kill the collector
            # the failed dispatch's timeline is evidence: close it, mark
            # the error, record it (record() pins errored timelines)
            root.end()
            self._recorder.record(tl.fail(exc))
            self._dispatch_failed(group, chip, mode, exc)
            self._pool_put(bufs)
        finally:
            if not launched:
                slot.release()

    def _dispatch_failed(self, group: list[_Pending], chip: int, mode: str,
                         exc: BaseException) -> None:
        """A dispatch on ``chip`` failed (launch or completion): feed the
        chip's quarantine breaker and fail the frames over to healthy
        chips where possible -- a requeued frame rides the NEXT dispatch,
        which the quarantine-aware ``_pick_chip`` routes away from the
        failing chip once its breaker opens. Frames out of failover
        budget (or abandoned, or under a non-quarantining router) get the
        error, exactly the old behavior."""
        model = group[0].model if group else ""
        r = self._router
        if r is not None and mode == "round_robin":
            r.record_result(chip, ok=False, exc=exc,
                            model=self._display_model(model),
                            multi_model=bool(self._bindings))
        can_failover = (r is not None and r.quarantine_enabled
                        and mode == "round_robin"
                        and not self._stopped.is_set())
        if not can_failover:
            self._fail_group(group, exc)
            return
        retry, doomed = [], []
        budget = r.chips + 1
        if (self._bindings
                and r.failure_confined(chip, self._display_model(model))):
            budget = 1
        for p in group:
            if (p.done.is_set() or p.abandoned or p.failovers >= budget):
                doomed.append(p)
            else:
                p.failovers += 1
                retry.append(p)
        if retry:
            obs.CHIP_FAILOVER_FRAMES.inc(len(retry))
            log.warning(
                "failing %d frame(s) over from chip %d after %s: %s",
                len(retry), chip, type(exc).__name__, exc,
            )
            self._q.requeue(retry)
        if doomed:
            self._fail_group(doomed, exc)

    # -- completer side -----------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            d = self._cq.get()
            if d is None:
                return
            pop_ns = time.monotonic_ns()
            t_pop = pop_ns / 1e9
            t_ready = t_pop
            try:
                inject(fault_sites.SERVING_BATCH_COMPLETE)
                # drain the async device ride BEFORE the timed fetch:
                # np.asarray on a still-computing jax value would charge
                # the tail of device compute to the d2h split, burying
                # the actual transfer + fan-out cost it gates on
                jax.block_until_ready(d.out)
                t_ready = time.monotonic()
                # the ONE blocking host fetch, off the collector's critical
                # path: batch N+1 is already staging/computing while this
                # D2H + fan-out runs
                if isinstance(d.out, jax.Array):
                    # packed egress payload ([B, P] uint8, ops/pallas/
                    # pack.py layout): literally one fetch for the whole
                    # dispatch, landing in a pooled aligned staging
                    # buffer. Frames get zero-copy row views; the last
                    # PackedResult.release returns the buffer.
                    fetched = np.asarray(d.out)
                    staging = self._egress_take(fetched.shape)
                    np.copyto(staging, fetched)
                    share = _EgressStaging(staging, len(d.group),
                                           self._egress_put)
                    for i, p in enumerate(d.group):
                        if p.done.is_set() or p.abandoned:
                            # the waiter already gave up (deadline or
                            # watchdog): nobody will ever release this
                            # row's share, so release it on their behalf
                            share.release_one()
                            continue
                        p.result = egress_lib.PackedResult(
                            staging[i], release=share.release_one
                        )
                        p.done.set()
                else:
                    host = jax.tree.map(np.asarray, d.out)
                    for i, p in enumerate(d.group):
                        p.result = jax.tree.map(lambda a, _i=i: a[_i],
                                                host)
                        p.done.set()
                # one completed ride = one per-frame service-time sample
                # (staging through D2H), keyed per (model, bucket): what
                # the admission shed and the eviction margin consult --
                # and a cheap model's ride can no longer poison an
                # expensive model's estimate
                if d.staged_t > 0:
                    self.service_estimate.observe(
                        time.monotonic() - d.staged_t,
                        key=(d.model, d.bucket),
                    )
                with self._inflight_lock:
                    self._sheds_since_complete[d.model] = 0
                if self._router is not None and d.mode == "round_robin":
                    # a completed dispatch is the chip's success signal --
                    # and a quarantined chip's successful PROBE, which
                    # reinstates it
                    self._router.record_result(
                        d.chip, ok=True,
                        model=self._display_model(d.model),
                        multi_model=bool(self._bindings),
                    )
            except BaseException as exc:  # deliver, keep draining
                if d.timeline is not None:
                    d.timeline.fail(exc)
                self._dispatch_failed(d.group, d.chip, d.mode, exc)
            finally:
                done_ns = time.monotonic_ns()
                done_t = done_ns / 1e9
                if d.timeline is not None:
                    d.timeline.span("complete", start_ns=pop_ns,
                                    end_ns=done_ns, parent=d.root)
                    d.root.end(done_ns)
                    # record() pins the timeline when an error marked it
                    self._recorder.record(d.timeline)
                # overlap: how long this dispatch's predecessor was still
                # completing after this one had already launched. Serial
                # mode (max_inflight=1) launches only after the previous
                # completion, so this is identically 0 there.
                overlap = max(0.0, self._last_done_t - d.launch_t)
                self._last_done_t = done_t
                self.overlap_s_total += overlap
                obs.DISPATCH_OVERLAP.observe(overlap)
                obs.BATCH_STAGE_LATENCY.labels(stage="complete").observe(
                    done_t - t_pop
                )
                # host split: launch -> result-ready is the device-side
                # ride; ready -> done is the D2H fetch + fan-out the
                # completer pays on the host (the egress-gated number)
                obs.HOST_STAGE_SPLIT.labels(stage="device").observe(
                    max(0.0, t_ready - d.launch_t))
                obs.HOST_STAGE_SPLIT.labels(stage="d2h").observe(
                    max(0.0, done_t - t_ready))
                self._pool_put(d.bufs)
                with self._inflight_lock:
                    self._inflight_count = max(0, self._inflight_count - 1)
                    obs.INFLIGHT_DISPATCHES.set(self._inflight_count)
                    if d.chip < self._n_windows:
                        self._chip_inflight[d.chip] = max(
                            0, self._chip_inflight[d.chip] - 1
                        )
                        obs.CHIP_INFLIGHT.labels(chip=str(d.chip)).set(
                            self._chip_inflight[d.chip]
                        )
                d.slot.release()

    def _fail_group(self, group: list[_Pending], exc: BaseException,
                    log_it: bool = True) -> None:
        if log_it:
            log.exception(
                "batched dispatch failed (affected traces: %s)",
                ",".join(
                    p.trace_ctx.trace_id if p.trace_ctx is not None else "-"
                    for p in group
                ),
            )
        for p in group:
            if not p.done.is_set():
                p.error = exc
                p.done.set()
